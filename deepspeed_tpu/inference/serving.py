"""Continuous-batching serving loop (ref: deepspeed/inference/engine.py's
generate path and the DeepSpeed-FastGen / inference-v2 direction —
dynamic admission, paged KV, iteration-level scheduling).

TPU design.  The compiled programs are STATIC-shape and know nothing
about requests:

  prefill(params, [1, Tbucket] tokens, cache-view)   one admission
  decode (params, [B, 1] tokens, cache)              one token for ALL slots

The host-side :class:`ServingEngine` owns everything dynamic — a FIFO of
requests, a slot table (batch row ↔ request), the
:class:`~deepspeed_tpu.inference.kernels.PageAllocator` free list, and
per-slot sequence lengths.  Iteration-level scheduling as in FastGen:
each ``step()`` admits as many queued requests as slots+pages allow
(one bucketed prefill each), then runs ONE batched decode for every
active slot.  Completed sequences free their pages immediately; when the
pool runs dry, the youngest sequence is preempted vLLM-style (pages
released, request requeued for recompute-from-scratch).

Static-shape tricks worth noting:
- prompt lengths are padded to ``prefill_bucket`` multiples → bounded
  compile count; the padded tail's K/V lands beyond the row's seq_len
  and is never attended to (then overwritten as decode advances).
- inactive slots' table rows point at a reserved TRASH page: the decode
  step structurally writes a token for every row, and aiming dead rows
  at a sacrificial page keeps them from corrupting live sequences.
- the decode jit donates the cache, so pages update in place in HBM.

Automatic prefix caching (``prefix_cache=`` / the config block): the
page allocator is a refcounted, content-addressed pool — full pages are
keyed by a chained hash of their token span, incoming prompts map to
their longest cached page-aligned prefix, matched pages are shared
read-only into the new sequence's table (prefill starts at the first
uncached token, cutting TTFT), and released pages stay warm in an
eviction-ordered pool reclaimed only under allocation pressure.  Token-
identical with caching on or off; composes with split-fuse, chunked
decode, int8 weights, TP meshes, and the ZeRO-Inference streamed engine
(which shares this scheduler).

Host-sync discipline (the part that makes this a TPU serving loop and
not a CPU one): the decode inner loop performs exactly ONE device→host
transfer per step — the batched sampled tokens.  Sampling runs on-device
for all rows at once (per-row temperature, greedy = argmax), the page
table and seq_lens upload only when the slot composition changed
(dirty flags), and between composition changes the device-side
structural ``seq_lens + 1`` of the decode step is simply trusted.
Prefill-boundary tokens follow the same discipline: every prompt that
finishes prefilling within a step queues its last-position logits, and
ONE batched ``_sample_rows`` fetch appends them all — no per-slot
device round-trip on the admission path.

Speculative decoding (``speculative=`` / the config block): each decode
iteration drafts up to K cheap tokens per slot (prompt-lookup n-gram by
default, or a resident small-model drafter), scores all K+1 positions
in ONE batched continuation forward — the same multi-position program
split-fuse chunks run — keeps the longest accepted prefix plus a
bonus/corrected token, and rewinds each slot's KV frontier past the
rejected tail (the device's structural ``seq_lens + K+1`` is replaced
by the host's per-slot accepted length on the next dirty upload).
Greedy outputs are token-identical to speculation off; temperature>0
uses point-mass rejection sampling so the distribution is unchanged.
Composes with chunked decode, split-fuse, int8, TP meshes, the prefix
cache, and the ZeRO-Inference engine — where one verify sweep amortizes
one full layer-weight stream over the whole accepted span.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu import faults as faults_mod
from deepspeed_tpu.config import (CommConfig, DevprofConfig, FaultsConfig,
                                  HistoryConfig,
                                  IncidentsConfig, KVTierConfig,
                                  PrefixCacheConfig, SLOConfig,
                                  SpeculativeConfig, TelemetryConfig,
                                  TracingConfig)
from deepspeed_tpu.devprof import NULL_DEVPROF, DevProf
from deepspeed_tpu.faults import ChecksumError, FaultPlan, InjectedFault
from deepspeed_tpu.history import NULL_HISTORY, MetricHistory
from deepspeed_tpu.incidents import NULL_INCIDENTS, IncidentManager
from deepspeed_tpu.inference.kernels import (PagedKVCache, PageAllocator,
                                             resolve_serving_kernels)
from deepspeed_tpu.inference.prefix_cache import (extend_page_keys,
                                                  key_hex,
                                                  matchable_pages,
                                                  page_keys)
from deepspeed_tpu.inference.speculative import (build_drafter,
                                                 verify_accept)
from deepspeed_tpu.request_trace import (BoundTracer, RequestTracer,
                                          event_to_dict)
from deepspeed_tpu.slo import NULL_SLO_TRACKER, SLOTracker
from deepspeed_tpu.telemetry import (LATENCY_BUCKETS_S, MetricsRegistry,
                                     Span, TelemetryExporter)
from deepspeed_tpu.utils.logging import logger


@jax.jit
def _sample_rows(logits: jnp.ndarray, keys: jnp.ndarray,
                 temps: jnp.ndarray) -> jnp.ndarray:
    """Batched per-row sampling: [B, V] logits + [B] keys + [B] temps →
    [B] tokens.  temperature 0 rows take the argmax; others sample
    categorically at their temperature.  One jit, one result array — the
    serving loop fetches it with a single device→host transfer."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(temps == 0.0, greedy, sampled.astype(jnp.int32))


def _req_key(req_id: Any) -> str:
    """Canonical string form of a request id — the /requestz?id= query
    arrives as text, so matching happens in string space."""
    return str(req_id)


class EngineClosed(RuntimeError):
    """``submit`` after ``shutdown()``: the engine is torn down and can
    never serve this request.  Typed (rather than whatever downstream
    error the dead telemetry/scheduler state would eventually raise) so
    a fleet router's DEAD-replica path is deterministic — catch, mark
    the replica dead, re-route."""


@dataclasses.dataclass
class RequestShed:
    """Typed admission rejection: the engine declined to serve this
    request (queue-depth or deadline load shedding).  Lands in
    ``engine.finished`` IN PLACE of a token list — a router retries it
    on another replica; nothing about this request ran."""

    req_id: Any
    reason: str                        # "queue_depth" | "deadline"
    tier: Optional[str] = None


@dataclasses.dataclass
class RequestFailed:
    """Typed per-request failure: an exception in this request's slot
    (or its admission) failed THIS request — its pages, COW refs and
    tier pins were released, and the engine kept serving its
    neighbors.  Lands in ``engine.finished`` in place of a token list
    (before this existed, the exception took down the whole engine)."""

    req_id: Any
    reason: str          # "slot_exception" | "admit_exception" |
    #                      "replica_failed" (router: the whole replica
    #                      died mid-generation)
    error: str = ""
    tier: Optional[str] = None
    # tokens this request had generated when it failed: a router may
    # safely re-submit only when this is 0 — a request that already
    # emitted tokens must fail typed, never double-generate
    generated: int = 0


# a finished entry: the served tokens, or a typed shed/failure result
RequestResult = Union[List[int], RequestShed, RequestFailed]

# a shed inside this window marks /healthz degraded (shedding active)
_SHED_ACTIVE_WINDOW_S = 30.0


@dataclasses.dataclass
class Request:
    req_id: Any
    tokens: List[int]                  # prompt
    max_new_tokens: int = 32
    temperature: float = 0.0           # 0 → greedy
    # TTFT clock: submit-time perf_counter, cleared once the first token
    # is observed (preempted requeues carry the cleared state so a
    # recompute never double-counts).  None also means "telemetry off".
    t_submit: Optional[float] = None
    # cached chained page-key list (prefix caching): grown lazily, never
    # recomputed — tokens are immutable per incarnation, and a preempted
    # requeue hands its extended chain to the recompute request
    page_keys: Optional[List[bytes]] = None
    # flight-recorder state: the per-request sampling decision (made
    # once at submit) and the first-token edge (a preempted requeue
    # carries both so a recompute never re-emits first_token)
    traced: bool = False
    first_token_seen: bool = False
    # introspection/SLO state: wall-clock arrival (never cleared —
    # unlike t_submit — so /statusz ages and the SLO deadline survive
    # the first token AND a preemption requeue) and the SLO tier
    t_arrival: float = 0.0
    tier: Optional[str] = None


@dataclasses.dataclass
class _Promotion:
    """One admission's in-flight tier→HBM page promotion: the demoted
    keys being streamed back, their freshly allocated target pages, and
    the double-buffered reader driving the transfer.  ``primed`` holds
    group 0's presubmitted tier-read buffers (issued at admission so
    NVMe reads overlap whatever the engine does before the slot's first
    suffix-prefill chunk needs the pages); it stays None while the aio
    priority group asks KV promotion to yield to layer-weight streams.
    ``deferred`` counts the steps this slot's prefill stood aside so
    the promotion could hide under other slots' compute."""

    keys: List[bytes]
    page_map: Dict[bytes, int]         # key -> target HBM page
    reader: Any                        # param_stream.TierPageReader
    primed: Optional[list] = None
    t_start: float = 0.0
    deferred: int = 0
    channel: bool = False              # owns the NVMe read channel


# promotion deferral cap: how many scheduler iterations one slot's
# prefill may stand aside waiting for its tier reads (or for aio
# priority) before it blocks on the fence — bounds starvation when the
# promoting slot is the only work
_KV_PROMO_DEFER_CAP = 16


@dataclasses.dataclass
class _Slot:
    req: Request
    seq_len: int                       # tokens resident in the KV cache
    generated: List[int]
    rng: jax.Array
    seq_id: int = -1                   # PageAllocator owner key
    prefill_done: int = -1             # chunked prefill progress; -1 = done
    last_tok_t: float = 0.0            # inter-token latency clock
    promo: Optional[_Promotion] = None  # in-flight tier-page promotion

    @property
    def prefilling(self) -> bool:
        return 0 <= self.prefill_done < len(self.req.tokens)


class ServingEngine:
    """Host scheduler driving jitted prefill/decode over a paged cache.

    model_fns: ``(prefill_fn, decode_fn)`` with the
    :func:`~deepspeed_tpu.models.llama.forward_paged` contract
    ``(params, tokens, cache) -> (logits, cache)``; built automatically
    for llama via :func:`llama_serving_engine`.
    """

    def __init__(self, params, prefill_fn, decode_fn, *,
                 n_layers: int, n_kv: int, head_dim: int,
                 max_batch: int = 4, page_size: int = 16,
                 num_pages: int = 128, max_seq: int = 256,
                 prefill_bucket: int = 32, eos_token_id: Optional[int] = None,
                 cache_dtype=jnp.bfloat16, seed: int = 0,
                 decode_chunk: int = 1, prefill_chunk: int = 0,
                 chunk_prefill_fn=None, mesh=None, telemetry=None,
                 prefix_cache=None, admit_lookahead: int = 4,
                 tracing=None, speculative=None, drafter=None,
                 slo=None, kv_tier=None, faults=None,
                 shed_queue_depth: int = 0,
                 shed_expired_deadline: bool = False,
                 replica_id: Optional[str] = None,
                 history=None, incidents=None, kernels=None,
                 devprof=None, comm=None):
        # Sharded serving (ref: deepspeed/module_inject/replace_module.py
        # TP injection + deepspeed/moe/sharded_moe.py expert-parallel
        # inference): with a mesh, params arrive pre-sharded from the
        # builder, the KV cache's head axis shards over ``model`` (TP;
        # under expert-only parallelism it stays replicated), and every
        # host-built jit input is placed replicated on the mesh (a
        # device-0-committed array mixed with sharded arrays is an
        # error, not a resharding).
        active = mesh is not None and any(
            mesh.size(ax) > 1 for ax in ("model", "expert"))
        self._mesh = mesh
        if active:
            from jax.sharding import PartitionSpec as P

            self._repl = mesh.replicated()
            if mesh.size("model") > 1:
                if n_kv % mesh.size("model"):
                    raise ValueError(
                        f"n_kv_heads {n_kv} not divisible by model-axis "
                        f"size {mesh.size('model')}")
                self._kv_sharding = mesh.sharding(
                    P(None, "model", None, None, None))
            else:
                self._kv_sharding = self._repl
        else:
            self._repl = self._kv_sharding = None
        self.params = params
        self.decode_chunk = int(decode_chunk)
        if self.decode_chunk < 1:
            raise ValueError(
                f"decode_chunk must be >= 1, got {decode_chunk}")
        # FastGen split-fuse scheduling: prompts are absorbed
        # prefill_chunk tokens per iteration BETWEEN decode steps, so one
        # long admission never stalls every in-flight decode.  0 = whole
        # prompt in one bucketed prefill at admission (the classic path).
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk and chunk_prefill_fn is None:
            raise ValueError(
                "prefill_chunk > 0 needs chunk_prefill_fn — a "
                "(params, tokens, cache) step that attends over history + "
                "chunk (forward_paged(..., continuation=True))")
        self.eos = eos_token_id
        self.page_size = page_size
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.prefill_bucket = prefill_bucket
        self.max_pages_per_seq = -(-max_seq // page_size)

        # last page is the sacrificial target for inactive-slot writes
        self.trash_page = num_pages - 1
        # ---- automatic prefix caching: the allocator becomes a
        # refcounted content-addressed pool (full pages keyed by a
        # chained hash of their token span); matched prompts share
        # pages read-only and skip their prefill compute.  The pool cap
        # is the planner's accounting for pinned shared pages: warm
        # (refcount-0) cached pages may hold at most this many of the
        # usable pages — everything above it frees eagerly.
        pc = PrefixCacheConfig.coerce(prefix_cache)
        self.prefix_cache = pc
        self._pc_on = pc.enabled
        usable = num_pages - 1
        self.allocator = PageAllocator(
            usable, cache_pages=pc.pool_cap(usable),
            eviction=pc.eviction)
        if self._pc_on and chunk_prefill_fn is None:
            raise ValueError(
                "prefix_cache needs chunk_prefill_fn — cache-hit "
                "admissions prefill only the uncached suffix via the "
                "continuation forward (forward_paged(..., "
                "continuation=True))")
        # bounded admission lookahead (head-of-line blocking fix): when
        # the queue head cannot fit its pages, up to this many younger
        # requests are considered instead of stalling the whole queue
        self.admit_lookahead = int(admit_lookahead)
        if self.admit_lookahead < 0:
            raise ValueError(
                f"admit_lookahead must be >= 0, got {admit_lookahead}")
        # ---- speculative decoding: draft K cheap tokens per slot,
        # score all K+1 positions in ONE continuation forward, keep the
        # accepted prefix + a bonus token, rewind the KV frontier past
        # the rejects.  The verify pass IS the continuation-chunk
        # program, so it needs the same forward split-fuse does.  When
        # enabled, the speculative sweep replaces the chunked decode
        # scan (decode_chunk is accepted and unused — the sweep already
        # syncs once per up-to-(K+1) tokens).
        sc = SpeculativeConfig.coerce(speculative)
        self.speculative = sc
        self._spec_on = sc.enabled
        self.drafter = None
        if self._spec_on:
            if chunk_prefill_fn is None:
                raise ValueError(
                    "speculative decoding needs chunk_prefill_fn — the "
                    "verify pass scores K+1 positions per slot via the "
                    "continuation forward (forward_paged(..., "
                    "continuation=True)), which must return logits at "
                    "EVERY position")
            self.drafter = drafter if drafter is not None \
                else build_drafter(sc)

        def put_repl(x):
            x = jnp.asarray(x)
            return (jax.device_put(x, self._repl)
                    if self._repl is not None else x)

        self._put = put_repl
        # ---- serving-kernel policy: resolved ONCE, here, at build —
        # config block + env overrides collapse to a concrete choice
        # per dispatch site BEFORE any program traces (the old
        # DSTPU_FORCE_PAGED_PALLAS read inside the gate made a cached
        # trace depend on ambient env state).  Forced Pallas under a
        # sharded mesh demotes to xla VISIBLY: the reason lands in
        # policy.fallbacks, the serving_kernel_fallbacks counter, and
        # /statusz — never a silent False deep in the gate.
        self._interpret = jax.default_backend() != "tpu"
        self._kernels = resolve_serving_kernels(
            kernels, tp=active, interpret=self._interpret)
        if self._kernels.fused_sampling == "on":
            from deepspeed_tpu.ops.sampling_pallas import fused_sample_rows

            _itp = self._interpret
            self._sample_fn = (lambda lg, ky, tm:
                               fused_sample_rows(lg, ky, tm,
                                                 interpret=_itp))
        else:
            self._sample_fn = _sample_rows
        # ---- quantized weight placement (the training int8 wire
        # reused for serving, ISSUE 18): when comm.quantized_serving is
        # on, the BUILDER quantizes replica weights host-side so the
        # H2D upload carries int8 codes + scales, records placement
        # stats via _record_comm_placement, and this engine publishes
        # them (/statusz "comm" block + comm_* metric family).  The
        # engine itself only holds the coerced config — builders and
        # the ZeRO-Inference layer stream read it from here.
        self._comm = CommConfig.coerce(comm)
        self.comm_placement: Optional[Dict[str, Any]] = None
        # kv_tier coerced BEFORE the cache alloc below: the
        # quantized_resident mode changes the DEVICE cache's layout
        # (int8 code planes + f32 per-token-row scale planes), not just
        # the tier pool's host encoding
        kvt = KVTierConfig.coerce(kv_tier)
        self.kv_tier = kvt
        self._kvt_on = kvt.enabled
        self._quant_resident = kvt.enabled and kvt.quantized_resident
        if self._quant_resident and \
                self._kernels.paged_attention == "pallas_v1":
            raise ValueError(
                "kernels.paged_attention=pallas_v1 cannot serve "
                "int8-resident pages (kv_tier.quantized_resident) — "
                "there is no quantized v1 kernel; use xla or pallas_v2")
        self.cache = self._alloc_cache(n_layers, n_kv, num_pages,
                                       page_size, head_dim, cache_dtype)
        self._build_programs(prefill_fn, decode_fn, chunk_prefill_fn)
        self._table_host = np.full((max_batch, self.max_pages_per_seq),
                                   self.trash_page, np.int32)
        # dirty flags: device table/seq_lens re-upload only when the slot
        # composition changed since the last decode
        self._table_dirty = True
        self._lens_dirty = True
        self.slots: List[Optional[_Slot]] = [None] * max_batch
        # prefill-boundary sampling queue: (slot, logits row, key, temp)
        # collected per admission / final prefill chunk, flushed as ONE
        # batched _sample_rows fetch per step (no per-slot round-trip)
        self._pending_boundary: List[Tuple[int, Any, Any, float]] = []
        self.queue: "collections.deque[Request]" = collections.deque()
        self._seq_counter = 0
        self._rng = jax.random.PRNGKey(seed)
        self.finished: Dict[Any, List[int]] = {}
        self._newly_finished: List[Any] = []

        # ---- telemetry: one registry for every hot-path metric (the
        # old ad-hoc `stats` dict survives as a read-only shim below).
        # `telemetry` accepts None/bool/dict/TelemetryConfig — or an
        # existing MetricsRegistry to share one across engines.
        if isinstance(telemetry, MetricsRegistry):
            self.registry = telemetry
            tcfg = None                    # caller owns the sinks
        else:
            tcfg = TelemetryConfig.coerce(telemetry)
            self.registry = MetricsRegistry(enabled=tcfg.enabled)
        # _tel_on guards every perf_counter read in the decode loop: the
        # disabled path must cost nothing beyond this bool (no clock, no
        # lock, no TraceAnnotation)
        self._tel_on = self.registry.enabled
        r = self.registry
        self._c_admitted = r.counter(
            "serving_admitted_requests", "requests admitted to a slot")
        self._c_preempted = r.counter(
            "serving_preempted_requests",
            "vLLM-style recompute preemptions under page pressure")
        self._c_decode_steps = r.counter(
            "serving_decode_steps", "batched decode steps (tokens/slot)")
        self._c_decode_syncs = r.counter(
            "serving_decode_syncs", "device->host token syncs")
        self._c_prefill_chunks = r.counter(
            "serving_prefill_chunks", "split-fuse prompt chunks absorbed")
        self._g_queue = r.gauge(
            "serving_queue_depth", "requests waiting for a slot")
        self._g_occupancy = r.gauge(
            "serving_batch_occupancy",
            "fraction of decode slots active this step")
        self._g_kv_util = r.gauge(
            "serving_kv_page_utilization",
            "fraction of the usable KV page pool referenced by live "
            "sequences (warm cached pages count as reclaimable, not "
            "allocated)")
        self._c_admit_skips = r.counter(
            "serving_admit_skips",
            "queue entries skipped over by admission lookahead (head-"
            "of-line blocking avoided; each admission at queue index i "
            "adds i)")
        # prefix-cache metric family (all zero when the feature is off)
        self._c_pc_hits = r.counter(
            "prefix_cache_hits",
            "admissions that matched >= 1 cached page")
        self._c_pc_misses = r.counter(
            "prefix_cache_misses", "admissions with no cached prefix")
        self._c_pc_cached_tokens = r.counter(
            "prefix_cache_cached_tokens",
            "prompt tokens served from cached pages (prefill compute "
            "skipped entirely)")
        self._c_pc_prompt_tokens = r.counter(
            "prefix_cache_prompt_tokens",
            "prompt tokens admitted (hit + miss denominators)")
        self._c_pc_published = r.counter(
            "prefix_cache_published_pages",
            "full pages content-addressed into the index")
        self._c_pc_evicted = r.counter(
            "prefix_cache_evicted_pages",
            "cached pages reclaimed (allocation pressure or pool cap)")
        self._g_pc_pool = r.gauge(
            "prefix_cache_pool_pages",
            "refcount-0 cached pages held warm in the pool")
        self._g_pc_frac = r.gauge(
            "prefix_cache_cached_token_fraction",
            "cumulative cached / admitted prompt tokens")
        self._evicted_seen = 0
        self._c_boundary_syncs = r.counter(
            "serving_boundary_syncs",
            "batched prefill-boundary sampling syncs (one per step "
            "with >= 1 prefill completion — replaces one host "
            "round-trip per admitted slot)")
        # speculative-decoding metric family (all zero when off)
        self._c_spec_drafted = r.counter(
            "spec_drafted_tokens",
            "draft tokens proposed across verify sweeps")
        self._c_spec_accepted = r.counter(
            "spec_accepted_tokens", "draft tokens accepted by verify")
        self._c_spec_rejected = r.counter(
            "spec_rejected_tokens",
            "draft tokens rejected (KV frontier rolled back past them)")
        self._c_spec_sweeps = r.counter(
            "spec_verify_sweeps", "batched draft-and-verify sweeps")
        self._c_spec_slots = r.counter(
            "spec_verify_slots",
            "slot-sweeps verified (the denominator of the mean "
            "acceptance length)")
        self._c_spec_emitted = r.counter(
            "spec_emitted_tokens",
            "tokens emitted by verify sweeps (accepted + bonus, before "
            "EOS/budget truncation) — divide by spec_verify_slots for "
            "the mean acceptance length")
        self._h_spec_len = r.histogram(
            "spec_accept_length",
            "tokens emitted per slot per verify sweep (accepted prefix "
            "+ bonus; 1 = nothing accepted, a plain decode step)",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16))
        self._g_spec_occ = r.gauge(
            "spec_verify_occupancy",
            "fraction of decode slots active in the last verify sweep")
        self._h_ttft = r.histogram(
            "serving_ttft_seconds",
            "submit -> first generated token", LATENCY_BUCKETS_S)
        self._h_itl = r.histogram(
            "serving_inter_token_seconds",
            "gap between consecutive tokens of one request as a client "
            "sees them (chunked decode delivers bursts of K: K-1 "
            "near-zero gaps + one sync-interval gap per chunk)",
            LATENCY_BUCKETS_S)
        # span pieces hoisted out of step(): one histogram resolve and
        # one label format at build time, zero registry locks per step
        self._h_step_span = r.histogram(
            "serving_step_seconds",
            "scheduler iteration wall time (admit -> decode sync)")
        self._span_label = f"{r.namespace}/serving_step"
        # ---- time-series history + incidents (PR 15): both blocks
        # ride the exporter's tick-hook pass, so enabling either needs
        # an exporter even without Prometheus/HTTP sinks (a sink-less
        # exporter is just the shared timed pass — one monotonic read
        # per step).  Coerced here; constructed below once the tracer
        # and SLO tracker they observe exist.
        hcfg = HistoryConfig.coerce(history)
        icfg = IncidentsConfig.coerce(incidents)
        if hcfg.enabled and not self._tel_on:
            raise ValueError(
                "history needs the telemetry block — the rings sample "
                "the metrics registry; enable telemetry (or drop the "
                "history block)")
        if icfg.enabled and not (
                tracing.enabled
                if isinstance(tracing, (RequestTracer, BoundTracer))
                else TracingConfig.coerce(tracing).enabled):
            # validated BEFORE the exporter below: raising after it
            # would leak the bound HTTP port + server thread with no
            # handle left for the caller to shut down
            raise ValueError(
                "incidents needs the tracing block — the trigger "
                "events (slo_burn_alert, kv_promote_failed, replica "
                "failover, rollbacks) live in the flight recorder; "
                "enable tracing (or drop the incidents block)")
        dcfg = DevprofConfig.coerce(devprof)
        if dcfg.enabled and not self._tel_on:
            # validated BEFORE the exporter below, like incidents: the
            # sentinel counters, device-time attribution and MFU/MBU
            # gauges all live in the registry
            raise ValueError(
                "devprof needs the telemetry block — the compile "
                "sentinel, device-time and roofline surfaces are "
                "registry metrics; enable telemetry (or drop the "
                "devprof block)")
        self.history_cfg = hcfg
        self.incidents_cfg = icfg
        self.devprof_cfg = dcfg
        # telemetry sinks for serving loops: the exporter ticks from
        # step() (a monotonic compare until interval_s elapses)
        self._tel_exporter = None
        if tcfg is not None and self._tel_on and (
                tcfg.prometheus_path or tcfg.http_port is not None
                or hcfg.enabled or icfg.enabled):
            self._tel_exporter = TelemetryExporter(
                self.registry, prometheus_path=tcfg.prometheus_path,
                interval_s=tcfg.interval_s, http_port=tcfg.http_port)

        # ---- per-request tracing: every lifecycle edge lands in the
        # flight recorder (queued → admitted → prefill-chunk →
        # first-token → decode-batch → preempt/requeue → finish).
        # `tracing` accepts None/bool/dict/TracingConfig — or an
        # existing RequestTracer to share one recorder across engines.
        # _trace_on guards every emit site; the disabled tracer is the
        # shared no-op singleton (no clock, no lock, no ring).
        if isinstance(tracing, (RequestTracer, BoundTracer)):
            self.tracer = tracing
        else:
            self.tracer = RequestTracer.from_config(
                TracingConfig.coerce(tracing))
        # fleet replica identity: every trace event this engine emits
        # carries the replica id (the fleet's flight recorder is shared
        # across replicas — untagged events would be unattributable)
        self.replica_id = None if replica_id is None else str(replica_id)
        if self.replica_id is not None:
            self.tracer = self.tracer.bind(replica=self.replica_id)
        self._trace_on = self.tracer.enabled

        # ---- device-truth observability (see deepspeed_tpu.devprof):
        # sentinel wrappers around the compiled sweep programs count
        # and attribute every XLA compile (warmup vs steady-state),
        # sampled block_until_ready deltas attribute device time per
        # phase, and a one-time cost analysis of the programs feeds
        # live MFU/MBU gauges.  On-demand /profilez captures land
        # under the tracer's dump_dir.
        self.devprof = (
            DevProf(dcfg, registry=self.registry, tracer=self.tracer,
                    dump_dir=getattr(self.tracer, "dump_dir",
                                     "/tmp/dstpu_flight"))
            if dcfg.enabled else NULL_DEVPROF)
        self._devprof_on = self.devprof.enabled
        if self._devprof_on:
            self._prefill = self.devprof.wrap("prefill", self._prefill)
            self._chunk_prefill = self.devprof.wrap(
                "chunk_prefill", self._chunk_prefill)
            self._decode_chunk_fn = self.devprof.wrap(
                "decode_chunk", self._decode_chunk_fn)
            if dcfg.cost_analysis:
                self._devprof_cost_analyze()
            self._devprof_warmup()

        # rolling-update identity: which weight image this engine is
        # serving (swap_params bumps it; the fleet's per-version SLO
        # rollup groups replicas by it)
        self.weights_version: Any = 0

        # ---- tiered KV cache (ZeRO-Infinity tiering for the prefix
        # pool): published refcount-0 pages reclaimed under pressure
        # demote to a host pool (spilling onward to NVMe) instead of
        # dropping from the content index; tier hits re-admit through a
        # double-buffered promotion overlapped with the uncached
        # suffix's prefill chunks.  The allocator owns the index
        # states; the KVTierPool owns the payloads; this engine owns
        # the device<->host data movement.
        kvt = self.kv_tier        # coerced above, before the cache alloc
        self._kv_pool = None
        # cross-replica KV fabric (attach_fabric): export/admit ride
        # the spill pool, so the handle stays None unless kv_tier is on
        self._fabric = None
        # slot whose in-flight promotion owns the NVMe read channel
        # (host-resident promotions run concurrently and never claim it)
        self._promo_channel: Optional[int] = None
        self._kvt_wm_pages: Optional[int] = None
        if self._kvt_on:
            if not self._pc_on:
                raise ValueError(
                    "kv_tier needs the prefix_cache block — only "
                    "published refcount-0 prefix-cache pages demote; "
                    "without content addressing there is nothing to "
                    "spill or match")
            from deepspeed_tpu.inference.kv_tier import KVTierPool

            self._kv_pool = KVTierPool(
                kvt, page_shape=(n_layers, n_kv, page_size, head_dim),
                page_dtype=cache_dtype, registry=self.registry)
            self.allocator.spill = self._kv_pool
            self.allocator.demote_hook = self._demote_for_evict
            if kvt.demote_watermark < 1.0 and self.allocator.cache_pages:
                self._kvt_wm_pages = int(
                    kvt.demote_watermark * self.allocator.cache_pages)
            # compile the promote scatter + every pow2 demote-gather
            # bucket NOW (against the sacrificial trash page), off the
            # serving critical path — the first real demote/promote
            # must cost a DMA, not an XLA compile inside a request's
            # TTFT
            if self._quant_resident:
                zc = np.zeros((n_layers, n_kv, 1, page_size, head_dim),
                              np.int8)
                zs = np.ones((n_layers, n_kv, 1, page_size, 1),
                             np.float32)
                self._upload_promoted_q([self.trash_page], zc, zs,
                                        zc, zs)
            else:
                z = np.zeros((n_layers, n_kv, 1, page_size, head_dim),
                             np.dtype(cache_dtype))
                self._upload_promoted([self.trash_page], z, z)
            n = 1
            while True:
                if self._quant_resident:
                    self._fetch_pages_host_q([self.trash_page] * n)
                else:
                    self._fetch_pages_host([self.trash_page] * n)
                if n >= self.max_pages_per_seq:
                    break
                n *= 2
            # biggest prewarmed gather bucket: batched demotions chunk
            # their fetches to it so no sweep size compiles in-run
            self._kvt_fetch_cap = n
        self._c_kvt_demoted = r.counter(
            "kv_tier_demoted_pages",
            "warm pages captured to the host/NVMe tier instead of "
            "being dropped (re-demotes of still-spilled spans count: "
            "they kept a key matchable)")
        self._c_kvt_promoted = r.counter(
            "kv_tier_promoted_pages",
            "demoted pages streamed back into fresh HBM pages on a "
            "tier hit")
        self._c_kvt_deferrals = r.counter(
            "kv_tier_promote_deferrals",
            "scheduler iterations a promoting slot's prefill stood "
            "aside (promotion hiding under other slots' compute)")
        self._c_kvt_admit_waits = r.counter(
            "kv_tier_admit_waits",
            "admission ATTEMPTS held back because the tier hit needed "
            "the busy NVMe promotion channel (the admit loop may retry "
            "a waiting request several times per scheduler iteration, "
            "so this measures wait pressure, not distinct requests; "
            "waiting keeps the demoted span a DMA instead of "
            "re-prefilling it)")
        self._c_kvt_qres_promotes = r.counter(
            "kv_tier_quant_resident_promotes",
            "tier promotions published as int8-resident pages — the "
            "cold entry's codes+scales landed in HBM verbatim, the "
            "dequantize->scatter the dense path pays was skipped")
        self._g_kvt_inflight = r.gauge(
            "kv_tier_promoting_pages",
            "pages with a tier promotion in flight right now")
        # serving_kernel_dispatch counter family: one counter per
        # RESOLVED dispatch site (the suffix names the choice
        # resolve_serving_kernels baked at build), plus the visible
        # fallback count — together with /statusz "kernels" these make
        # the policy auditable at runtime, not just at build
        pk = self._kernels.paged_attention
        fs = ("fused" if self._kernels.fused_sampling == "on"
              else "xla")
        self._c_kdisp_paged = r.counter(
            f"serving_kernel_dispatch_paged_{pk}",
            "decode sweeps dispatched under the resolved "
            "paged-attention policy (auto = the per-shape gate inside "
            "the compiled forward)")
        self._c_kdisp_sample = r.counter(
            f"serving_kernel_dispatch_sample_{fs}",
            "batched sampling dispatches (decode-chunk syncs + "
            "prefill-boundary flushes) under the resolved sampler")
        self._c_kernel_fb = r.counter(
            "serving_kernel_fallbacks",
            "forced kernel choices the build demoted visibly (e.g. "
            "pallas under a sharded mesh falls back to xla — the "
            "reason is in /statusz kernels.fallbacks)")
        if self._kernels.fallbacks:
            self._c_kernel_fb.inc(len(self._kernels.fallbacks))
        self._h_kvt_promote = r.histogram(
            "kv_tier_promote_seconds",
            "admission-submit -> pages-landed latency of one "
            "promotion (all of its pages)")

        # ---- SLO & goodput accounting (the control-plane contract the
        # multi-replica router will route on): requests carry a tier,
        # are classified attained/violated at finish, and the tracker
        # keeps rolling attainment, multiwindow burn rates, and goodput
        # (attained-request tokens/s) live in the registry.  Burn-rate
        # trips fire structured slo_burn_alert events into the flight
        # recorder.  perf_counter clock: every timestamp the tracker
        # sees (shared `now` reads from the token path) is on it.
        self.slo_cfg = SLOConfig.coerce(slo)
        self.slo_tracker = (
            SLOTracker(self.slo_cfg, self.registry, tracer=self.tracer,
                       clock=time.perf_counter)
            if self.slo_cfg.enabled else NULL_SLO_TRACKER)
        self._slo_on = self.slo_tracker.enabled

        # ---- robustness: fault injection, load shedding, per-request
        # failure isolation, and the degraded-state accounting that
        # /healthz and /statusz surface.  A `faults` block builds a
        # deterministic FaultPlan and installs it process-wide for the
        # aio/tier hook points (the engine owns the install for its
        # lifetime; `shutdown` clears it).  Shedding: queue-depth sheds
        # reject at submit, deadline sheds drop queue entries whose SLO
        # deadline already expired — both produce typed RequestShed
        # results instead of letting doomed work consume the batch.
        self.shed_queue_depth = int(shed_queue_depth)
        if self.shed_queue_depth < 0:
            raise ValueError(
                f"shed_queue_depth must be >= 0 (0 = off), got "
                f"{shed_queue_depth}")
        self._shed_deadline = bool(shed_expired_deadline)
        if self._shed_deadline and not self._slo_on:
            raise ValueError(
                "shed_expired_deadline needs the slo block — deadlines "
                "are per-tier SLO objectives; without it there is "
                "nothing to shed against")
        if isinstance(faults, FaultPlan):
            fcfg = FaultsConfig(enabled=True)
            self._fault_plan: Optional[FaultPlan] = faults
        else:
            fcfg = FaultsConfig.coerce(faults)
            self._fault_plan = (FaultPlan.from_config(fcfg)
                                if fcfg.enabled else None)
        self.faults_cfg = fcfg
        self._owns_fault_plan = False
        if self._fault_plan is not None and \
                faults_mod.active_plan() is not self._fault_plan:
            faults_mod.install_fault_plan(self._fault_plan)
            self._owns_fault_plan = True
        self._c_shed = r.counter(
            "serving_shed_requests",
            "requests rejected at admission by load shedding "
            "(queue-depth or expired-deadline; typed RequestShed "
            "results, counted per SLO tier by the tracker)")
        self._c_failed = r.counter(
            "serving_failed_requests",
            "requests failed by a slot/admission exception and "
            "released in isolation (typed RequestFailed results; the "
            "engine kept serving)")
        self._c_kvt_checksum = r.counter(
            "kv_tier_checksum_failures",
            "promotions that hit a spilled-page checksum mismatch "
            "(entry dropped, span re-prefilled)")
        self._c_kvt_fb_events = r.counter(
            "kv_tier_fallback_events",
            "promotions abandoned after an unrecoverable tier "
            "read/checksum failure — the span fell back to re-prefill "
            "(correctness preserved, the DMA saving lost)")
        self._c_kvt_fb_pages = r.counter(
            "kv_tier_fallback_pages",
            "pages whose content was re-prefilled instead of promoted")
        # host-side ints mirror the counters so /statusz and the leak
        # checks work with telemetry disabled
        self._n_submitted = 0       # arrivals (queued + shed)
        self._n_shed = 0
        self._n_failed = 0
        self._shed_by_reason: Dict[str, int] = {"queue_depth": 0,
                                                "deadline": 0}
        self._last_shed_t: Optional[float] = None
        self._n_kvt_fallbacks = 0
        self._n_kvt_checksum = 0
        self._kvt_fault_streak = 0

        # ---- time-series history + incident capture (the black-box
        # flight recorder): rings over this registry sampled on the
        # exporter tick, an IncidentManager subscribed to the ring's
        # structured events plus EWMA detectors over key series.  Both
        # evaluate on the shared tick-hook pass — never the decode hot
        # path.  With no exporter (telemetry=MetricsRegistry, the
        # fleet-replica pattern) step() drives them inline.
        # (incidents-without-tracing already rejected above, before
        # the exporter existed to leak)
        self.history = (MetricHistory(hcfg, self.registry)
                        if hcfg.enabled else NULL_HISTORY)
        # subclasses adding their own default watch series (ZI's
        # prefetch-wait p95) must respect an operator's EXPLICIT
        # detect list — only a None (defaults in play) invites them
        self._detect_defaulted = icfg.enabled and icfg.detect is None
        if icfg.enabled:
            # None = engine defaults; an EXPLICIT empty detect list
            # disables the anomaly detectors (hard triggers only)
            detect = icfg.detect if icfg.detect is not None else (
                ("serving_ttft_seconds:p95",)
                + tuple(f"slo_{t}_goodput_tokens_per_s"
                        for t in self.slo_tracker.tiers))
            icfg = dataclasses.replace(icfg, detect=tuple(detect))
            self.incidents_cfg = icfg
            self.incident_mgr = IncidentManager(
                icfg, registry=self.registry, tracer=self.tracer,
                history=self.history if self.history.enabled else None,
                statusz_fn=self.statusz,
                source=self.replica_id or "engine")
        else:
            self.incident_mgr = NULL_INCIDENTS
        if self._devprof_on and self.incident_mgr.enabled:
            # a steady-state recompile is a contract violation: the
            # probe trips a bundle, and every bundle (whatever its
            # class) carries the compile ledger + capture references
            self.incident_mgr.add_probe(self.devprof.incident_probe)
            self.incident_mgr.add_attachment("devprof",
                                             self.devprof.bundle_info)
        # shared timed pass: SLO window refresh + history sampling +
        # incident evaluation ride ONE exporter tick-hook walk (the
        # register_tick_hook contract) instead of three per-step paths
        self._slo_tick_hooked = False
        self._tick_inline = (self._tel_exporter is None and
                             (self.history.enabled
                              or self.incident_mgr.enabled
                              or self._devprof_on))
        if self._tel_exporter is not None:
            ex = self._tel_exporter
            if self._slo_on:
                ex.register_tick_hook(
                    lambda now: self.slo_tracker.maybe_refresh(),
                    interval_s=1.0, name="slo_refresh")
                self._slo_tick_hooked = True
            if self.history.enabled:
                ex.register_tick_hook(
                    self.history.maybe_sample,
                    interval_s=hcfg.sample_interval_s,
                    name="history_sample")
            if self.incident_mgr.enabled:
                # after history: detectors judge THIS tick's sample
                ex.register_tick_hook(
                    self.incident_mgr.maybe_evaluate,
                    interval_s=icfg.eval_interval_s,
                    name="incident_evaluate")
            if self._devprof_on:
                # roofline gauges: flops/bytes counter deltas → MFU/MBU
                ex.register_tick_hook(
                    self.devprof.tick, interval_s=1.0,
                    name="devprof_roofline")

        # ---- introspection: /statusz (live engine snapshot),
        # /healthz (liveness/readiness, watchdog-fed), /requestz?id=
        # (one request's ring events), /historyz (metric-history rings
        # + incident ticker) ride the telemetry HTTP server
        self._t_start = time.perf_counter()
        self._last_step_t: Optional[float] = None
        self._watchdog = None
        self._closed = False
        if self._tel_exporter is not None:
            self._tel_exporter.register_provider("statusz", self.statusz)
            self._tel_exporter.register_provider("healthz", self.healthz)
            self._tel_exporter.register_provider("requestz",
                                                 self.requestz)
            if self.history.enabled or self.incident_mgr.enabled:
                self._tel_exporter.register_provider("historyz",
                                                     self.historyz)
            if self._devprof_on:
                self._tel_exporter.register_provider("profilez",
                                                     self.profilez)
            if self._trace_on:
                # /tracez?since= — incremental flight-recorder drain
                # for the remote scrape plane (obs_wire)
                from deepspeed_tpu.obs_wire import tracez_provider
                self._tel_exporter.register_provider(
                    "tracez", tracez_provider(
                        self.tracer.recorder, replica=self.replica_id))

    # (the `stats` deprecation shim from PR 2/PR 6 was removed on its
    # announced schedule — read `engine.registry.snapshot()` instead)

    # -------------------------------------------------- subclass hooks
    # (the ZeRO-Inference engine swaps both: per-layer cache tuples so
    # streamed block programs update one layer's pages in place, and
    # host-driven streamed executors in place of the whole-model jits)
    def _alloc_cache(self, n_layers, n_kv, num_pages, page_size,
                     head_dim, cache_dtype) -> PagedKVCache:
        def put_kv(x):
            return (jax.device_put(x, self._kv_sharding)
                    if self._kv_sharding is not None else x)

        table = self._put(jnp.full(
            (self.max_batch, self.max_pages_per_seq),
            self.trash_page, jnp.int32))
        seq_lens = self._put(jnp.zeros((self.max_batch,), jnp.int32))
        if self._quant_resident:
            # int8-resident pages: codes replace the dense planes
            # (~2x the pages per HBM byte at bf16, 4x at f32) and a
            # per-token-row f32 scale plane rides along.  Scales init
            # to ONE — the codec's convention for all-zero rows, so an
            # untouched page round-trips exactly.
            shape = (n_layers, n_kv, num_pages, page_size, head_dim)
            sshape = (n_layers, n_kv, num_pages, page_size, 1)
            return PagedKVCache(
                k=put_kv(jnp.zeros(shape, jnp.int8)),
                v=put_kv(jnp.zeros(shape, jnp.int8)),
                table=table, seq_lens=seq_lens, page_size=page_size,
                k_scale=put_kv(jnp.ones(sshape, jnp.float32)),
                v_scale=put_kv(jnp.ones(sshape, jnp.float32)))
        return PagedKVCache(
            k=put_kv(jnp.zeros(
                (n_layers, n_kv, num_pages, page_size, head_dim),
                cache_dtype)),
            v=put_kv(jnp.zeros(
                (n_layers, n_kv, num_pages, page_size, head_dim),
                cache_dtype)),
            table=table, seq_lens=seq_lens,
            page_size=page_size)

    def _build_programs(self, prefill_fn, decode_fn,
                        chunk_prefill_fn) -> None:
        """Install ``self._prefill`` / ``self._chunk_prefill`` /
        ``self._decode_chunk_fn`` — any callables honoring the jitted
        contracts; the base engine compiles whole-model programs."""
        self._prefill = jax.jit(prefill_fn, donate_argnums=(2,))
        self._chunk_prefill = (jax.jit(chunk_prefill_fn,
                                       donate_argnums=(2,))
                               if chunk_prefill_fn is not None else None)

        # K decode steps in ONE on-device scan: each step's sampled token
        # feeds the next, so the host syncs once per K tokens.  On a
        # high-latency link (this container's tunnel: ~90ms RTT per sync,
        # SERVING_BENCH.json ms_per_decode_step 97.6 unchunked vs 17.4 at
        # K=8) this is the difference between latency-bound and
        # compute-bound serving.  Tokens a request emits after its own
        # EOS within a chunk are discarded by the host (waste < K).
        # K=1 runs the same path as a length-1 scan.
        # The sampler is the policy-resolved one (fused pallas argmax
        # when kernels.fused_sampling resolved "on", the jitted XLA
        # twin otherwise) — both emit bit-identical greedy tokens and
        # share the categorical math, so flipping the policy can never
        # change a served greedy stream.
        sample = self._sample_fn

        def chunk_fn(params, tok, cache, keys, temps):
            def one(carry, key_k):
                t, c = carry
                logits, c = decode_fn(params, t, c)
                nxt = sample(logits[:, -1], key_k, temps)
                return (nxt[:, None], c), nxt

            (_, cache), toks = jax.lax.scan(one, (tok, cache), keys)
            return jnp.swapaxes(toks, 0, 1), cache          # [B, K]

        self._decode_chunk_fn = jax.jit(chunk_fn, donate_argnums=(2,))

    def _devprof_cost_analyze(self) -> None:
        """Build-time roofline pass (devprof.cost_analysis): lower the
        compiled sweep programs once at their steady shapes and record
        the compiler's flops/bytes estimates as per-dispatch costs.
        Abstract (ShapeDtypeStruct) args — no device work, and the AOT
        lower/compile path never touches the jit dispatch caches the
        sentinel watches.  Best-effort per program: a backend without
        ``cost_analysis`` just leaves that site uncosted."""
        dp = self.devprof

        def absx(x):
            return (jax.ShapeDtypeStruct(x.shape, x.dtype)
                    if hasattr(x, "shape") and hasattr(x, "dtype")
                    else x)

        tm = jax.tree_util.tree_map
        try:
            params_a = tm(absx, self.params)
            cache_a = tm(absx, self.cache)
            K = self.decode_chunk
            keys = jax.random.split(
                jax.random.PRNGKey(0), K * self.max_batch).reshape(
                    K, self.max_batch, -1)
            dp.cost_analyze(
                "decode_chunk", self._decode_chunk_fn, params_a,
                jax.ShapeDtypeStruct((self.max_batch, 1), jnp.int32),
                cache_a, absx(keys),
                jax.ShapeDtypeStruct((self.max_batch,), jnp.float32))
            # whole-prompt prefill at the base bucket (the view a
            # bucket-padded admission hands the program)
            view_a = tm(absx, self.cache._replace(
                table=jnp.zeros((1, self.max_pages_per_seq), jnp.int32),
                seq_lens=jnp.zeros((1,), jnp.int32)))
            dp.cost_analyze(
                "prefill", self._prefill, params_a,
                jax.ShapeDtypeStruct((1, self.prefill_bucket),
                                     jnp.int32), view_a)
            if self._spec_on and self._chunk_prefill is not None:
                # under speculation the continuation forward IS the
                # steady-state decode program — cost it at the verify
                # sweep's shape
                Kd = self.speculative.draft_tokens
                dp.cost_analyze(
                    "chunk_prefill", self._chunk_prefill, params_a,
                    jax.ShapeDtypeStruct((self.max_batch, Kd + 1),
                                         jnp.int32), cache_a)
        except Exception:
            # roofline accounting is observability, never a build
            # failure — uncosted sites simply contribute 0 to MFU/MBU
            logger.exception("devprof: build-time cost analysis")

    def _devprof_warmup(self) -> None:
        """Devprof build-time precompile: dispatch every sweep program
        once per steady shape so the jit caches are fully populated
        before the first request.  The zero-steady-recompile contract
        ("a compile after the first token is a shape-drift bug") is
        only honest if the shape set is CLOSED at build — without
        this, the decode chunk's first compile and chunk-prefill's
        lazily-reached power-of-two table buckets would land after the
        first token and read as violations.  Every warmup write goes
        to the trash page (all table rows are trash at build) so
        serving state is untouched; the dispatches run through the
        sentinel wrappers and are counted — and attributed — as
        warmup compiles.  Side benefit: the first real request pays
        zero compilation (production TPU serving does exactly this —
        precompile the bucket set at startup)."""
        zi = jnp.zeros
        n0 = time.perf_counter()
        row = self.max_pages_per_seq * self.page_size
        if self.prefill_bucket:
            # cold full prefill pads the prompt to prefill_bucket
            # MULTIPLES clamped at the table row — enumerate them all
            bkt = self.prefill_bucket
            ends = sorted({min(i * bkt, row)
                           for i in range(1, -(-row // bkt) + 1)})
            for end in ends:
                view = PagedKVCache(
                    k=self.cache.k, v=self.cache.v,
                    table=self._put(self._table_host[0:1]),
                    seq_lens=self._put(zi((1,), jnp.int32)),
                    page_size=self.page_size)
                _, view = self._prefill(
                    self.params, self._put(zi((1, end), jnp.int32)),
                    view)
                self.cache = self.cache._replace(k=view.k, v=view.v)
        if self._chunk_prefill is not None:
            # the continuation forward's page-table width is bucketed
            # to powers of two clamped at the full row — enumerate the
            # same closed set _advance_prefill draws from
            C = self.prefill_chunk or self.prefill_bucket
            widths, w = [], 1
            while w < self.max_pages_per_seq:
                widths.append(w)
                w *= 2
            widths.append(self.max_pages_per_seq)
            for w in widths:
                view = PagedKVCache(
                    k=self.cache.k, v=self.cache.v,
                    table=self._put(self._table_host[0:1, :w]),
                    seq_lens=self._put(zi((1,), jnp.int32)),
                    page_size=self.page_size)
                _, view = self._chunk_prefill(
                    self.params, self._put(zi((1, C), jnp.int32)),
                    view)
                self.cache = self.cache._replace(k=view.k, v=view.v)
        # whole-cache dispatches (spec verify, decode) see the
        # page_size leaf as the weak-i32 scalar a previous jit RETURN
        # left in the cache, not the python int the constructor put
        # there — normalize first, or the warmup would compile the
        # int-leaf twin of each program and the first real dispatch
        # would still compile (and read as a steady "recompile")
        self.cache = self.cache._replace(
            page_size=jnp.asarray(self.page_size))
        if self._spec_on and self._chunk_prefill is not None:
            # the verify sweep's whole-cache continuation shape
            Kd = self.speculative.draft_tokens
            _, self.cache = self._chunk_prefill(
                self.params,
                self._put(zi((self.max_batch, Kd + 1), jnp.int32)),
                self.cache)
        K = self.decode_chunk
        keys = jax.random.split(
            jax.random.PRNGKey(0), K * self.max_batch).reshape(
                K, self.max_batch, -1)
        out, self.cache = self._decode_chunk_fn(
            self.params,
            self._put(zi((self.max_batch, 1), jnp.int32)),
            self.cache, self._put(keys),
            self._put(zi((self.max_batch,), jnp.float32)))
        del out
        logger.info("devprof warmup: %d programs precompiled in %.1fs",
                    self.devprof.ledger.warmup,
                    time.perf_counter() - n0)

    # ------------------------------------------------------------- requests
    def submit(self, req_id, tokens, max_new_tokens: int = 32,
               temperature: float = 0.0,
               tier: Optional[str] = None,
               arrival: Optional[float] = None) -> Optional[RequestShed]:
        """Queue a request.  ``tier`` names an SLO tier from the
        ``slo`` config block (None → the block's default tier); naming
        a tier with the block disabled raises rather than silently
        dropping the latency objective.  ``arrival`` carries an
        earlier ``perf_counter`` arrival time through a router's
        failover re-submit, so SLO deadlines and TTFT judge the user's
        real clock, not the re-route.

        Returns None when queued.  With ``shed_queue_depth`` set and
        the queue at capacity, the request is NOT queued: a typed
        :class:`RequestShed` is recorded in ``finished`` and returned
        (load shedding is a first-class outcome a router retries
        elsewhere, never an exception).  Raises :class:`EngineClosed`
        after :meth:`shutdown` — a dead engine must reject
        deterministically, not fail downstream."""
        if self._closed:
            raise EngineClosed(
                f"request {req_id!r} submitted after shutdown"
                + (f" (replica {self.replica_id})"
                   if self.replica_id else ""))
        tokens = list(map(int, tokens))
        if not tokens:
            raise ValueError(f"request {req_id}: empty prompt")
        if len(tokens) + max_new_tokens > self.max_seq:
            raise ValueError(
                f"request {req_id}: prompt {len(tokens)} + "
                f"{max_new_tokens} new > max_seq {self.max_seq}")
        lifetime_pages = self._pages_needed(len(tokens) + max_new_tokens)
        usable = self.trash_page  # pool size minus the reserved page
        if lifetime_pages > usable:
            raise ValueError(
                f"request {req_id}: needs {lifetime_pages} pages at full "
                f"length but the pool has {usable} — it could never "
                "complete even alone")
        self._n_submitted += 1
        if self.shed_queue_depth and \
                len(self.queue) >= self.shed_queue_depth:
            return self._shed(req_id, tier, "queue_depth")
        traced = self._trace_on and self.tracer.sampled(req_id)
        now = time.perf_counter() if arrival is None else float(arrival)
        if self._slo_on or tier is not None:
            # BEFORE the queue append: an unknown tier must reject the
            # request, not classify it later under a KeyError
            self.slo_tracker.on_submit(req_id, tier, now=now)
        self.queue.append(Request(
            req_id, tokens, max_new_tokens, temperature,
            t_submit=now if self._tel_on else None,
            traced=traced, t_arrival=now, tier=tier))
        self._g_queue.set(len(self.queue))
        if traced:
            self.tracer.event("queued", req_id, attrs={
                "prompt_tokens": len(tokens),
                "max_new_tokens": max_new_tokens,
                "queue_depth": len(self.queue)})

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    # ------------------------------------- robustness: shed / fail / leaks
    def _shed(self, req_id, tier: Optional[str],
              reason: str) -> RequestShed:
        """Record a typed admission rejection: per-tier SLO shed
        accounting, telemetry, trace event, and the degraded-state
        clock /healthz reads.  Nothing about the request ran — there
        is nothing to release."""
        # validates the tier name exactly like on_submit would (an
        # unknown tier is a caller bug even when the answer is "no")
        self.slo_tracker.on_shed(req_id, tier)
        res = RequestShed(req_id, reason, tier)
        self.finished[req_id] = res
        self._c_shed.inc()
        self._n_shed += 1
        self._shed_by_reason[reason] = \
            self._shed_by_reason.get(reason, 0) + 1
        self._last_shed_t = time.perf_counter()
        if self._trace_on:
            self.tracer.event("request_shed", req_id, attrs={
                "reason": reason, "tier": tier,
                "queue_depth": len(self.queue)})
        self._g_queue.set(len(self.queue))
        return res

    def _shed_expired(self) -> None:
        """Deadline shedding at admission: drop queued requests whose
        SLO deadline has already expired — serving them would burn a
        slot on work no client is waiting for.  Runs once per step
        before admission."""
        now = time.perf_counter()
        kept: List[Request] = []
        shed = False
        for r in self.queue:
            obj = self.slo_cfg.tiers.get(
                r.tier or self.slo_cfg.default_tier)
            dl = obj.deadline_s if obj is not None else None
            if dl is not None and now - r.t_arrival > dl:
                self._shed(r.req_id, r.tier, "deadline")
                self._newly_finished.append(r.req_id)
                shed = True
            else:
                kept.append(r)
        if shed:
            self.queue = collections.deque(kept)

    def _record_failure(self, req: Request, reason: str,
                        exc: BaseException, b: int = -1,
                        generated: int = 0) -> None:
        """ONE failure ledger for both the slot and admission paths:
        the chaos soak reconciles typed results, telemetry counters,
        per-tier SLO lifetimes and trace events against each other, so
        the bookkeeping must never fork."""
        self._c_failed.inc()
        self._n_failed += 1
        self.slo_tracker.on_fail(req.req_id)
        self.finished[req.req_id] = RequestFailed(
            req.req_id, reason, repr(exc), req.tier,
            generated=generated)
        self._newly_finished.append(req.req_id)
        if self._trace_on:
            # always emitted (not sampling-gated): a failure is exactly
            # what the flight recorder exists to explain
            self.tracer.event("request_failed", req.req_id, b, attrs={
                "error": repr(exc)[:200], "reason": reason,
                "generated": generated})

    def _fail_slot(self, b: int, exc: BaseException) -> None:
        """Per-request failure isolation: an exception in slot ``b``'s
        host-side work fails THAT request — its promotion is fenced
        and cancelled, its pages/COW refs released, its pending
        boundary sample dropped — and the engine keeps serving the
        other slots.  The request finishes as a typed
        :class:`RequestFailed` (before this, the exception killed the
        whole engine)."""
        s = self.slots[b]
        req = s.req
        logger.warning(
            "serving: request %r failed in slot %d (%s) — releasing "
            "and continuing", req.req_id, b, exc)
        if s.promo is not None:
            try:
                self._cancel_promotion(s)
            except Exception:
                logger.exception(
                    "serving: promotion cancel during slot failure")
        self.allocator.release(s.seq_id)
        self._table_host[b, :] = self.trash_page
        self._table_dirty = self._lens_dirty = True
        self.slots[b] = None
        # a queued boundary sample for this slot would append a token
        # to a dead request (or index a vacated slot) at the flush
        self._pending_boundary = [p for p in self._pending_boundary
                                  if p[0] != b]
        self._record_failure(req, "slot_exception", exc, b=b,
                             generated=len(s.generated))

    def check_leaks(self) -> List[str]:
        """Page-accounting invariants; returns violations (empty =
        clean).  Reused by the chaos soak and the fault tests after
        every scenario: each page must sit in exactly one of
        {free list, warm pool, live-owned, parked}, refcounts must
        match ownership multiplicity, and an idle engine must own
        nothing."""
        al = self.allocator
        probs: List[str] = []
        usable = self.trash_page
        owned_flat = [p for pages in al.owned.values() for p in pages]
        live = set(owned_flat)
        cnt = collections.Counter(al.free)
        cnt.update(al.pool.keys())     # keys() — a dict would be read
        cnt.update(live)               # as a counts mapping
        cnt.update(al._parked)
        missing = [p for p in range(usable) if cnt[p] != 1]
        if missing:
            probs.append(
                f"pages not in exactly one of free/warm/live/parked: "
                f"{missing[:16]}")
        for p, n in al.refs.items():
            owners = sum(1 for pages in al.owned.values()
                         if p in pages)
            if n != owners:
                probs.append(
                    f"page {p}: refcount {n} != {owners} owners")
        for p in al.promoting:
            if p not in al.refs and p not in al._parked:
                probs.append(
                    f"page {p}: promoting but neither owned nor parked")
        idle = not any(s is not None for s in self.slots) \
            and not self.queue
        if idle:
            if al.owned:
                probs.append(f"idle engine owns pages: {dict(al.owned)}")
            if al.promoting:
                probs.append(
                    f"idle engine has promotions in flight: "
                    f"{dict(al.promoting)}")
            if al._parked:
                probs.append(f"idle engine has parked pages: "
                             f"{al._parked}")
            if self._kv_pool is not None and self._kv_pool._pinned:
                probs.append(
                    f"idle engine holds tier pins: "
                    f"{list(self._kv_pool._pinned)}")
        return probs

    # ------------------------------------------- fleet handoff hooks
    # (consumed by deepspeed_tpu.fleet.FleetRouter: drain re-routes a
    # replica's queued work, failover salvages a dead replica's whole
    # request set; both are pure host bookkeeping — no device work, so
    # they stay callable on an engine whose compute path is wedged)
    def take_queued(self) -> List[Request]:
        """Pop and return every queued (not-yet-admitted) request —
        the drain/failover queue handoff.  Each request's SLO record
        is forgotten here (the destination replica re-announces it;
        carry ``t_arrival`` through ``submit(arrival=)`` so the user's
        clock survives the hop)."""
        taken, self.queue = list(self.queue), collections.deque()
        for r in taken:
            self.slo_tracker.forget(r.req_id)
        self._g_queue.set(0)
        if taken and self._trace_on:
            self.tracer.event("queue_handoff",
                              attrs={"requests": len(taken)})
        return taken

    def abandon_inflight(self) -> List[Tuple[Request, int]]:
        """Release every active slot WITHOUT finishing its request:
        promotions fenced and cancelled, pages/COW refs freed, pending
        boundary samples dropped, SLO records forgotten.  Returns
        ``[(request, tokens_generated)]`` so a router can decide per
        request: zero tokens → safe to re-submit elsewhere; any tokens
        → must fail typed (re-running would double-generate).  The
        failover half of the fleet handoff; leaves ``check_leaks``
        clean on this engine."""
        out: List[Tuple[Request, int]] = []
        for b, s in enumerate(self.slots):
            if s is None:
                continue
            if s.promo is not None:
                try:
                    self._cancel_promotion(s)
                except Exception:
                    logger.exception(
                        "serving: promotion cancel during abandon")
            self.allocator.release(s.seq_id)
            self._table_host[b, :] = self.trash_page
            self.slots[b] = None
            self.slo_tracker.forget(s.req.req_id)
            if self._trace_on:
                self.tracer.event("abandoned", s.req.req_id, b, attrs={
                    "generated": len(s.generated)})
            out.append((s.req, len(s.generated)))
        if out:
            self._table_dirty = self._lens_dirty = True
            self._pending_boundary = []
        return out

    def warm_keys(self) -> frozenset:
        """The replica's published-key digest: every content key
        matchable at admission — the HBM prefix-cache index plus (when
        the tier is live) the spilled host/NVMe entries.  The fleet
        router diffs these digests to answer "which replica has this
        prompt warm" without touching any page payloads."""
        return frozenset(self.warm_digest())

    def warm_digest(self) -> Dict[bytes, str]:
        """:meth:`warm_keys` with tier locations: content key →
        ``"hbm"`` / ``"host"`` / ``"nvme"``.  The fleet router's
        cost-aware affinity prefers an HBM-warm replica over an
        NVMe-warm one when warm-prefix lengths tie — a promotion from
        NVMe is a DMA plus an aio read, not a dict lookup.  A span
        resident in both HBM and the spill (a promoted page whose
        spill copy was kept as a free re-demote) reports HBM."""
        d = {k: "hbm" for k in self.allocator.index}
        pool = self._kv_pool
        if pool is not None and pool.disabled is None:
            for k, e in pool.entries.items():
                d.setdefault(k, e.location)
        return d

    # ------------------------------------------------ KV fabric verbs
    # (consumed by deepspeed_tpu.fleet.FleetRouter's migration and
    # prefill→decode handoff paths; both are host bookkeeping + one
    # batched device→host gather on the export side)
    def attach_fabric(self, fabric) -> None:
        """Join a :class:`~deepspeed_tpu.kv_fabric.KVFabric`: this
        replica may then export page chains into it and admit chains
        other replicas computed.  Requires the ``kv_tier`` block —
        admitted entries land in the local spill pool so the existing
        tier-hit admission path (``begin_promotion`` + TierPageReader,
        checksum-verified, re-prefill fallback) serves them."""
        if fabric is not None and not self._kvt_on:
            raise ValueError(
                "attach_fabric needs the kv_tier block — the local "
                "spill pool is the admission side of the transport "
                "(migrated chains land there and re-admit through the "
                "tier promotion path)")
        self._fabric = fabric

    def export_pages(self, keys: List[bytes], fabric=None) -> int:
        """Export the longest contiguous prefix of ``keys`` this
        replica holds (HBM published pages batch-fetch device→host and
        encode; spilled tier entries ride as-is, int8 cold pages
        included) into the fabric.  Returns the number of leading keys
        now covered by the fabric; an export failure mid-chain stops
        there — the published prefix is still chain-valid, and the
        uncovered tail re-prefills on the importer."""
        from deepspeed_tpu.inference.kv_tier import encode_entry

        fab = fabric if fabric is not None else self._fabric
        if fab is None or not self._kvt_on:
            raise ValueError(
                "export_pages needs an attached fabric and the "
                "kv_tier block")
        plan: List[Tuple[bytes, str, Optional[int]]] = []
        for k in keys:
            if fab.has(k):
                plan.append((k, "fab", None))
            elif k in self.allocator.index:
                plan.append((k, "hbm", self.allocator.index[k]))
            elif self._kv_pool.has(k):
                plan.append((k, "tier", None))
            else:
                break
        hbm = [(k, p) for k, kind, p in plan if kind == "hbm"]
        payload: Dict[bytes, tuple] = {}
        # one batched gather per prewarmed-bucket chunk, not one
        # device read per page — same discipline as the demote sweep
        cap = self._kvt_fetch_cap
        for i in range(0, len(hbm), cap):
            chunk = hbm[i:i + cap]
            kh, vh = self._fetch_pages_host([p for _, p in chunk])
            for j, (kk, _p) in enumerate(chunk):
                payload[kk] = (kh[:, :, j], vh[:, :, j])
        n = 0
        nbytes = 0
        for k, kind, _p in plan:
            try:
                if kind == "hbm":
                    e = encode_entry(
                        k, *payload[k],
                        quantize=self.kv_tier.quantize_cold,
                        page_dtype=self._kv_pool.page_dtype)
                    fab.publish(k, e)
                    nbytes += e.nbytes
                elif kind == "tier":
                    e = self._kv_pool.entry_payload(k)
                    fab.publish(k, e)
                    nbytes += e.nbytes
            except (IOError, OSError) as exc:
                # injected export failure or an unreadable spill file:
                # the chain stops here, the rest re-prefills remotely
                logger.warning(
                    "serving: fabric export stopped at page %d/%d "
                    "(%s)", n, len(plan), exc)
                break
            n += 1
        if n and self._trace_on:
            self.tracer.event("kv_export", attrs={
                "pages": n, "bytes": nbytes})
        return n

    def admit_fabric(self, keys: List[bytes],
                     deadline: Optional[float] = None) -> int:
        """Fetch the longest contiguous prefix of ``keys`` out of the
        fabric into the LOCAL spill pool, so the next admission's
        chained walk treats the span as tier hits and promotes it
        through the existing checksum-verified path.  ``deadline``
        (perf_counter): stop fetching once past it — a migration that
        blows its budget admits the partial prefix it has (still
        chain-valid) and the rest re-prefills.  Returns the leading
        keys now locally matchable."""
        fab = self._fabric
        if fab is None or not self._kvt_on:
            raise ValueError(
                "admit_fabric needs an attached fabric and the "
                "kv_tier block")
        n = 0
        for k in keys:
            if k in self.allocator.index or self._kv_pool.has(k):
                n += 1              # already warm here — free
                continue
            if deadline is not None and \
                    time.perf_counter() > deadline:
                break
            if not fab.has(k):
                break
            try:
                entry = fab.fetch(k)
            except (KeyError, IOError, OSError):
                break               # evicted or injected fetch failure
            if self._kv_pool.admit_entry(entry) is None:
                break               # pool can't hold it (or disabled)
            n += 1
        if n and self._trace_on:
            self.tracer.event("fabric_admit", attrs={"pages": n})
        return n

    def swap_params(self, new_params, version=None) -> None:
        """Rolling-update weight swap: replace the served weight image
        in place (the jitted programs take params as a plain argument,
        so no recompile as long as shapes/dtypes match — and they MUST
        match, because a shape change would silently retrace inside
        the next request's TTFT).  ``new_params`` must be prepared
        exactly like the originals (same quantization, same TP
        sharding — use the family builder's preparation).

        Only a DRAINED engine may swap: the fleet's rollout drains the
        replica first, so no in-flight request ever mixes layers from
        two versions.  The engine's generated prefix-cache pages are
        version-poisoned by a swap (old-version KV under new weights),
        so the ENTIRE warm pool and spill tier are invalidated here.
        """
        if self._closed:
            raise EngineClosed(
                "swap_params on a shut-down engine"
                + (f" (replica {self.replica_id})"
                   if self.replica_id else ""))
        if self.has_work:
            raise RuntimeError(
                "swap_params needs a drained engine (queue and slots "
                "empty) — drain the replica first so no in-flight "
                "request mixes weight versions")
        old_leaves = jax.tree_util.tree_flatten(self.params)
        new_leaves = jax.tree_util.tree_flatten(new_params)
        if old_leaves[1] != new_leaves[1] or any(
                getattr(a, "shape", None) != getattr(b, "shape", None)
                or getattr(a, "dtype", None) != getattr(b, "dtype", None)
                for a, b in zip(old_leaves[0], new_leaves[0])):
            raise ValueError(
                "swap_params: new weight tree does not match the "
                "served one (structure/shape/dtype) — a mismatched "
                "swap would retrace or mis-serve; rebuild the engine "
                "for an architecture change")
        self.params = new_params
        self._invalidate_warm_pages()
        if version is not None:
            self.weights_version = version
        if self._trace_on:
            self.tracer.event("weights_swap", attrs={
                "version": _req_key(self.weights_version)})

    def _invalidate_warm_pages(self) -> None:
        """Drop every published prefix-cache page (HBM warm pool and
        spill tier): KV computed under the old weights must never be
        shared into a new-version request's page table."""
        if not self._pc_on:
            return
        al = self.allocator
        # a drained engine's published pages are all warm (refcount 0);
        # reclaim_warm drops them from the pool + content index without
        # the demote hook — a version swap must not spill poisoned
        # pages to the tier — and the tier's existing entries discard
        if al.pool:
            al.reclaim_warm(list(al.pool), demoted=False)
        if self._kv_pool is not None:
            for key in list(self._kv_pool.entries):
                self._kv_pool.discard(key)

    # ----------------------------------------------------------- scheduling
    # dstpu: hot-path
    def _upload_dirty(self) -> None:
        """One batched host→device upload of whatever changed (the whole
        table is [max_batch, pages_per_seq] int32 — tiny; uploading it
        wholesale beats per-row ``.at[b].set`` device updates).

        Rows still mid-chunked-prefill upload as TRASH with len 0: the
        batched decode writes a token structurally for every row, and a
        half-prefilled row must not take that write into its real pages
        (its chunk forwards use a private host-built view instead)."""
        pending = [b for b, s in enumerate(self.slots)
                   if s is not None and s.prefilling]
        if self._table_dirty:
            up = self._table_host.copy()
            for b in pending:
                up[b, :] = self.trash_page
            self.cache = self.cache._replace(table=self._put(up))
            self._table_dirty = False
        if self._lens_dirty:
            lens = np.zeros((self.max_batch,), np.int32)
            for b, s in enumerate(self.slots):
                if s is not None and not s.prefilling:
                    lens[b] = s.seq_len
            self.cache = self.cache._replace(seq_lens=self._put(lens))
            self._lens_dirty = False

    def _free_slot(self) -> Optional[int]:
        for b, s in enumerate(self.slots):
            if s is None:
                return b
        return None

    def _pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def _admit_one(self) -> bool:
        """Admit one queued request into a free slot; returns True if
        admitted.  Head-of-line blocking fix: when the HEAD request's
        pages do not fit, up to ``admit_lookahead`` younger requests
        are considered instead of stalling the whole queue (skipped
        entries counted in ``serving_admit_skips``).  The head is
        always tried first, so a large request is never starved — it
        admits the moment its pages exist."""
        if not self.queue:
            return False
        b = self._free_slot()
        if b is None:
            return False       # no slot: nothing in the window fits
        window = min(len(self.queue), 1 + self.admit_lookahead)
        for i in range(window):
            req = self.queue[i]
            try:
                admitted = self._try_admit(b, req, queue_skips=i)
            except faults_mod.FatalStreamError:
                # an unrecoverable WEIGHT stream is engine-fatal, not
                # per-request: every future admission needs the same
                # bytes.  _try_admit cleaned up (the request stays
                # queued for a restarted engine); the structured fatal
                # — postmortem already dumped — reaches the supervisor
                raise
            except Exception as e:
                # _try_admit cleaned up after itself (pages released,
                # promotions cancelled, pins dropped) — fail THIS
                # request and keep the engine serving
                logger.warning(
                    "serving: request %r failed during admission (%s) "
                    "— releasing and continuing", req.req_id, e)
                del self.queue[i]
                self._record_failure(req, "admit_exception", e)
                return True      # progress: the queue shrank
            if admitted:
                del self.queue[i]
                if i:
                    self._c_admit_skips.inc(i)
                return True
        return False

    def _try_admit(self, b: int, req: Request,
                   queue_skips: int = 0) -> bool:
        """Admit ``req`` into slot ``b`` if its pages fit; no side
        effects on failure.  Cache-aware: the prompt's longest cached
        page-aligned prefix is shared into the page table (refcount
        bumps, read-only) and prefill starts at the first uncached
        token — cached-prefix tokens skip compute entirely."""
        T = len(req.tokens)
        ps = self.page_size
        # ---- longest cached page-aligned prefix (chained-hash walk
        # across EVERY tier: HBM index hits share read-only as before;
        # demoted spans on the host/NVMe tier are hits too, re-admitted
        # through promotion).  At least one prompt token always
        # prefills (the engine samples the first generated token from
        # the last prompt position's logits), so a fully covered prompt
        # gives up its final page.
        matched: List[Tuple[str, Any]] = []
        if self._pc_on:
            if req.page_keys is None:
                req.page_keys = page_keys(req.tokens, ps)
            keys = req.page_keys[:matchable_pages(T, ps)]
            if self._kvt_on:
                matched = self.allocator.lookup_tiered(keys)
                if self._promo_channel is not None and any(
                        kind == "tier" and
                        self._kv_pool.location(k) == "nvme"
                        for kind, k in matched):
                    # the NVMe read channel is single-consumer (one
                    # promotion's alternating aio slots at a time).
                    # Host-resident tier hits promote concurrently —
                    # their reads are dict lookups — but an admission
                    # needing NVMe bytes while another promotion owns
                    # the channel WAITS: admitting with only the HBM
                    # prefix would re-prefill a span that is sitting
                    # demoted, turning a DMA back into compute.  The
                    # lookahead window keeps other traffic admitting.
                    self._c_kvt_admit_waits.inc()
                    return False
            else:
                matched = [("hbm", p)
                           for p in self.allocator.lookup(keys)]
        cm = len(matched)
        cached = cm * ps
        hbm_pages = [p for kind, p in matched if kind == "hbm"]
        tier_keys = [k for kind, k in matched if kind == "tier"]
        bkt = self.prefill_chunk or self.prefill_bucket
        # bucket-pad the UNCACHED suffix for a bounded compile count,
        # clamped to the table width (a prompt near max_seq must not
        # pad past the row)
        end = min(cached + -(-(T - cached) // bkt) * bkt,
                  self.max_pages_per_seq * ps)
        # tier-matched spans skip prefill COMPUTE but still need fresh
        # physical pages for the promoted payload to land in
        need = self._pages_needed(max(end, T + 1)) - cm + len(tier_keys)
        # matched warm-pool pages revive rather than consume free pages,
        # but they stop being evictable once shared — the fresh-page
        # demand must be met WITHOUT counting them as reclaimable
        pooled = sum(1 for p in hbm_pages if p in self.allocator.pool)
        if self.allocator.available - pooled < need:
            return False
        seq_id = self._seq_counter
        self._seq_counter += 1
        promo = None
        page_map: Dict[bytes, int] = {}
        try:
            # share BEFORE allocate: allocation pressure must never
            # evict a page this very admission is about to map.  (It
            # MAY demote a warm page into the tier pool mid-allocate —
            # the pool pins this admission's tier keys below, so the
            # cascade can't drop the very entries about to be
            # promoted.)
            if tier_keys:
                self._kv_pool.pin(tier_keys)
            if hbm_pages:
                self.allocator.share(seq_id, hbm_pages)
            # batch-demote the shortfall up front: one device read for
            # the whole admission instead of one per page in _evict_one
            self._ensure_free(need)
            pages = self.allocator.allocate(seq_id, need)
            fresh = iter(pages)
            row: List[int] = []
            for kind, val in matched:
                if kind == "hbm":
                    row.append(val)
                else:
                    pg = next(fresh)
                    page_map[val] = pg
                    row.append(pg)
            suffix = list(fresh)
            self._table_host[b, :] = self.trash_page
            self._table_host[b, :cm] = row
            self._table_host[b, cm:cm + len(suffix)] = suffix
            self._table_dirty = self._lens_dirty = True
            if self._pc_on:
                (self._c_pc_hits if cm else self._c_pc_misses).inc()
                self._c_pc_cached_tokens.inc(cached)
                self._c_pc_prompt_tokens.inc(T)
            if req.traced:
                # BEFORE the prefill compute below: the trace's
                # admitted→first_token span is the prefill cost
                self.tracer.event("admitted", req.req_id, b, attrs={
                    "cached_tokens": cached,
                    "tier_pages": len(tier_keys),
                    "queue_skips": queue_skips})

            self._rng, rng = jax.random.split(self._rng)
            if tier_keys:
                promo = self._begin_promotion(b, tier_keys, page_map)
            if self.prefill_chunk or cached:
                # split-fuse and/or cache-hit admission: the uncached
                # suffix is absorbed in continuation chunks starting at
                # the first uncached token; the slot is not
                # decode-ready until prefill_done reaches T.  (A hit
                # under prefill_chunk=0 absorbs prefill_bucket tokens
                # per iteration.)
                self.slots[b] = _Slot(req=req, seq_len=cached,
                                      generated=[], rng=rng,
                                      seq_id=seq_id,
                                      prefill_done=cached, promo=promo)
                self._c_admitted.inc()
                return True

            toks = np.full((1, end), 0, np.int32)
            toks[0, :T] = req.tokens
            # table row from the HOST copy: a [b:b+1] device slice can
            # alias the live table buffer (full-range slice), which
            # prefill's cache donation would then delete out from under
            # the decode path
            view = PagedKVCache(
                k=self.cache.k, v=self.cache.v,
                table=self._put(self._table_host[b:b + 1]),
                seq_lens=self._put(jnp.zeros((1,), jnp.int32)),
                page_size=self.page_size)
            logits, view = self._prefill(self.params, self._put(toks),
                                         view)
            if self._devprof_on and self.devprof.should_sample(
                    "prefill"):
                # dstpu: host-sync-ok: sampled devprof device-time
                # attribution (one sync per 1/sample_rate prefills)
                self.devprof.observe_device("prefill", logits)
            self.cache = self.cache._replace(k=view.k, v=view.v)

            slot = _Slot(req=req, seq_len=T, generated=[], rng=rng,
                         seq_id=seq_id)
            self.slots[b] = slot
            self._c_admitted.inc()
            # the prompt's full pages are immutable from here on
            # (decode writes only at the frontier) — make them
            # matchable now so concurrent same-prefix requests hit
            self._publish_full_pages(b, slot, upto=T)
            # first generated token comes from the REAL last prompt
            # position; sampling is deferred into the step's one
            # batched boundary flush
            self._queue_boundary(b, logits[0, T - 1], slot)
            return True
        except BaseException:
            # an exception between page allocation and slot publish
            # must not leak: fence + cancel any in-flight tier
            # promotion, drop the pins, release every page this seq
            # acquired (shared AND fresh), and clear the table row —
            # then let the caller decide the request's fate
            if self._promo_channel == b:
                # this admission owned the NVMe channel: drain ANY
                # reads it submitted — a presubmit that raised partway
                # (promo never assigned, primed never set) still left
                # in-flight aio ops targeting buffers about to be
                # dropped, and stale fds on the shared channel slot
                try:
                    self._kv_pool.fence_all_reads()
                except Exception:
                    logger.exception(
                        "serving: fence during admission cleanup")
            if page_map:
                # covers a promotion begun partway too (cancel of a
                # never-begun page is a no-op)
                for pg in page_map.values():
                    self.allocator.cancel_promotion(pg)
                if self._promo_channel == b:
                    self._promo_channel = None
                self._g_kvt_inflight.set(len(self.allocator.promoting))
            if tier_keys:
                self._kv_pool.unpin(tier_keys)
            self.allocator.release(seq_id)
            self._table_host[b, :] = self.trash_page
            self._table_dirty = self._lens_dirty = True
            self.slots[b] = None
            self._pending_boundary = [p for p in self._pending_boundary
                                      if p[0] != b]
            raise

    def _valid_tokens(self, s: "_Slot") -> int:
        """Positions of slot ``s`` that hold REAL written KV: mid-
        prefill that is the absorbed prefix; once decoding, the prompt
        plus every generated token fed back through decode (the final
        generated token never is, and structural post-EOS chunk writes
        land past this bound — never inside a publishable page)."""
        if s.prefilling:
            return s.prefill_done
        return len(s.req.tokens) + max(len(s.generated) - 1, 0)

    def _publish_full_pages(self, b: int, s: "_Slot",
                            upto: int) -> None:
        """Content-address every full page of slot ``b`` holding tokens
        ``0..upto-1`` (chained keys; idempotent — shared prefix pages
        dedup onto their existing index entries)."""
        if not self._pc_on:
            return
        ps = self.page_size
        full = min(upto, self.max_pages_per_seq * ps) // ps
        if full <= 0:
            return
        if s.req.page_keys is None:
            s.req.page_keys = []
        if len(s.req.page_keys) < full:
            # incremental: only the pages grown since the last event
            # (admission hashed the prompt; finish hashes generated)
            extend_page_keys(s.req.page_keys,
                             s.req.tokens + s.generated, full, ps)
        for slot_idx in range(full):
            page = int(self._table_host[b, slot_idx])
            if page == self.trash_page:
                break
            if page in self.allocator.promoting:
                # in-flight promotion: the payload hasn't landed, so
                # indexing this page now would serve garbage to every
                # future match — finish_promotion publishes it
                continue
            if self.allocator.publish(page, s.req.page_keys[slot_idx]):
                self._c_pc_published.inc()

    # ------------------------------------------------ KV tier: promote
    # dstpu: page-guard-ok: every quarantine lands in page_map first,
    # and the caller (_try_admit)'s BaseException handler cancels each
    # page_map entry, drops the tier pins and releases the seq
    def _begin_promotion(self, b: int, tier_keys: List[bytes],
                         page_map: Dict[bytes, int]) -> _Promotion:
        """Start streaming a tier-matched span back into the fresh HBM
        pages just allocated for it.  The reader's group-0 reads are
        presubmitted HERE (admission time) when the aio priority group
        allows, so NVMe latency overlaps every step the engine runs
        before this slot's first suffix-prefill chunk; the upload
        itself happens in :meth:`_complete_promotion`, batched per
        group, double-buffered against the next group's reads."""
        from deepspeed_tpu.param_stream import TierPageReader

        for key, pg in page_map.items():
            self.allocator.begin_promotion(pg, key)
        # pinned entries can neither drop nor spill, so a promotion
        # whose keys are all host-resident stays channel-free: it
        # reads through the pool's no-op-fencing host view and any
        # number may be in flight.  Only an NVMe-backed promotion
        # claims the single aio channel (and only it may fence or
        # slot-toggle that channel).
        channel = any(self._kv_pool.location(k) == "nvme"
                      for k in tier_keys)
        reader = TierPageReader(
            self._kv_pool if channel else self._kv_pool.host_view(),
            tier_keys, to_device=None,
            group_pages=self.kv_tier.promote_group_pages,
            registry=self.registry, tracer=self.tracer,
            retries=self.kv_tier.io_retries,
            retry_backoff_s=self.kv_tier.io_retry_backoff_s)
        # bound late: the callback needs the reader's own group table
        reader.to_device = lambda bufs, g: self._promote_group(
            page_map, bufs, reader.group_keys(g))
        promo = _Promotion(keys=list(tier_keys), page_map=page_map,
                           reader=reader, channel=channel,
                           t_start=time.perf_counter())
        if channel:
            self._promo_channel = b
        # host-resident presubmit is pure dict lookups — never defer
        # it on aio priority; only NVMe reads yield to weight streams
        if not channel or self._kv_pool.may_submit():
            promo.primed = reader.presubmit(0)
        self._g_kvt_inflight.set(len(self.allocator.promoting))
        return promo

    def _promotion_ready(self, b: int, s: "_Slot") -> bool:
        """Gate for the promoting slot's prefill: defer (bounded) while
        the tier reads are still in flight — the promotion then hides
        under other slots' compute — or while aio priority asks KV to
        yield to layer-weight streams; once ready (or at the deferral
        cap), drain the promotion and let prefill proceed."""
        p = s.promo
        if p.primed is None:
            if self._kv_pool.may_submit() or \
                    p.deferred >= _KV_PROMO_DEFER_CAP:
                p.primed = p.reader.presubmit(0)
            else:
                p.deferred += 1
                self._c_kvt_deferrals.inc()
                return False
        # only the channel owner's reads are on the aio queue — a
        # host-resident promotion's buffers fenced for free at
        # presubmit, so it never defers on another slot's reads
        if p.channel and self._kv_pool.reads_pending() and \
                p.deferred < _KV_PROMO_DEFER_CAP:
            p.deferred += 1
            self._c_kvt_deferrals.inc()
            return False
        self._complete_promotion(b, s)
        return True

    def _complete_promotion(self, b: int, s: "_Slot") -> None:
        """Drain the slot's promotion: every group fences, dequantizes
        and scatters into its target pages (group g+1's tier reads in
        flight while group g uploads), then the pages publish under
        their content keys — matchable for concurrent admissions.

        Graceful degradation: the reader already retried transient aio
        errors and tried the synchronous fallback; whatever still
        escapes (a checksum mismatch, an unrecoverable read) abandons
        the promotion and falls back to re-prefilling the unlanded
        span — correctness preserved, the DMA saving lost."""
        p = s.promo
        try:
            for _ in p.reader.sweep(range(p.reader.n_groups),
                                    primed=p.primed):
                pass
        except Exception as e:
            self._promotion_fallback(b, s, e)
            return
        self._kvt_fault_streak = 0
        dt = time.perf_counter() - p.t_start
        self._h_kvt_promote.observe(dt)
        self._kv_pool.unpin(p.keys)
        if s.req.traced:
            self.tracer.event("kv_promote", s.req.req_id, b, attrs={
                "pages": len(p.keys), "wait_s": round(dt, 6),
                "deferred_steps": p.deferred})
        s.promo = None
        if p.channel and self._promo_channel == b:
            self._promo_channel = None
        self._g_kvt_inflight.set(len(self.allocator.promoting))

    def _promotion_fallback(self, b: int, s: "_Slot",
                            exc: BaseException) -> None:
        """Abandon a failed promotion and re-prefill the span it was
        supposed to stream (ISSUE acceptance: promote failure or
        checksum mismatch must cost compute, never correctness).

        Groups land in page order, so landed pages (already published)
        form a contiguous prefix; everything from the first unlanded
        page onward rolls back: its allocator quarantine is cancelled
        (the pages stay owned — prefill writes them now), its suspect
        tier entries drop from the pool, and the slot's absorbed
        prefix retreats to the first unlanded page boundary.  Repeated
        failures trip the tier circuit breaker
        (``kv_tier.disable_after``)."""
        p = s.promo
        try:
            self._kv_pool.fence_all_reads()
        except Exception:
            pass                    # the channel may be the failure
        unlanded = [(key, pg) for key, pg in p.page_map.items()
                    if pg in self.allocator.promoting]
        self._kv_pool.unpin(p.keys)
        for key, pg in unlanded:
            self.allocator.cancel_promotion(pg)
            # the payload is suspect (failed read or corrupt) — a
            # future admission must re-prefill, not re-promote it.
            # UNLESS a concurrent promotion still pins the key: its
            # reads are in flight against this entry, so it must keep
            # resolving (it will hit the same checksum and run its own
            # fallback, which then drops the entry)
            if key not in self._kv_pool._pinned:
                self._kv_pool.discard(key)
        if unlanded:
            # roll the absorbed prefix back to the first unlanded
            # page: everything before it (HBM-shared + landed
            # promotions) is intact history the continuation chunks
            # attend over
            row = [int(x) for x in self._table_host[b]]
            first_bad = min(row.index(pg) for _k, pg in unlanded)
            fb_tokens = first_bad * self.page_size
            s.prefill_done = min(s.prefill_done, fb_tokens)
            s.seq_len = min(s.seq_len, fb_tokens)
        else:
            fb_tokens = s.prefill_done
        if isinstance(exc, ChecksumError):
            self._c_kvt_checksum.inc()
            self._n_kvt_checksum += 1
        self._c_kvt_fb_events.inc()
        self._c_kvt_fb_pages.inc(len(unlanded))
        self._n_kvt_fallbacks += 1
        logger.warning(
            "serving: KV-tier promotion failed for request %r "
            "(%s) — re-prefilling %d pages from token %d",
            s.req.req_id, exc, len(unlanded), fb_tokens)
        if self._trace_on:
            self.tracer.event("kv_promote_failed", s.req.req_id, b,
                              attrs={"error": repr(exc)[:200],
                                     "pages": len(unlanded),
                                     "resume_token": fb_tokens})
        s.promo = None
        if p.channel and self._promo_channel == b:
            self._promo_channel = None
        self._g_kvt_inflight.set(len(self.allocator.promoting))
        # circuit breaker: repeated promote failures disable the tier
        # (demotes become evictions, hits become misses) — /healthz
        # reports degraded, the router routes around
        self._kvt_fault_streak += 1
        da = self.kv_tier.disable_after
        if da and self._kvt_fault_streak >= da and \
                self._kv_pool.disabled is None:
            self._kv_pool.disable(
                f"{self._kvt_fault_streak} consecutive promotion "
                "failures")

    def _promote_group(self, page_map: Dict[bytes, int], bufs,
                       g_keys) -> List[int]:
        """TierPageReader ``to_device``: one fenced GROUP of spilled
        pages → decode (dequantize cold pages) → one batched scatter
        into the target HBM pages → publish."""
        i = 0
        if self._quant_resident:
            # int8-resident publish: the entry's codes + scales go to
            # the device VERBATIM — no dequantize on the host, no
            # dense scatter, and (because decode_quantized still
            # verifies the stored checksums first) the same corruption
            # guarantees as the dense path
            pages, kqs, kss, vqs, vss = [], [], [], [], []
            for key in g_keys:
                names, _shapes, _dtypes = self._kv_pool.entry_meta(key)
                take = bufs[i:i + len(names)]
                i += len(names)
                kq, ks_, vq, vs_ = self._kv_pool.decode_quantized(
                    key, take)
                kqs.append(kq)
                kss.append(ks_)
                vqs.append(vq)
                vss.append(vs_)
                pages.append(page_map[key])
            self._upload_promoted_q(
                pages, np.stack(kqs, axis=2), np.stack(kss, axis=2),
                np.stack(vqs, axis=2), np.stack(vss, axis=2))
            self._c_kvt_qres_promotes.inc(len(g_keys))
        else:
            pages, ks, vs = [], [], []
            for key in g_keys:
                names, _shapes, _dtypes = self._kv_pool.entry_meta(key)
                take = bufs[i:i + len(names)]
                i += len(names)
                k, v = self._kv_pool.decode(key, take)
                ks.append(k)
                vs.append(v)
                pages.append(page_map[key])
            self._upload_promoted(pages, np.stack(ks, axis=2),
                                  np.stack(vs, axis=2))
        for key, pg in zip(g_keys, pages):
            if self.allocator.finish_promotion(pg, key):
                self._c_pc_published.inc()
        self._c_kvt_promoted.inc(len(g_keys))
        return pages

    def _cancel_promotion(self, s: "_Slot") -> None:
        """Abandon a slot's in-flight promotion (preemption): fence any
        outstanding tier reads (they target host buffers about to be
        dropped), release the allocator quarantine, and let the pages
        free through the normal release path.  The spill entries stay
        — the recompute requeue will hit and promote them again."""
        p = s.promo
        if p is None:
            return
        if p.channel:
            # regardless of `primed`: a presubmit that raised partway
            # may have submitted reads without ever assigning it —
            # drain whatever is on the channel (free when nothing is)
            try:
                self._kv_pool.fence_all_reads()
            except Exception:
                # a failing drain must never abort the cancel — the
                # quarantine/pin/channel cleanup below is what keeps
                # the engine admitting
                logger.exception("serving: promotion-cancel fence")
        for pg in p.page_map.values():
            self.allocator.cancel_promotion(pg)
        self._kv_pool.unpin(p.keys)
        s.promo = None
        if p.channel and self._promo_channel is not None:
            self._promo_channel = None
        self._g_kvt_inflight.set(len(self.allocator.promoting))

    # ------------------------------------------------- KV tier: demote
    def _fetch_idx(self, pages: List[int]):
        """Bucket a page-id list to a power-of-two length (repeating
        the last id) so the eager gather/scatter ops below compile a
        BOUNDED set of shapes — a churning cache must not pay one XLA
        compile per distinct batch size."""
        n = len(pages)
        cap = 1
        while cap < n:
            cap *= 2
        return np.asarray(list(pages) + [pages[-1]] * (cap - n),
                          np.int32), n

    def _fetch_pages_host(self, pages: List[int]):
        """Device→host copy of whole pages across the layer stack:
        ``[L, KV, n, ps, Dh]`` (k, v).  The ZI engine overrides for its
        per-layer cache tuples."""
        idx, n = self._fetch_idx(pages)
        k, v = jax.device_get((self.cache.k[:, :, idx],
                               self.cache.v[:, :, idx]))
        return np.asarray(k)[:, :, :n], np.asarray(v)[:, :, :n]

    def _fetch_pages_host_q(self, pages: List[int]):
        """Quantized-resident twin of :meth:`_fetch_pages_host`: ONE
        device→host transfer of the int8 codes + f32 scales —
        ``(kq [L, KV, n, ps, Dh] i8, ks [L, KV, n, ps, 1] f32, vq,
        vs)`` — so a demotion captures the page VERBATIM (no dequant,
        no requantize, no extra rounding)."""
        idx, n = self._fetch_idx(pages)
        c = self.cache
        kq, ks, vq, vs = jax.device_get(
            (c.k[:, :, idx], c.k_scale[:, :, idx],
             c.v[:, :, idx], c.v_scale[:, :, idx]))
        return (np.asarray(kq)[:, :, :n], np.asarray(ks)[:, :, :n],
                np.asarray(vq)[:, :, :n], np.asarray(vs)[:, :, :n])

    def _promote_idx(self, pages: List[int], *arrays):
        """Pad a promotion scatter to the FIXED promote group size:
        pad lanes aim one past the page array and drop (the
        ``write_token_pages`` trick), so every group — full, tail, or
        short chain — runs the same compiled update."""
        G = max(self.kv_tier.promote_group_pages, len(pages))
        pad = G - len(pages)
        idx = np.asarray(list(pages) + [self.trash_page + 1] * pad,
                         np.int32)
        if pad:
            arrays = tuple(
                np.concatenate(
                    [a, np.zeros(a.shape[:2] + (pad,) + a.shape[3:],
                                 a.dtype)], axis=2)
                for a in arrays)
        return (jnp.asarray(idx),) + tuple(arrays)

    def _upload_promoted(self, pages: List[int], k_host, v_host) -> None:
        """Scatter promoted payloads (``[L, KV, n, ps, Dh]``) into
        their target pages.  One dispatch per array; jax's async
        dispatch overlaps the H2D DMA with whatever device work is in
        flight, and the first forward reading these pages orders after
        the update through the value dependency."""
        idx, k_host, v_host = self._promote_idx(pages, k_host, v_host)
        self.cache = self.cache._replace(
            k=self.cache.k.at[:, :, idx].set(
                self._put(jnp.asarray(k_host)), mode="drop"),
            v=self.cache.v.at[:, :, idx].set(
                self._put(jnp.asarray(v_host)), mode="drop"))
        if self._devprof_on and self.devprof.should_sample("promote"):
            # dstpu: host-sync-ok: sampled devprof device-time
            # attribution (one sync per 1/sample_rate promote scatters)
            self.devprof.observe_device("promote", self.cache.k)

    def _upload_promoted_q(self, pages: List[int], kq, ks,
                           vq, vs) -> None:
        """Quantized-resident promote scatter: the cold entry's int8
        codes + scales land in the device planes DIRECTLY — the dense
        path's dequantize (host) + wide scatter never runs, which is
        the point of ``kv_tier.quantized_resident`` (the page is also
        4x smaller on the H2D wire than its f32 decode)."""
        idx, kq, ks, vq, vs = self._promote_idx(pages, kq, ks, vq, vs)
        c = self.cache
        self.cache = c._replace(
            k=c.k.at[:, :, idx].set(
                self._put(jnp.asarray(kq)), mode="drop"),
            k_scale=c.k_scale.at[:, :, idx].set(
                self._put(jnp.asarray(ks)), mode="drop"),
            v=c.v.at[:, :, idx].set(
                self._put(jnp.asarray(vq)), mode="drop"),
            v_scale=c.v_scale.at[:, :, idx].set(
                self._put(jnp.asarray(vs)), mode="drop"))
        if self._devprof_on and self.devprof.should_sample("promote"):
            # dstpu: host-sync-ok: sampled devprof device-time
            # attribution (one sync per 1/sample_rate promote scatters)
            self.devprof.observe_device("promote", self.cache.k)

    def _demote_for_evict(self, page: int, key: bytes) -> bool:
        """``PageAllocator.demote_hook``: capture an evicted warm
        page's KV to the tier pool.  A span whose payload is already
        spilled (promoted earlier, evicted again) re-demotes for free —
        no device read, no copy."""
        pool = self._kv_pool
        if pool is None:
            return False
        if pool.has(key):
            pool.touch(key)
            self._c_kvt_demoted.inc()
            return True
        if self._quant_resident:
            kq, ks, vq, vs = self._fetch_pages_host_q([page])
            loc = pool.demote_prequantized(
                key, kq[:, :, 0], ks[:, :, 0], vq[:, :, 0], vs[:, :, 0])
        else:
            k, v = self._fetch_pages_host([page])
            loc = pool.demote(key, k[:, :, 0], v[:, :, 0])
        if loc is None:
            return False
        self._c_kvt_demoted.inc()
        if self._trace_on:
            self.tracer.event("kv_demote", attrs={
                "key": key_hex(key)[:12], "tier": loc})
        return True

    def _demote_warm_batch(self, cands) -> None:
        """Demote a batch of warm ``(page, key)`` candidates with ONE
        batched device→host read (pages whose spans are already
        spilled just refresh their age), then reclaim them to the free
        list.  Shared by the watermark sweep and the pre-allocation
        top-up — the per-page ``_evict_one`` hook stays only as the
        fallback for pressure neither anticipated."""
        al = self.allocator
        fresh = [(p, k) for p, k in cands if not self._kv_pool.has(k)]
        if fresh:
            # fetch in precompiled-bucket chunks: a big watermark sweep
            # over the whole warm pool must not trigger a fresh gather
            # compile inside the serving step.  The quantized-resident
            # fetch returns 4 component arrays (codes + scales); the
            # dense one 2 — zip/concat handles both.
            cap = self._kvt_fetch_cap
            parts = []
            for i in range(0, len(fresh), cap):
                pg = [p for p, _ in fresh[i:i + cap]]
                parts.append(self._fetch_pages_host_q(pg)
                             if self._quant_resident
                             else self._fetch_pages_host(pg))
            bufs = tuple(np.concatenate(comp, axis=2)
                         for comp in zip(*parts))
        at = {p: i for i, (p, _) in enumerate(fresh)}
        demoted, dropped = [], []
        for p, key in cands:
            if p in at:
                i = at[p]
                page = tuple(a[:, :, i] for a in bufs)
                loc = (self._kv_pool.demote_prequantized(key, *page)
                       if self._quant_resident
                       else self._kv_pool.demote(key, *page))
            else:
                loc = self._kv_pool.touch(key)
            (demoted if loc else dropped).append(p)
        al.reclaim_warm(demoted, demoted=True)
        al.reclaim_warm(dropped, demoted=False)
        if demoted:
            self._c_kvt_demoted.inc(len(demoted))
            if self._trace_on:
                self.tracer.event("kv_demote", attrs={
                    "pages": len(demoted)})

    def _ensure_free(self, n: int) -> None:
        """Top the free list up to ``n`` pages by batch-demoting the
        oldest warm pages BEFORE an allocation dips into the warm
        pool — one batched device read per shortfall instead of one
        synchronous per-page copy inside each ``_evict_one``."""
        if not self._kvt_on:
            return
        al = self.allocator
        short = n - len(al.free)
        if short <= 0:
            return
        cands = al.oldest_warm(short)
        if cands:
            self._demote_warm_batch(cands)

    def _demote_watermark_sweep(self) -> None:
        """Proactive demotion: when the warm pool fills past the
        ``demote_watermark`` fraction of its cap, the oldest warm pages
        demote in ONE batched device→host read — freeing HBM pages
        ahead of allocation pressure so admissions stop paying the
        per-eviction copy on their own critical path."""
        al = self.allocator
        excess = len(al.pool) - self._kvt_wm_pages
        if excess <= 0:
            return
        self._demote_warm_batch(al.oldest_warm(excess))

    # dstpu: hot-path
    def _advance_prefill(self, b: int, s: "_Slot") -> None:
        """Absorb the next chunk of slot ``b``'s prompt (one fixed-shape
        continuation forward: history + chunk).  On the final chunk,
        sample the first generated token from the last REAL prompt
        position and flip the slot decode-ready.

        Chunk size is ``prefill_chunk`` under split-fuse; a cache-hit
        admission with ``prefill_chunk=0`` absorbs its uncached suffix
        ``prefill_bucket`` tokens per iteration through the same path
        (history = the shared cached pages).  A slot with an in-flight
        tier promotion must not run its first chunk before the promoted
        pages land (the chunk attends over them); it defers — bounded —
        while the reads are still in flight, hiding the promotion under
        the other slots' compute in the same scheduler iteration."""
        if s.promo is not None and not self._promotion_ready(b, s):
            return
        C = self.prefill_chunk or self.prefill_bucket
        T = len(s.req.tokens)
        done = s.prefill_done
        take = min(C, T - done)
        toks = np.zeros((1, C), np.int32)
        toks[0, :take] = s.req.tokens[done:done + take]
        # slice the view table to the live history: the chunk attention
        # gathers every page the table names, so handing it the full
        # max_seq row would make each chunk cost O(max_seq) rather than
        # O(done + C).  Power-of-two bucketing bounds the compile count.
        np_live = -(-(done + C) // self.page_size)
        np_bkt = 1
        while np_bkt < np_live:
            np_bkt *= 2
        np_bkt = min(np_bkt, self.max_pages_per_seq)
        view = PagedKVCache(
            k=self.cache.k, v=self.cache.v,
            table=self._put(self._table_host[b:b + 1, :np_bkt]),
            seq_lens=self._put(jnp.full((1,), done, jnp.int32)),
            page_size=self.page_size)
        logits, view = self._chunk_prefill(self.params, self._put(toks),
                                           view)
        if self._devprof_on and self.devprof.should_sample("prefill"):
            # dstpu: host-sync-ok: sampled devprof device-time
            # attribution (one sync per 1/sample_rate prefill chunks)
            self.devprof.observe_device("prefill", logits)
        self.cache = self.cache._replace(k=view.k, v=view.v)
        s.prefill_done = done + take
        s.seq_len = s.prefill_done
        self._c_prefill_chunks.inc()
        if s.req.traced:
            self.tracer.event("prefill_chunk", s.req.req_id, b, attrs={
                "done": s.prefill_done, "of": T, "take": take})
        if s.prefill_done >= T:
            s.prefill_done = -1
            # decode-ready: the device table/lens row must flip from
            # trash to the real pages before the next decode
            self._table_dirty = self._lens_dirty = True
            # prompt pages are full and immutable now — make them
            # matchable before the first token can finish the request
            self._publish_full_pages(b, s, upto=T)
            self._queue_boundary(b, logits[0, take - 1], s)

    def _preempt_youngest(self) -> None:
        """vLLM-style recompute preemption: release the youngest slot's
        pages and requeue prompt+generated as a fresh request."""
        cand = [(len(s.generated), b) for b, s in enumerate(self.slots)
                if s is not None]
        if not cand:
            raise MemoryError("out of KV pages with no slot to preempt")
        _, b = min(cand)
        s = self.slots[b]
        logger.warning("serving: preempting request %r (%d generated)",
                       s.req.req_id, len(s.generated))
        # publish-then-release: the victim's full pages stay matchable
        # in the warm pool, so its recompute-from-scratch requeue (and
        # any same-prefix request) re-admits against its own cached
        # prefix — preemption releases REFERENCES, not page contents
        self._publish_full_pages(b, s, upto=self._valid_tokens(s))
        # promotion pages were skipped by the publish guard above; now
        # fence + abandon the in-flight transfer before release frees
        # them (the spill entries survive for the recompute to re-hit)
        if s.promo is not None:
            self._cancel_promotion(s)
        self.allocator.release(s.seq_id)
        self._table_host[b, :] = self.trash_page
        self._table_dirty = self._lens_dirty = True
        self.slots[b] = None
        req = s.req
        if req.traced:
            self.tracer.event("preempt", req.req_id, b, attrs={
                "generated": len(s.generated)})
        # requeue prompt+generated for recompute; the finished output is
        # simply tokens+generated of the FINAL incarnation, which already
        # contains everything produced before preemption
        # NOT re-announced to the SLO tracker: its record (and with it
        # the original arrival time) survives under the same req_id, so
        # the recompute is judged against the user's real clock
        self.queue.appendleft(Request(
            req.req_id, req.tokens + s.generated,
            req.max_new_tokens - len(s.generated), req.temperature,
            t_submit=req.t_submit, page_keys=req.page_keys,
            traced=req.traced, first_token_seen=req.first_token_seen,
            t_arrival=req.t_arrival, tier=req.tier))
        self._c_preempted.inc()
        if req.traced:
            self.tracer.event("requeue", req.req_id)

    def _queue_boundary(self, b: int, logits_row, slot: _Slot) -> None:
        """Defer sampling a prefill-boundary token: hold the slot's
        last-position logits ROW on device and flush every pending row
        through one batched :func:`_sample_rows` per step — the old
        path ran ``sample_logits`` + ``int()`` per slot, one device
        round-trip per admission."""
        slot.rng, key = jax.random.split(slot.rng)
        self._pending_boundary.append(
            (b, logits_row, key, slot.req.temperature))

    # dstpu: hot-path
    def _flush_boundary(self) -> None:
        if not self._pending_boundary:
            return
        pend, self._pending_boundary = self._pending_boundary, []
        # pad to max_batch: the pending count varies per step (1 slot
        # finishing prefill … all of them under a cache-hit burst) and
        # _sample_rows would compile once per distinct size — pay one
        # fixed shape instead, row count is bounded by max_batch anyway
        pad = self.max_batch - len(pend)
        rows = [p[1] for p in pend] + [pend[0][1]] * pad
        keys = [p[2] for p in pend] + [pend[0][2]] * pad
        temps = np.zeros((self.max_batch,), np.float32)
        temps[:len(pend)] = [p[3] for p in pend]
        want_dev = (self._devprof_on
                    and self.devprof.should_sample("sample"))
        t0_dev = time.perf_counter() if want_dev else 0.0
        # dstpu: host-sync-ok: boundary sample fetch, one batched
        # transfer per step for every prefill completion (replaced
        # PR 7's per-slot device round-trip)
        toks = np.asarray(self._sample_fn(
            jnp.stack(rows), jnp.stack(keys), self._put(temps)))
        if want_dev:
            # the np.asarray above already synced — self-timed, no
            # extra block_until_ready needed
            self.devprof.record_device(
                "sample", time.perf_counter() - t0_dev)
        self._c_boundary_syncs.inc()
        self._c_kdisp_sample.inc()
        for (b, _, _, _), tok in zip(pend, toks):
            self._append_token(b, int(tok))

    # dstpu: hot-path
    def _append_token(self, b: int, tok: int) -> None:
        if self._devprof_on and not self.devprof.steady:
            # first token of the FIRST request: everything before this
            # is warmup compilation; every compile after is steady-state
            # (and trips the incident probe + bench gate)
            self.devprof.mark_steady()
        s = self.slots[b]
        s.generated.append(tok)
        if self._tel_on or self._slo_on:
            # ONE clock read shared by the TTFT/ITL histograms and the
            # SLO tracker — the slo-on-top-of-telemetry cost is a dict
            # hit, not a second perf_counter
            now = time.perf_counter()
            if self._tel_on:
                if s.req.t_submit is not None:
                    self._h_ttft.observe(now - s.req.t_submit)
                    s.req.t_submit = None  # once per request lifetime
                elif s.last_tok_t:
                    self._h_itl.observe(now - s.last_tok_t)
                s.last_tok_t = now
            if self._slo_on:
                self.slo_tracker.on_token(s.req.req_id, now=now)
        if s.req.traced and not s.req.first_token_seen:
            # adjacent to the TTFT observation above so the trace's
            # queued→first_token delta agrees with the histogram
            s.req.first_token_seen = True
            self.tracer.event("first_token", s.req.req_id, b)
        done = (self.eos is not None and tok == self.eos) or \
            len(s.generated) >= s.req.max_new_tokens
        if done:
            self.finished[s.req.req_id] = list(s.req.tokens) + s.generated
            self._newly_finished.append(s.req.req_id)
            if self._slo_on:
                # classify against the tier objectives NOW: attainment,
                # burn rates and goodput update; a burn trip fires the
                # alert into the flight recorder
                self.slo_tracker.on_finish(s.req.req_id)
            if s.req.traced:
                self.tracer.event("finish", s.req.req_id, b, attrs={
                    "generated": len(s.generated),
                    "total_tokens": len(s.req.tokens) + len(s.generated)})
            # publish-then-release: the finished request's full pages
            # (prompt AND generated history — the multi-turn prefix of
            # a follow-up request) enter the warm pool matchable, and
            # are reclaimed only under allocation pressure
            self._publish_full_pages(b, s, upto=self._valid_tokens(s))
            self.allocator.release(s.seq_id)
            self._table_host[b, :] = self.trash_page
            self._table_dirty = self._lens_dirty = True
            self.slots[b] = None

    # dstpu: hot-path
    def _grow_pages(self, ahead: int = 1) -> None:
        """Before decode writes: map every page the next ``ahead`` token
        positions will touch (chunked decode provisions its whole window
        up front); preempt when the pool is dry.  Positions past the
        request's lifetime are NOT provisioned — their garbage writes
        clamp into the sequence's own final page, which is released when
        it finishes."""
        ps = self.page_size
        for b, s in enumerate(self.slots):
            if s is None or s.prefilling:
                # chunk writes land in the pages reserved at admission
                continue
            lifetime = len(s.req.tokens) + s.req.max_new_tokens
            # last KV write is at lifetime-2: the final generated token is
            # appended to the output but never fed back through decode
            last_pos = min(s.seq_len + ahead - 1, lifetime - 2,
                           self.max_pages_per_seq * ps - 1)
            for slot_idx in range(s.seq_len // ps, last_pos // ps + 1):
                if self._table_host[b, slot_idx] != self.trash_page:
                    continue
                # available counts the warm pool: allocate reclaims
                # cached pages before any preemption is considered
                while not self.allocator.available:
                    self._preempt_youngest()
                    if self.slots[b] is None:   # we preempted ourselves
                        break
                if self.slots[b] is None:
                    break
                self._ensure_free(1)
                # dstpu: page-guard-ok: allocate records the page in
                # owned[seq_id] atomically, so _fail_slot / preemption
                # / fleet abandon_inflight release it with the seq —
                # there is no owned-but-untracked window here
                pg = self.allocator.allocate(s.seq_id, 1)[0]
                self._table_host[b, slot_idx] = pg
                self._table_dirty = True

    # ------------------------------------------------------------------ step
    def step(self) -> List[Any]:
        """One scheduling iteration: admit → batched decode.  Returns
        request ids that finished during this step."""
        self._newly_finished = []
        self._last_step_t = time.perf_counter()   # /healthz heartbeat
        if self._tel_on:
            # span: wall time into serving_step_seconds + a
            # TraceAnnotation so captured device timelines show the
            # scheduler iteration
            with Span(self._h_step_span, self._span_label):
                self._step_inner()
            if self._tel_exporter is not None:
                # one monotonic read drives the WHOLE timed control
                # plane: sink exports plus the tick hooks (SLO window
                # refresh, history sampling, incident evaluation)
                self._tel_exporter.maybe_export()
            elif self._tick_inline:
                # no exporter (telemetry= was a bare registry — the
                # fleet-replica pattern): drive the same pass inline
                now = time.monotonic()
                self.history.maybe_sample(now)
                self.incident_mgr.maybe_evaluate(now)
                self.devprof.tick(now)  # rate-limited internally
        else:
            self._step_inner()
            if self._tick_inline:
                self.incident_mgr.maybe_evaluate()
        if self._slo_on and not self._slo_tick_hooked:
            # time-driven window refresh (rate-limited to ~1/s inside):
            # an idle engine's burn gauges must decay as violations age
            # out, not stay latched at their last finish-time values.
            # (With an exporter this runs as a tick hook instead.)
            self.slo_tracker.maybe_refresh()
        return list(self._newly_finished)

    # dstpu: hot-path
    def _step_inner(self) -> None:
        if self._shed_deadline and self.queue:
            # BEFORE admission: a request whose deadline already
            # expired must shed, not burn a slot on unwanted work
            self._shed_expired()
        if self._kvt_wm_pages is not None:
            # BEFORE admission: proactively demoting past the
            # watermark frees pages the admissions below can use
            # without paying a per-eviction device read each
            self._demote_watermark_sweep()
        while self._admit_one():
            pass
        # split-fuse: absorb ONE chunk per pending-prefill slot, then
        # run the batched decode for every ready slot in the same
        # iteration.  Failure isolation: an exception in one slot's
        # host-side work (including injected `slot` faults) fails THAT
        # request and releases its resources; the others keep serving.
        for b, s in list(enumerate(self.slots)):
            if s is not None and s.prefilling:
                try:
                    if self._fault_plan is not None:
                        faults_mod.inject("slot", key=s.req.req_id)
                    self._advance_prefill(b, s)
                except faults_mod.FatalStreamError:
                    raise    # dead WEIGHT stream: engine-fatal, not
                except Exception as e:       # a per-request failure
                    self._fail_slot(b, e)
        if self._fault_plan is not None:
            # decode-ready slots get the same per-step injection
            # opportunity (a request that skipped chunked prefill
            # would otherwise be untargetable)
            for b, s in enumerate(self.slots):
                if s is not None and not s.prefilling:
                    try:
                        faults_mod.inject("slot", key=s.req.req_id)
                    except InjectedFault as e:
                        self._fail_slot(b, e)
        # every prompt that finished prefilling this step samples its
        # boundary token in ONE batched fetch, before the decode phase
        # reads generated[-1]
        self._flush_boundary()
        K = self.decode_chunk
        # the speculative sweep writes K_draft+1 positions per slot —
        # provision its whole window, like chunked decode does
        ahead = (self.speculative.draft_tokens + 1 if self._spec_on
                 else K)
        ready = lambda: [(b, s) for b, s in enumerate(self.slots)
                         if s is not None and not s.prefilling]
        active = ready()
        if active:
            self._grow_pages(ahead=ahead)
            active = ready()
        if self._tel_on:
            self._g_queue.set(len(self.queue))
            self._g_occupancy.set(len(active) / self.max_batch)
            usable = self.trash_page       # pool minus the reserved page
            # live-referenced pages only: the warm prefix pool is
            # reclaimable on demand, so it does not count as utilized
            self._g_kv_util.set(
                (usable - self.allocator.available) / max(usable, 1))
            if self._pc_on:
                ev = self.allocator.evicted
                if ev > self._evicted_seen:
                    self._c_pc_evicted.inc(ev - self._evicted_seen)
                    self._evicted_seen = ev
                self._g_pc_pool.set(len(self.allocator.pool))
                pt = self._c_pc_prompt_tokens.value
                if pt:
                    self._g_pc_frac.set(
                        self._c_pc_cached_tokens.value / pt)
        if active and self._spec_on:
            self._spec_step(active)
        elif active:
            self._upload_dirty()
            toks = np.zeros((self.max_batch, 1), np.int32)
            temps = np.zeros((self.max_batch,), np.float32)
            for b, s in active:
                toks[b, 0] = s.generated[-1] if s.generated \
                    else s.req.tokens[-1]
                temps[b] = s.req.temperature
            self._rng, r = jax.random.split(self._rng)
            keys = jax.random.split(r, K * self.max_batch).reshape(
                K, self.max_batch, -1)
            out, self.cache = self._decode_chunk_fn(
                self.params, self._put(toks), self.cache,
                self._put(keys), self._put(temps))
            if self._devprof_on and self.devprof.should_sample(
                    "decode"):
                # dstpu: host-sync-ok: sampled devprof device-time
                # attribution — the np.asarray below would sync anyway;
                # this just brackets it with a clock
                self.devprof.observe_device("decode", out)
            # trust the decode's structural seq_lens+K between
            # composition changes (inactive rows drift, rebuilt on the
            # next dirty upload)
            for b, s in active:
                s.seq_len += K
            self._c_decode_steps.inc(K)
            self._c_decode_syncs.inc()
            self._c_kdisp_paged.inc()
            self._c_kdisp_sample.inc(K)
            # dstpu: host-sync-ok: the ONE device→host transfer per
            # decode chunk (K tokens per sync — the module contract)
            host_toks = np.asarray(out)
            if self._trace_on and any(s.req.traced for _, s in active):
                # one event per BATCH sync (not per token): the decode
                # timeline at chunk granularity, nothing hotter
                self.tracer.event("decode_batch", attrs={
                    "active": len(active), "chunk": K})
            for b, s in active:
                for j in range(K):
                    self._append_token(b, int(host_toks[b, j]))
                    if self.slots[b] is None:   # finished mid-chunk:
                        break                   # rest is discard

    def _check_frontier_writable(self, active, ahead: int) -> None:
        """COW guard for the speculative write window: every page the
        verify's ``ahead`` frontier positions can touch must be
        privately owned (or the trash page).  Structurally always true
        — shared/published prefix-cache pages live strictly below the
        frontier — but a write into one would silently poison the
        content-addressed index for every future match, so the sweep
        asserts rather than trusts."""
        ps = self.page_size
        for b, s in active:
            last = min((s.seq_len + ahead - 1) // ps,
                       self.max_pages_per_seq - 1)
            for slot_idx in range(s.seq_len // ps, last + 1):
                pg = int(self._table_host[b, slot_idx])
                if pg != self.trash_page and \
                        not self.allocator.writable(pg):
                    raise RuntimeError(
                        f"speculative verify would write shared/"
                        f"published page {pg} (slot {b}, table slot "
                        f"{slot_idx}) — COW invariant violated")

    # dstpu: hot-path
    def _spec_step(self, active) -> None:
        """One draft-and-verify sweep over every decode-ready slot.

        Draft: the drafter proposes up to K tokens per slot from the
        request's own history (host-side; ∅ is fine — that row rides
        the sweep as a plain decode step).  Verify: ONE continuation
        forward scores all K+1 positions for the whole batch (under
        ZeRO-Inference this is one full layer-weight stream, amortized
        over every accepted token), then :func:`~deepspeed_tpu.
        inference.speculative.verify_accept` computes on device the
        accepted prefix length and the bonus/corrected token at every
        stop position — one host transfer per sweep, same discipline
        as chunked decode.  Rollback: each slot's ``seq_len`` advances
        by accepted+1 (not the structural K+1 the forward wrote), so
        rejected drafts' KV is abandoned above the frontier and
        overwritten by the next sweep; ``_publish_full_pages`` bounds
        on ``_valid_tokens`` keep rejected garbage out of the prefix
        cache."""
        K = self.speculative.draft_tokens
        Bm = self.max_batch
        toks = np.zeros((Bm, K + 1), np.int32)
        drafts = np.zeros((Bm, K), np.int32)
        dlens = np.zeros((Bm,), np.int32)
        temps = np.zeros((Bm,), np.float32)
        drafted = 0
        for b, s in active:
            hist = s.req.tokens + s.generated
            d = list(self.drafter.propose(hist, K))[:K]
            dlens[b] = len(d)
            drafts[b, :len(d)] = d
            toks[b, 0] = hist[-1]
            toks[b, 1:1 + len(d)] = d
            temps[b] = s.req.temperature
            drafted += len(d)
        self._c_spec_drafted.inc(drafted)
        traced_any = self._trace_on and any(
            s.req.traced for _, s in active)
        if traced_any:
            self.tracer.event("spec_draft", attrs={
                "active": len(active), "drafted": drafted})
        if self._pc_on:
            self._check_frontier_writable(active, K + 1)
        self._upload_dirty()
        self._rng, r = jax.random.split(self._rng)
        keys = jax.random.split(r, (K + 1) * Bm).reshape(Bm, K + 1, -1)
        logits, self.cache = self._chunk_prefill(
            self.params, self._put(toks), self.cache)
        n_acc_d, stop_d = verify_accept(
            logits, self._put(drafts), self._put(dlens),
            self._put(keys), self._put(temps))
        if self._devprof_on and self.devprof.should_sample(
                "spec_verify"):
            # dstpu: host-sync-ok: sampled devprof device-time
            # attribution — the device_get below syncs anyway; this
            # just brackets the verify sweep with a clock
            self.devprof.observe_device("spec_verify", n_acc_d)
        if traced_any:
            self.tracer.event("spec_verify", attrs={
                "active": len(active), "positions": K + 1})
        # dstpu: host-sync-ok: the ONE device→host transfer per verify
        # sweep (accepted lengths + stop tokens for the whole batch)
        n_acc, stop = jax.device_get((n_acc_d, stop_d))
        self._c_decode_syncs.inc()
        self._c_kdisp_paged.inc()   # the verify sweep IS a paged dispatch
        self._c_decode_steps.inc(K + 1)
        self._c_spec_sweeps.inc()
        if self._tel_on:
            self._g_spec_occ.set(len(active) / Bm)
        rejected = 0
        for b, s in active:
            a = int(n_acc[b])
            rejected += int(dlens[b]) - a
            self._c_spec_accepted.inc(a)
            self._c_spec_slots.inc()
            self._c_spec_emitted.inc(a + 1)
            self._h_spec_len.observe(a + 1)
            # KV rollback: the forward wrote K+1 positions and bumped
            # the device seq_lens structurally; only accepted+1 of them
            # (the re-fed token + accepted drafts) hold real history
            s.seq_len += a + 1
            if s.req.traced:
                self.tracer.event("spec_accept", s.req.req_id, b,
                                  attrs={"drafted": int(dlens[b]),
                                         "accepted": a})
            for j in range(a):
                self._append_token(b, int(drafts[b, j]))
                if self.slots[b] is None:    # finished mid-span:
                    break                    # rest is discard
            if self.slots[b] is not None:
                self._append_token(b, int(stop[b, a]))
        self._c_spec_rejected.inc(rejected)
        if rejected and traced_any:
            self.tracer.event("spec_rollback", attrs={
                "rejected": rejected})
        # every row was rewound below the structural seq_lens the
        # verify left on device — force the re-upload before the next
        # forward reads them
        self._lens_dirty = True

    def run(self, max_steps: int = 10_000) -> Dict[Any, List[int]]:
        """Drive until every submitted request completes."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("serving loop did not converge")
        return dict(self.finished)

    def drain_finished(self) -> Dict[Any, List[int]]:
        """Hand over and forget completed outputs (long-running servers
        call this instead of letting ``finished`` grow unboundedly)."""
        out, self.finished = self.finished, {}
        return out

    # --------------------------------------------------- introspection
    # (/statusz, /healthz and /requestz providers — registered on the
    # telemetry HTTP server when the config block carries http_port;
    # all three are also plain methods a fleet supervisor or test can
    # call in-process)
    def attach_watchdog(self, watchdog) -> None:
        """Feed ``/healthz`` from a :class:`~deepspeed_tpu.utils.
        watchdog.Watchdog`: readiness goes false the moment the
        watchdog fires, so a fleet probe drains traffic off a hung
        engine before the abort lands."""
        self._watchdog = watchdog
        if self.incident_mgr.enabled:
            # a watchdog fire is an incident class of its own: the
            # probe trips ONCE (latched — `fired` stays true for the
            # process's lifetime, and re-tripping every dedup window
            # would eat the max_bundles budget)
            tripped = []

            def _wd_probe():
                if watchdog.fired and not tripped:
                    tripped.append(True)
                    return "watchdog", {"phase": "watchdog_fired",
                                        **watchdog.health()}
                return None

            self.incident_mgr.add_probe(_wd_probe)
            # the probe alone only runs if the engine keeps stepping —
            # a genuinely hung scheduler thread (the case the watchdog
            # exists for) never reaches another tick, and an
            # abort_on_timeout fire kills the process right after
            # on_timeout.  Chaining the fire callback captures the
            # bundle from the WATCHDOG thread before any abort: safe
            # because the single writer has, by the fire's definition,
            # stopped stepping for timeout_s — worst case on a slow-
            # not-hung engine resuming mid-capture is one duplicate
            # bundle on a once-per-process path, vs losing the capture
            prev_timeout = watchdog.on_timeout

            def _on_timeout():
                try:
                    self.incident_mgr.evaluate()
                except Exception:
                    pass        # never mask the watchdog's own path
                if prev_timeout is not None:
                    prev_timeout()

            watchdog.on_timeout = _on_timeout

    def mesh_info(self) -> Dict[str, Any]:
        """The /statusz ``mesh`` block: is this replica an SPMD-sharded
        engine, and over what?  Axis names/sizes plus the device count
        it spans — a TP-sharded fleet is visibly sharded (``dstpu_top``
        renders the tp column from this)."""
        ms = self._mesh
        if ms is None:
            return {"sharded": False, "devices": 1, "axes": {},
                    "tp": 1, "ep": 1}
        axes = {a: int(s) for a, s in ms.sizes.items() if int(s) > 1}
        return {
            "sharded": any(s > 1 for s in axes.values()),
            "devices": int(ms.mesh.devices.size),
            "axes": axes,
            "tp": int(ms.size("model")),
            "ep": int(ms.size("expert")),
        }

    def statusz(self) -> Dict[str, Any]:
        """Live machine-readable engine snapshot: per-slot state,
        in-flight requests with phase and age, KV/prefix-cache pool
        occupancy and fragmentation, speculation acceptance, SLO
        attainment per tier, and the full metrics snapshot.  Assembled
        from host-side bookkeeping only — no device sync, safe to poll
        every second (``tools/dstpu_top.py`` does)."""
        now = time.perf_counter()
        slots: List[Dict[str, Any]] = []
        mapped_capacity = 0
        valid_tokens = 0
        for b, s in enumerate(self.slots):
            if s is None:
                slots.append({"slot": b, "state": "idle"})
                continue
            pages = int(np.sum(self._table_host[b] != self.trash_page))
            mapped_capacity += pages * self.page_size
            valid_tokens += self._valid_tokens(s)
            row: Dict[str, Any] = {
                "slot": b,
                "state": "prefill" if s.prefilling else "decode",
                "req": _req_key(s.req.req_id),
                "tier": s.req.tier,
                "prompt_tokens": len(s.req.tokens),
                "generated": len(s.generated),
                "max_new_tokens": s.req.max_new_tokens,
                "seq_len": s.seq_len,
                "pages": pages,
                "age_s": round(now - s.req.t_arrival, 3),
            }
            if s.prefilling:
                row["prefill_done"] = s.prefill_done
            slots.append(row)
        queue = [{"req": _req_key(r.req_id), "tier": r.tier,
                  "prompt_tokens": len(r.tokens),
                  "age_s": round(now - r.t_arrival, 3)}
                 for r in list(self.queue)[:32]]
        al = self.allocator
        usable = self.trash_page       # pool minus the reserved page
        live = usable - al.available
        spec_slots = int(self._c_spec_slots.value)
        cnt_hits = int(self._c_pc_hits.value)
        cnt_miss = int(self._c_pc_misses.value)
        pt = int(self._c_pc_prompt_tokens.value)
        from deepspeed_tpu.obs_wire import wire_stamp
        status: Dict[str, Any] = {
            "schema_version": 1,
            **wire_stamp(),
            "engine": type(self).__name__,
            "replica": self.replica_id,
            "weights_version": _req_key(self.weights_version),
            "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "uptime_s": round(now - self._t_start, 3),
            "last_step_age_s": (
                round(now - self._last_step_t, 3)
                if self._last_step_t is not None else None),
            "max_batch": self.max_batch,
            "active_slots": sum(1 for s in self.slots if s is not None),
            "slots": slots,
            "queue": {"depth": len(self.queue), "head": queue},
            "finished_pending_drain": len(self.finished),
            "kv": {
                "page_size": self.page_size,
                "pages_usable": usable,
                "pages_free": len(al.free),
                "pages_warm": len(al.pool),
                "pages_live": live,
                "utilization": round(live / max(usable, 1), 4),
                # internal fragmentation of the mapped working set:
                # the fraction of page capacity mapped into live slots
                # that holds no real KV yet (bucket padding + decode
                # headroom) — high values mean page_size is oversized
                # for the traffic
                "fragmentation": round(
                    1.0 - valid_tokens / mapped_capacity, 4)
                if mapped_capacity else 0.0,
            },
            "prefix_cache": {
                "enabled": self._pc_on,
                "warm_pool_pages": len(al.pool),
                "published_lifetime": al.published,
                "evicted_lifetime": al.evicted,
                "admission_hits": cnt_hits,
                "admission_misses": cnt_miss,
                "token_hit_rate": round(
                    self._c_pc_cached_tokens.value / pt, 4) if pt
                else 0.0,
            },
            "kv_tier": {
                "enabled": self._kvt_on,
                **(self._kv_pool.occupancy() if self._kv_pool is not None
                   else {}),
                "quantize_cold": self.kv_tier.quantize_cold
                if self._kvt_on else False,
                "quantized_resident": self._quant_resident,
                "demoted_lifetime": al.demoted,
                "promoted_lifetime": al.promoted,
                "promoting_pages": len(al.promoting),
                "promote_stall_s": round(
                    float(self._h_kvt_promote.sum), 6)
                if self._kvt_on else 0.0,
            },
            "speculative": {
                "enabled": self._spec_on,
                "verify_sweeps": int(self._c_spec_sweeps.value),
                "mean_accept_len": round(
                    self._c_spec_emitted.value / spec_slots, 4)
                if spec_slots else None,
            },
            "mesh": self.mesh_info(),
            "kernels": self._kernels.as_dict(),
            "history": {
                "enabled": self.history.enabled,
                "series": len(self.history.series_names()),
            },
            "incidents": self.incident_mgr.snapshot(),
            "devprof": self.devprof.statusz_block(),
            # the BOUND port (meaningful when http_port=0 asked for an
            # ephemeral bind): how a parent process that spawned this
            # replica learns where to scrape it
            "telemetry": {
                "http_port": self._tel_exporter.port
                if self._tel_exporter is not None else None,
            },
        }
        if self.comm_placement is not None:
            # quantized TP weight placement (comm.quantized_serving):
            # wire-byte ledger + worst per-leaf round-trip error, stamped
            # once at build by _record_comm_placement
            status["comm"] = dict(self.comm_placement)
        metrics = self.registry.snapshot()
        status["slo"] = self.slo_tracker.snapshot(now=now)
        # reuse the snapshot just taken — _robustness_status only
        # filters its counters, and /statusz is polled on an interval
        status["robustness"] = self._robustness_status(
            now, counters=metrics.get("counters", {}))
        status["metrics"] = metrics
        return status

    def _degraded_state(self, now: float) -> Tuple[bool, List[str]]:
        """Degraded = still serving, but shedding load or running with
        a tier disabled by repeated faults.  /healthz stays 200 (a
        degraded engine is exactly the one a router should KEEP
        probing) with ``{"degraded": true, "reasons": [...]}``; only a
        watchdog fire or shutdown turns readiness off (503)."""
        reasons: List[str] = []
        if self._last_shed_t is not None and \
                now - self._last_shed_t < _SHED_ACTIVE_WINDOW_S:
            reasons.append("load_shedding_active")
        if self._kv_pool is not None and \
                self._kv_pool.disabled is not None:
            reasons.append(
                f"kv_tier_disabled: {self._kv_pool.disabled}")
        return bool(reasons), reasons

    def _robustness_status(self, now: float,
                           counters: Optional[Dict[str, float]] = None
                           ) -> Dict[str, Any]:
        """The /statusz ``robustness`` block: shed/failed accounting,
        per-tier fault/retry/fallback counters, degraded state, and —
        when a fault plan is installed — the injection ledger the
        chaos soak reconciles against.  ``counters``: a registry
        snapshot's counter dict, when the caller already took one
        (statusz does — no second registry walk per poll)."""
        degraded, reasons = self._degraded_state(now)
        cnt = counters if counters is not None else ({}
            if not self._tel_on
            else self.registry.snapshot().get("counters", {}))
        out: Dict[str, Any] = {
            "degraded": degraded,
            "reasons": reasons,
            "shed_requests": self._n_shed,
            "shed_rate": round(
                self._n_shed / self._n_submitted, 4)
            if self._n_submitted else 0.0,
            "shed_by_reason": {k: v for k, v in
                               self._shed_by_reason.items() if v},
            "failed_requests": self._n_failed,
            "shed_queue_depth": self.shed_queue_depth,
            "shed_expired_deadline": self._shed_deadline,
            "kv_tier": {
                "fallback_events": self._n_kvt_fallbacks,
                "checksum_failures": self._n_kvt_checksum,
                "disabled": (self._kv_pool.disabled
                             if self._kv_pool is not None else None),
                "spill_failures": (self._kv_pool.spill_failures
                                   if self._kv_pool is not None else 0),
            },
            "io_retries": {
                k: int(v) for k, v in cnt.items()
                if k.endswith(("_io_retries", "_sync_fallbacks",
                               "_write_retries")) and v},
        }
        if self._fault_plan is not None:
            out["faults"] = self._fault_plan.snapshot()
        return out

    def healthz(self) -> Dict[str, Any]:
        """Liveness/readiness for a fleet supervisor probe.  ``ready``
        goes false after :meth:`shutdown` or once an attached
        watchdog has fired (the HTTP endpoint turns that into a 503)."""
        from deepspeed_tpu.obs_wire import wire_stamp
        now = time.perf_counter()
        h: Dict[str, Any] = {
            **wire_stamp(),
            "alive": True,
            "ready": not self._closed,
            "replica": self.replica_id,
            "uptime_s": round(now - self._t_start, 3),
            "last_step_age_s": (
                round(now - self._last_step_t, 3)
                if self._last_step_t is not None else None),
            "queue_depth": len(self.queue),
            "active_slots": sum(1 for s in self.slots if s is not None),
            "watchdog": None,
        }
        wd = self._watchdog
        if wd is not None:
            h["watchdog"] = wd.health()
            if wd.fired:
                h["ready"] = False
        # degraded ≠ unready: shedding or a disabled tier keeps the
        # 200 (the engine IS serving) and reports why it is limping —
        # the router's shed/fail-over signal, not a kill signal
        degraded, reasons = self._degraded_state(now)
        h["degraded"] = degraded
        h["reasons"] = reasons
        return h

    def requestz(self, req_id) -> Dict[str, Any]:
        """Drill into ONE request: its flight-recorder events (from the
        ring — a wrapped ring may have lost the oldest) plus its
        current disposition.  ``req_id`` matches on the string form, so
        the HTTP query ``/requestz?id=3`` finds integer id 3."""
        rid = str(req_id)
        events = []
        if self.tracer.enabled:
            events = [e for e in self.tracer.recorder.events()
                      if e[1] is not None and _req_key(e[1]) == rid]
        # list() snapshots: this runs on the HTTP serving thread while
        # the engine thread mutates queue/finished — iterating the live
        # containers would raise "mutated during iteration"
        in_queue = any(_req_key(r.req_id) == rid
                       for r in list(self.queue))
        slot = next((b for b, s in enumerate(list(self.slots))
                     if s is not None
                     and _req_key(s.req.req_id) == rid), None)
        finished = any(_req_key(k) == rid for k in list(self.finished))
        out: Dict[str, Any] = {
            "req": rid,
            "found": bool(events) or in_queue or slot is not None
            or finished,
            "state": ("finished" if finished
                      else "active" if slot is not None
                      else "queued" if in_queue
                      else "unknown"),
            "slot": slot,
            "tracing_enabled": self.tracer.enabled,
            "events": [event_to_dict(e) for e in events],
        }
        if events:
            from deepspeed_tpu.request_trace import request_breakdown

            rows = request_breakdown(events)["requests"]
            if rows:
                out["breakdown"] = next(iter(rows.values()))
        return out

    def historyz(self) -> Dict[str, Any]:
        """The ``/historyz`` document: every metric-history ring
        (multi-resolution time series sampled on the exporter tick)
        plus recent incident-bundle metadata — the machine-readable
        feed behind ``dstpu_top``'s sparklines and incident ticker.
        Host-side bookkeeping only, safe to poll."""
        from deepspeed_tpu.obs_wire import wire_stamp
        return {
            **wire_stamp(),
            "history": self.history.snapshot(),
            "incidents": self.incident_mgr.snapshot(),
        }

    def profilez(self, capture_s=None) -> Dict[str, Any]:
        """The ``/profilez`` document: devprof's statusz block (compile
        ledger totals, per-phase device seconds, MFU/MBU), and — when
        ``capture_s`` is given — an on-demand :mod:`jax.profiler` trace
        capture of that many seconds written under the tracer's
        ``dump_dir`` (clamped to ``devprof.capture_max_s``)."""
        return self.devprof.profilez(capture_s)

    def shutdown(self) -> None:
        """Idempotent teardown: final sink flush, then stop the
        telemetry/introspection HTTP server and join its thread — so
        back-to-back engine constructions on one fixed port (the test
        suite's pattern) never hit ``EADDRINUSE`` or leak the serving
        thread."""
        if self._closed:
            return
        self._closed = True
        if self._owns_fault_plan:
            faults_mod.clear_fault_plan(self._fault_plan)
        ex = self._tel_exporter
        if ex is not None:
            try:
                ex.maybe_export(force=True)
            except Exception:
                pass
            ex.close()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


def _shard_params_for_serving(params, specs_tree, mesh):
    """Place a serving param tree (bf16 or int8-quantized) on ``mesh``
    under the model's own TP/EP specs — int8 codes take the weight's
    spec, per-row group scales ride alongside (ref: module_inject's
    int8 + mp_size injection composing with TP)."""
    from deepspeed_tpu import zero as _zero
    from deepspeed_tpu.inference.quantized import shard_quantized

    return shard_quantized(params, _zero.resolve_specs(None, specs_tree),
                           mesh)


# below this, the exact path keeps a leaf: scales would outweigh the
# payload saved, and tiny leaves are the accuracy-critical ones (norm
# gains, biases)
_WIRE_MIN_ELEMS = 1024


def _quantized_shard_params(params, specs_tree, mesh, comm_cfg):
    """int8-wire variant of :func:`_shard_params_for_serving` (ref:
    ZeRO++ qwZ's quantized weight gather reused at serving time,
    arXiv:2306.10209): each float weight leaf is quantized ON THE HOST
    so the H2D upload that places the TP replica carries int8 codes +
    f32 scales instead of the full-precision image, then dequantized on
    device back to the leaf's own dtype under the leaf's own
    PartitionSpec (scales ride replicated — they are tiny).  Every
    quantized leaf is gated by ``comm_cfg.serving_rtol`` on its exact
    host-side round-trip error — a leaf the codec cannot represent
    within tolerance fails the BUILD, never silently serves degraded
    weights.  QuantizedTensor leaves (weight_dtype="int8" already
    shipped codes), non-float leaves, and sub-``_WIRE_MIN_ELEMS``
    leaves take the exact path.  Returns ``(placed, stats)``; the
    caller stamps ``stats`` onto the engine via
    :func:`_record_comm_placement`."""
    from jax.tree_util import keystr, tree_map_with_path

    from deepspeed_tpu import zero as _zero
    from deepspeed_tpu.comm.collectives import (dequantize_from_wire,
                                                quantize_for_wire_np)
    from deepspeed_tpu.inference.quantized import _is_qt, shard_quantized

    specs = _zero.resolve_specs(None, specs_tree)
    stats = {"leaves_quantized": 0, "leaves_exact": 0,
             "bytes_on_wire_int8": 0, "bytes_on_wire_f32": 0,
             "max_rel_err": 0.0,
             "serving_rtol": float(comm_cfg.serving_rtol)}

    def put(path, leaf, spec):
        a = None if _is_qt(leaf) else np.asarray(leaf)
        if a is None or a.dtype.kind != "f" or a.size < _WIRE_MIN_ELEMS:
            stats["leaves_exact"] += 1
            return shard_quantized(leaf, spec, mesh)
        q, s, dt = quantize_for_wire_np(a)
        af32 = a.astype(np.float32)
        deq_host = (q.astype(np.float32).reshape(s.size, -1)
                    * s[:, None]).reshape(a.shape)
        ref = float(np.abs(af32).max()) or 1.0
        rel = float(np.abs(deq_host - af32).max()) / ref
        if rel > comm_cfg.serving_rtol:
            raise ValueError(
                f"comm.quantized_serving: leaf {keystr(path)} "
                f"{a.shape} round-trips at rel err {rel:.3e} > "
                f"serving_rtol {comm_cfg.serving_rtol:g} — raise the "
                "tolerance or serve this model unquantized")
        stats["leaves_quantized"] += 1
        stats["bytes_on_wire_int8"] += q.nbytes + s.nbytes
        stats["bytes_on_wire_f32"] += a.size * 4
        stats["max_rel_err"] = max(stats["max_rel_err"], rel)
        # the H2D below is the wire this whole path exists for: int8
        # codes under the weight's spec + replicated scales, dequantized
        # device-side into the leaf's serving dtype
        q_dev = jax.device_put(q, mesh.sharding(spec))
        s_dev = jax.device_put(s, mesh.replicated())
        return jax.device_put(
            dequantize_from_wire(q_dev, s_dev, jnp.dtype(dt)),
            mesh.sharding(spec))

    placed = tree_map_with_path(put, params, specs, is_leaf=_is_qt)
    i8 = stats["bytes_on_wire_int8"]
    stats["compression_ratio"] = round(
        stats["bytes_on_wire_f32"] / i8, 4) if i8 else 0.0
    stats["max_rel_err"] = round(stats["max_rel_err"], 8)
    return placed, stats


def _record_comm_placement(eng: ServingEngine, stats: Dict[str, Any]):
    """Stamp quantized-placement stats onto a built engine: the
    /statusz ``comm`` block plus the ``comm_*`` metric family — the
    SAME names the training engine reports for its gradient wire, so
    one dashboard joins both sides of the shared int8 codec."""
    eng.comm_placement = dict(stats)
    r = eng.registry
    if not r.enabled:
        return
    r.counter(
        "comm_bytes_on_wire_int8",
        "bytes actually shipped on the quantized wire (int8 codes + "
        "f32 scales)").inc(stats["bytes_on_wire_int8"])
    r.counter(
        "comm_bytes_on_wire_f32",
        "bytes a flat f32 wire would have shipped for the same "
        "payload").inc(stats["bytes_on_wire_f32"])
    r.gauge(
        "comm_compression_ratio",
        "f32 wire bytes / quantized wire bytes").set(
        stats["compression_ratio"])
    r.gauge(
        "comm_serving_max_rel_err",
        "worst per-leaf round-trip error of the quantized weight "
        "placement (gated by comm.serving_rtol at build)").set(
        stats["max_rel_err"])


def _route_zero_inference(zero_inference, family: str, params, cfg,
                          weight_dtype, quant_group_size, mesh, kw):
    """Shared builder branch: a live ``zero_inference`` block routes to
    the weight-streamed engine (inference/zero_inference.py); returns
    None when the resident path should proceed."""
    from deepspeed_tpu.config import ZeroInferenceConfig

    zi = ZeroInferenceConfig.coerce(zero_inference)
    if not zi.enabled:
        return None
    from deepspeed_tpu.inference.zero_inference import (
        zero_inference_serving_engine)

    return zero_inference_serving_engine(
        params, cfg, zi, family=family, weight_dtype=weight_dtype,
        quant_group_size=quant_group_size, mesh=mesh, **kw)


def _resolve_kernels_for_builder(kernels, mesh):
    """Resolve the serving-kernel policy for a model builder, with the
    SAME sharding predicate the engine uses (any model/expert axis > 1
    demotes forced pallas — the kernels read the full page table per
    device).  The returned :class:`~deepspeed_tpu.inference.kernels.
    ServingKernelPolicy` is baked into the forward closures AND passed
    through as the engine's ``kernels`` kwarg, so there is exactly one
    resolution per build."""
    active = mesh is not None and any(
        mesh.size(ax) > 1 for ax in ("model", "expert"))
    return resolve_serving_kernels(
        kernels, tp=active,
        interpret=jax.default_backend() != "tpu")


def llama_serving_engine(params, cfg, weight_dtype: str = "bfloat16",
                         quant_group_size: int = 128, mesh=None,
                         zero_inference=None, **kw) -> ServingEngine:
    """ServingEngine over models/llama.py's paged forward.

    ``weight_dtype="int8"``: weight-only quantized serving (ref:
    init_inference(dtype=int8)) — int8 codes + group scales in HBM
    (half the bf16 weight residency), dequant traced into the forward.

    ``mesh``: TP-sharded serving (ref: replace_module.py TP injection) —
    params shard Megatron-style over the ``model`` axis, the KV cache
    shards its head axis, and both jits run under GSPMD with the psum
    after wo/w2 inserted by XLA.  The mesh is published ambient so the
    forward picks its TP-compatible attention paths.

    ``zero_inference``: a :class:`~deepspeed_tpu.config.
    ZeroInferenceConfig` (or its dict form) routes to the weight-
    streamed ZeRO-Inference engine — layer weights live on a host/NVMe
    tier and stream through a double-buffered HBM working set, so the
    served model's weight image may exceed HBM.
    """
    from deepspeed_tpu.models import llama

    zi_engine = _route_zero_inference(
        zero_inference, "llama", params, cfg, weight_dtype,
        quant_group_size, mesh, kw)
    if zi_engine is not None:
        return zi_engine

    # tp baked in at BUILD time: the compiled paths must not re-read the
    # mutable ambient mesh on a later retrace (a cleared/replaced global
    # would silently re-enable pallas kernels over the sharded cache)
    tp = mesh is not None and mesh.size("model") > 1
    # the kernel policy resolves HERE too (config + env, once) and the
    # same ServingKernelPolicy passes through to the engine, so the
    # paged_kernel the closures bake and the policy /statusz reports
    # are one object, not two resolutions that could drift
    kw["kernels"] = _resolve_kernels_for_builder(kw.get("kernels"), mesh)
    pk = kw["kernels"].paged_attention

    def step(params, tokens, cache):
        return llama.forward_paged(params, tokens, cfg, cache, tp=tp,
                                   paged_kernel=pk)

    def chunk_step(params, tokens, cache):
        return llama.forward_paged(params, tokens, cfg, cache,
                                   continuation=True, tp=tp,
                                   paged_kernel=pk)

    if weight_dtype != "bfloat16":
        from deepspeed_tpu.inference.quantized import quantize_for_inference

        # raises on anything but "int8" — never silently serve
        # unquantized; stacked [L, d] norm gains stay exact
        params, step, chunk_step = quantize_for_inference(
            params, step, chunk_step, weight_dtype=weight_dtype,
            group_size=quant_group_size,
            skip_paths=("attn_norm", "mlp_norm", "final_norm"))

    comm_stats = None
    if tp:
        cc = CommConfig.coerce(kw.get("comm"))
        if cc.quantized_serving:
            # the training int8 wire reused for replica placement: H2D
            # ships codes + scales, gated by serving_rtol per leaf
            params, comm_stats = _quantized_shard_params(
                params, llama.param_specs(cfg), mesh, cc)
        else:
            params = _shard_params_for_serving(
                params, llama.param_specs(cfg), mesh)

    eng = ServingEngine(
        params, step, step, n_layers=cfg.n_layers, n_kv=cfg.n_kv_heads,
        head_dim=cfg.head_dim, chunk_prefill_fn=chunk_step, mesh=mesh,
        **kw)
    if comm_stats is not None:
        _record_comm_placement(eng, comm_stats)
    return eng


def mixtral_serving_engine(params, cfg, weight_dtype: str = "bfloat16",
                           quant_group_size: int = 128, mesh=None,
                           zero_inference=None, **kw) -> ServingEngine:
    """ServingEngine over models/mixtral.py's paged MoE forward (ref:
    DeepSpeed-MoE inference serving, deepspeed/inference/engine.py) —
    iteration-level scheduling, paged KV, split-fuse and decode chunking
    all apply to the MoE model unchanged.  ``zero_inference`` streams
    the expert stacks (the dominant MoE weight bytes) from a host/NVMe
    tier, like the llama builder."""
    from deepspeed_tpu.models import mixtral

    zi_engine = _route_zero_inference(
        zero_inference, "mixtral", params, cfg, weight_dtype,
        quant_group_size, mesh, kw)
    if zi_engine is not None:
        return zi_engine

    # sharded MoE serving (ref: DeepSpeed-MoE inference — expert
    # parallelism, optionally composed with Megatron TP): the stacked
    # [L, E, ...] expert FFNs shard over the expert axis (XLA inserts
    # the expert psum at the weighted combine), attention shards
    # Megatron-style over the model axis, and the KV cache's head axis
    # follows it.  The model's own param_specs is the single source of
    # truth for which leaves shard; unused axes are size-1 no-ops.
    sharded = mesh is not None and any(
        mesh.size(ax) > 1 for ax in ("model", "expert"))
    if sharded and cfg.num_experts % mesh.size("expert"):
        raise ValueError(
            f"num_experts {cfg.num_experts} not divisible by "
            f"expert-axis size {mesh.size('expert')}")

    kw["kernels"] = _resolve_kernels_for_builder(kw.get("kernels"), mesh)
    pk = kw["kernels"].paged_attention

    def step(params, tokens, cache):
        return mixtral.forward_paged(params, tokens, cfg, cache,
                                     tp=sharded, paged_kernel=pk)

    def chunk_step(params, tokens, cache):
        return mixtral.forward_paged(params, tokens, cfg, cache,
                                     continuation=True, tp=sharded,
                                     paged_kernel=pk)

    if weight_dtype != "bfloat16":
        from deepspeed_tpu.inference.quantized import quantize_for_inference

        # the router stays exact (int8 gate logits could flip a
        # near-tied top-k choice) and so do the stacked norm gains
        params, step, chunk_step = quantize_for_inference(
            params, step, chunk_step, weight_dtype=weight_dtype,
            group_size=quant_group_size,
            skip_paths=("gate", "attn_norm", "mlp_norm", "final_norm"))

    comm_stats = None
    if sharded:
        # expert FFNs shard over the expert axis, attention
        # Megatron-style over model (ref: DeepSpeed-MoE inference)
        cc = CommConfig.coerce(kw.get("comm"))
        if cc.quantized_serving:
            params, comm_stats = _quantized_shard_params(
                params, mixtral.param_specs(cfg), mesh, cc)
        else:
            params = _shard_params_for_serving(
                params, mixtral.param_specs(cfg), mesh)

    eng = ServingEngine(
        params, step, step, n_layers=cfg.n_layers, n_kv=cfg.n_kv_heads,
        head_dim=cfg.head_dim, chunk_prefill_fn=chunk_step, mesh=mesh,
        **kw)
    if comm_stats is not None:
        _record_comm_placement(eng, comm_stats)
    return eng


def gpt2_serving_engine(params, cfg, weight_dtype: str = "bfloat16",
                        quant_group_size: int = 128, mesh=None,
                        **kw) -> ServingEngine:
    """ServingEngine over models/gpt2.py's paged forward (ref: the
    reference serves GPT-2 through kernel injection,
    deepspeed/module_inject/containers/gpt2.py)."""
    from deepspeed_tpu.models import gpt2

    # TP baked in at build time, like the llama builder: the compiled
    # paths must not re-read the mutable ambient mesh on a retrace
    tp = mesh is not None and mesh.size("model") > 1
    if mesh is not None and mesh.size("expert") > 1:
        raise ValueError(
            "GPT-2 has no expert-parallel dimension — shard over the "
            "model axis instead")
    max_seq = kw.get("max_seq", 256)
    if max_seq > cfg.max_seq_len:
        # learned positions are HARD-bounded by the wpe table (unlike
        # RoPE); past it jax's clamping gather would silently reuse the
        # last position embedding
        raise ValueError(
            f"max_seq {max_seq} exceeds the learned position table "
            f"(cfg.max_seq_len={cfg.max_seq_len})")

    kw["kernels"] = _resolve_kernels_for_builder(kw.get("kernels"), mesh)
    pk = kw["kernels"].paged_attention

    def step(params, tokens, cache):
        return gpt2.forward_paged(params, tokens, cfg, cache, tp=tp,
                                  paged_kernel=pk)

    def chunk_step(params, tokens, cache):
        return gpt2.forward_paged(params, tokens, cfg, cache,
                                  continuation=True, tp=tp,
                                  paged_kernel=pk)

    if weight_dtype != "bfloat16":
        from deepspeed_tpu.inference.quantized import quantize_for_inference

        # only the matmul weights quantize: stacked biases/norm
        # vectors and the (tiny, accuracy-critical) position table stay
        # exact
        params, step, chunk_step = quantize_for_inference(
            params, step, chunk_step, weight_dtype=weight_dtype,
            group_size=quant_group_size,
            skip_paths=("ln1_w", "ln1_b", "ln2_w", "ln2_b", "qkv_b",
                        "proj_b", "fc_b", "out_b", "lnf_w", "lnf_b",
                        "wpe"))

    comm_stats = None
    if tp:
        # ref: module_inject/containers/gpt2.py — fused qkv shards its
        # output dim, proj/out row-parallel; biases on sharded outputs
        # follow the column split
        cc = CommConfig.coerce(kw.get("comm"))
        if cc.quantized_serving:
            params, comm_stats = _quantized_shard_params(
                params, gpt2.param_specs(cfg), mesh, cc)
        else:
            params = _shard_params_for_serving(
                params, gpt2.param_specs(cfg), mesh)

    eng = ServingEngine(
        params, step, step, n_layers=cfg.n_layers, n_kv=cfg.n_kv_heads,
        head_dim=cfg.head_dim, chunk_prefill_fn=chunk_step, mesh=mesh,
        **kw)
    if comm_stats is not None:
        _record_comm_placement(eng, comm_stats)
    return eng


def serving_engine(params, cfg, **kw):
    """Model registry for serving: dispatch on the config type (ref:
    init_inference accepting any supported model).  Decoder LMs get the
    paged continuous-batching engine; encoder families get the
    lot-batching :class:`~deepspeed_tpu.inference.encoder_serving.
    EncoderServingEngine` (same submit/run surface, no decode loop)."""
    from deepspeed_tpu.models.bert import BertConfig
    from deepspeed_tpu.models.cnn import CNNConfig
    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.models.mixtral import MixtralConfig

    if isinstance(cfg, MixtralConfig):
        return mixtral_serving_engine(params, cfg, **kw)
    if isinstance(cfg, LlamaConfig):
        return llama_serving_engine(params, cfg, **kw)
    zi = kw.pop("zero_inference", None)
    if zi is not None:
        from deepspeed_tpu.config import ZeroInferenceConfig

        if ZeroInferenceConfig.coerce(zi).enabled:
            # weight streaming needs the per-layer paged factoring,
            # which the layered decoder families provide (llama +
            # mixtral); fail loudly, never silently serve resident
            raise NotImplementedError(
                f"zero_inference streaming is not wired for "
                f"{type(cfg).__name__} — supported: LlamaConfig, "
                "MixtralConfig")
    if isinstance(cfg, GPT2Config):
        return gpt2_serving_engine(params, cfg, **kw)
    # per-request tracing lives in the paged-KV decode scheduler's
    # lifecycle (queued/admitted/first-token/finish edges); the encoder
    # engines are fixed-shape batch scorers with no such lifecycle —
    # the block is accepted and unused there, never an error.  The
    # history/incidents/devprof blocks ride the same lifecycle
    # (exporter tick hooks + flight-recorder triggers + the compile
    # sentinel's steady-state boundary at first token) and are likewise
    # accepted and unused on the encoder path.
    kw.pop("tracing", None)
    kw.pop("history", None)
    kw.pop("incidents", None)
    kw.pop("devprof", None)
    kn = kw.pop("kernels", None)
    if kn is not None:
        from deepspeed_tpu.config import KernelsConfig

        k = KernelsConfig.coerce(kn)
        if k.paged_attention != "auto" or k.fused_sampling != "auto":
            # the kernels block names paged-attention/sampling
            # dispatches; encoder engines have neither a paged cache
            # nor a decode sampler — fail loudly, never silently
            # serve a different kernel than the one pinned
            raise NotImplementedError(
                f"the kernels block pins paged-KV decode kernels, "
                f"which {type(cfg).__name__} does not serve — "
                "supported: LlamaConfig, MixtralConfig, GPT2Config")
    cm = kw.pop("comm", None)
    if cm is not None and CommConfig.coerce(cm).quantized_serving:
        # quantized placement rides the TP replica upload / ZI layer
        # stream, neither of which the encoder engines have — fail
        # loudly, never silently place full-precision weights under a
        # config that pinned the int8 wire
        raise NotImplementedError(
            f"comm.quantized_serving quantizes TP replica weight "
            f"placement, which {type(cfg).__name__} does not serve — "
            "supported: LlamaConfig, MixtralConfig, GPT2Config")
    sp = kw.pop("speculative", None)
    kw.pop("drafter", None)
    if sp is not None and SpeculativeConfig.coerce(sp).enabled:
        # speculation lives in the paged-KV decode loop; the encoder
        # engines have no decode loop to speculate — fail loudly,
        # never silently serve unaccelerated
        raise NotImplementedError(
            f"speculative decoding needs the paged-KV decode path, "
            f"which {type(cfg).__name__} does not serve — supported: "
            "LlamaConfig, MixtralConfig, GPT2Config")
    so = kw.pop("slo", None)
    if so is not None and SLOConfig.coerce(so).enabled:
        # SLO classification hangs off the decode scheduler's lifecycle
        # (submit/first-token/finish edges); the encoder engines score
        # fixed-shape lots with no such lifecycle — fail loudly, never
        # silently drop a latency objective
        raise NotImplementedError(
            f"the slo block needs the paged-KV decode path, which "
            f"{type(cfg).__name__} does not serve — supported: "
            "LlamaConfig, MixtralConfig, GPT2Config")
    pc = kw.pop("prefix_cache", None)
    if pc is not None and PrefixCacheConfig.coerce(pc).enabled:
        # prefix caching lives in the paged-KV decode scheduler; the
        # encoder engines are fixed-shape batch scorers with no pages
        # to share — fail loudly, never silently serve uncached
        raise NotImplementedError(
            f"prefix_cache needs the paged-KV decode path, which "
            f"{type(cfg).__name__} does not serve — supported: "
            "LlamaConfig, MixtralConfig, GPT2Config")
    kvt = kw.pop("kv_tier", None)
    if kvt is not None and KVTierConfig.coerce(kvt).enabled:
        # the tiered KV cache spills PAGES of the prefix pool; encoder
        # families have neither — fail loudly, never silently drop the
        # capacity the block was written for
        raise NotImplementedError(
            f"kv_tier needs the paged-KV decode path, which "
            f"{type(cfg).__name__} does not serve — supported: "
            "LlamaConfig, MixtralConfig, GPT2Config")
    fl = kw.pop("faults", None)
    if fl is not None and (isinstance(fl, FaultPlan)
                           or FaultsConfig.coerce(fl).enabled):
        # fault injection exercises the paged scheduler's isolation/
        # shed/fallback machinery; the encoder engines have none of it
        # — fail loudly, never silently skip the chaos the block asked
        # for
        raise NotImplementedError(
            f"the faults block needs the paged-KV decode path, which "
            f"{type(cfg).__name__} does not serve — supported: "
            "LlamaConfig, MixtralConfig, GPT2Config")
    if kw.pop("shed_queue_depth", 0) or kw.pop("shed_expired_deadline",
                                               False):
        raise NotImplementedError(
            f"load shedding lives in the paged-KV admission path, "
            f"which {type(cfg).__name__} does not serve — supported: "
            "LlamaConfig, MixtralConfig, GPT2Config")
    if isinstance(cfg, BertConfig):
        from deepspeed_tpu.inference.encoder_serving import (
            bert_serving_engine)

        return bert_serving_engine(params, cfg, **kw)
    if isinstance(cfg, CNNConfig):
        from deepspeed_tpu.inference.encoder_serving import (
            CNNServingEngine)

        for unsupported in ("mesh", "weight_dtype"):
            if kw.get(unsupported) not in (None, "bfloat16"):
                raise NotImplementedError(
                    f"CNN serving does not support {unsupported!r} — "
                    "it is a fixed-shape batched scorer")
            kw.pop(unsupported, None)
        return CNNServingEngine(params, cfg=cfg, **kw)
    raise TypeError(
        f"no serving path for config type {type(cfg).__name__}; "
        "supported: LlamaConfig, MixtralConfig, GPT2Config, BertConfig, "
        "CNNConfig")
