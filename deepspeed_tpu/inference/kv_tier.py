"""Tiered KV cache: host/NVMe spill pool for demoted prefix-cache pages
(ref: ZeRO-Infinity tiering, arXiv:2104.07857, and ZeRO-Offload host
staging, arXiv:2101.06840 — the weight-streaming playbook PR 1 built
`TierLayerReader` on, re-targeted at KV pages).

The paged prefix cache (PR 3) keeps published refcount-0 pages warm in
HBM until allocation pressure reclaims them; before this module,
reclaim meant DROP — the next prompt matching that span pays a full
re-prefill.  :class:`KVTierPool` gives the allocator somewhere cheaper
to put cold pages instead:

    HBM warm pool ──demote──▶ host pool ──spill──▶ NVMe ──▶ drop
         ▲                                │
         └──────────── promote ◀──────────┘

- **Demote** (eviction pressure or the ``demote_watermark`` sweep):
  the page's KV — one ``[L, KV, ps, Dh]`` array pair across the layer
  stack — is copied device→host and indexed under its content key.
  ``quantize_cold`` stores int8 codes + per-token-row f32 scales
  (~2x the pages per byte); off by default, keeping the spill path
  bit-exact.
- **Spill**: when the host pool overflows ``host_pool_bytes``, the
  OLDEST host entries cascade to per-page files under ``nvme_dir``
  through the aio pool (:mod:`deepspeed_tpu.io.aio`); with no
  ``nvme_dir`` (or past ``nvme_pool_bytes``) the oldest entries drop.
- **Promote**: an admission matching a demoted span allocates fresh
  HBM pages and streams the payload back through
  :class:`~deepspeed_tpu.param_stream.TierPageReader` — the pool
  implements the ``_Tier`` read interface (``get_submit`` /
  ``reads_pending`` / ``fence_reads`` / ``next_read_slot``), serving
  host entries as zero-copy arrays and NVMe entries as alternating-slot
  aio reads, so one promotion's group ``g+1`` reads overlap group
  ``g``'s dequant + H2D upload.

Quantization error contract (``quantize_cold``): symmetric per-row int8
over the head dim — scale = rowmax(|x|)/127, code = round(x/scale) — so
the dequantized page differs from the original by at most
``rowmax(|x|) * KV_TIER_QUANT_RTOL`` elementwise (one half quantization
step, plus the bf16 cast the cache dtype already imposes).  Tests gate
on exactly this bound.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu import faults as _faults
from deepspeed_tpu.faults import ChecksumError, retry_with_backoff
from deepspeed_tpu.inference.prefix_cache import TierEntry, key_hex
from deepspeed_tpu.utils.logging import logger

# per-element bound of the int8 cold-page codec, RELATIVE to the row's
# max |value| (the scale denominator): half a quantization step
KV_TIER_QUANT_RTOL = 0.5 / 127.0


def _crc(arr: np.ndarray) -> int:
    """crc32 of an array's raw bytes.  Extension dtypes (bfloat16,
    numpy type char 'E') refuse the buffer protocol, so checksum a
    uint8 VIEW — same bytes, no copy."""
    return zlib.crc32(np.ascontiguousarray(arr).view(np.uint8))


# ------------------------------------------------------------ int8 codec
def quantize_page(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric int8 over the last (head) dim: x [..., Dh] float →
    (codes int8 [..., Dh], scales f32 [..., 1]).  All-zero rows take
    scale 1.0 so the codec is exact on them."""
    x32 = np.asarray(x, np.float32)
    amax = np.abs(x32).max(axis=-1, keepdims=True)
    scale = amax / 127.0
    scale[scale == 0.0] = 1.0
    codes = np.clip(np.rint(x32 / scale), -127, 127).astype(np.int8)
    return codes, scale.astype(np.float32)


def dequantize_page(codes: np.ndarray, scale: np.ndarray,
                    dtype) -> np.ndarray:
    """Inverse of :func:`quantize_page`, cast back to the page dtype."""
    return (codes.astype(np.float32) * scale).astype(dtype)


def encode_entry(key: bytes, k: np.ndarray, v: np.ndarray, *,
                 quantize: bool, page_dtype, tick: int = 0) -> TierEntry:
    """Serialize one page's (k, v) into a host-resident
    :class:`~deepspeed_tpu.inference.prefix_cache.TierEntry`: the
    spill tier's demote path and the cross-replica KV fabric's export
    path share exactly this encoding (same buffer naming, same
    per-buffer crc32 recorded now and verified when a promotion — or a
    migrated admission on another replica — decodes the payload
    back)."""
    hexk = key_hex(key)
    if quantize:
        kq, ks = quantize_page(k)
        vq, vs = quantize_page(v)
        data = (kq, ks, vq, vs)
    else:
        data = (np.ascontiguousarray(k), np.ascontiguousarray(v))
    bufs = tuple((f"kv_{hexk}_{i}", tuple(b.shape), str(b.dtype))
                 for i, b in enumerate(data))
    sums = tuple(_crc(b) for b in data)
    return TierEntry(
        key=key, location="host", quantized=quantize,
        dtype=str(np.dtype(page_dtype)), buffers=bufs,
        nbytes=int(sum(b.nbytes for b in data)), data=data,
        tick=tick, checksums=sums)


def encode_prequantized_entry(key: bytes, kq: np.ndarray, ks: np.ndarray,
                              vq: np.ndarray, vs: np.ndarray, *,
                              page_dtype, tick: int = 0) -> TierEntry:
    """Serialize a page whose payload is ALREADY the int8 codec's
    (codes, scales) — the quantized-resident serving path demotes the
    device's code/scale planes verbatim, so no dequantize/requantize
    round-trip (and no second rounding) ever touches the data.  Buffer
    naming and checksums match :func:`encode_entry`'s quantized layout
    exactly: a prequantized demote and a host-side quantize of the
    same values produce interchangeable entries."""
    hexk = key_hex(key)
    data = tuple(np.ascontiguousarray(b) for b in (kq, ks, vq, vs))
    bufs = tuple((f"kv_{hexk}_{i}", tuple(b.shape), str(b.dtype))
                 for i, b in enumerate(data))
    sums = tuple(_crc(b) for b in data)
    return TierEntry(
        key=key, location="host", quantized=True,
        dtype=str(np.dtype(page_dtype)), buffers=bufs,
        nbytes=int(sum(b.nbytes for b in data)), data=data,
        tick=tick, checksums=sums)


# ------------------------------------------------- NVMe read/write legs
class _KVNvmeChannel:
    """Alternating-slot aio READ channel over per-page spill files,
    plus a blocking write leg for the spill cascade.

    Unlike :class:`~deepspeed_tpu.infinity._NvmeTier` (per-leaf files
    opened once and held for the engine's lifetime), spill files come
    and go with cache churn — fds open per batch and close at the
    fence, so a long-lived server never accumulates one fd per page it
    ever demoted."""

    def __init__(self, path: str, n_threads: int = 4, retries: int = 2,
                 backoff_s: float = 0.05, on_retry=None):
        from deepspeed_tpu.io.aio import AioHandle

        os.makedirs(path, exist_ok=True)
        self.dir = path
        self.rpools = [AioHandle(n_threads), AioHandle(n_threads)]
        self.rslot = 0
        self._rfds: List[List[int]] = [[], []]
        self._wpool = AioHandle(n_threads)
        # bounded spill-write retry (transient aio errors must not turn
        # a demotion into a dropped page on the first hiccup)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._on_retry = on_retry

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name + ".bin")

    # ---------------------------------------------------------- reads
    def submit_read(self, name: str, buf: np.ndarray) -> None:
        pool = self.rpools[self.rslot]
        fd = pool.open(self._path(name))
        pool.pread(fd, buf, 0)
        self._rfds[self.rslot].append(fd)

    def reads_pending(self) -> int:
        return self.rpools[self.rslot].pending()

    def fence_reads(self) -> None:
        pool = self.rpools[self.rslot]
        errs = pool.wait()
        for fd in self._rfds[self.rslot]:
            pool.close(fd)
        self._rfds[self.rslot] = []
        if errs:
            raise IOError(f"{errs} KV-tier NVMe reads failed")

    def next_read_slot(self) -> None:
        self.rslot ^= 1

    def fence_all_reads(self) -> None:
        """Drain BOTH slots (promotion cancel/abandon: the aio reads
        target host buffers the caller is about to drop).  Read errors
        are deliberately IGNORED here — every caller is abandoning the
        transfer, and an error raised mid-cancel would leave the
        channel/pin/quarantine state latched forever (the hang this
        drain exists to prevent)."""
        for s in (0, 1):
            pool = self.rpools[s]
            pool.wait()
            for fd in self._rfds[s]:
                pool.close(fd)
            self._rfds[s] = []
        self.rslot = 0

    # --------------------------------------------------------- writes
    def write(self, name: str, buf: np.ndarray) -> None:
        """Blocking spill write (demote is already the slow path),
        retried with backoff on transient errors; the LAST failure
        propagates and the caller degrades (the entry drops instead
        of spilling — correctness preserved, capacity lost)."""
        def attempt():
            fd = self._wpool.open(self._path(name), write=True)
            try:
                self._wpool.pwrite(fd, buf, 0)
                errs = self._wpool.wait()
            finally:
                self._wpool.close(fd)
            if errs:
                raise IOError(f"KV-tier NVMe write of {name} failed")

        retry_with_backoff(attempt, attempts=self.retries,
                           backoff_s=self.backoff_s,
                           on_retry=self._on_retry)

    def unlink(self, name: str) -> None:
        try:
            os.remove(self._path(name))
        except OSError:
            pass


class KVTierPool:
    """Host + NVMe spill pool for demoted KV pages, content-addressed
    by the same chained page keys as the HBM prefix cache.

    One pool per engine; the engine installs it as
    ``PageAllocator.spill`` so the allocator's chain walk
    (``lookup_tiered``) treats demoted spans as cache hits, and as
    ``demote_hook`` so eviction captures the page instead of dropping
    it.  The pool doubles as the ``_Tier`` read backend of
    :class:`~deepspeed_tpu.param_stream.TierPageReader` — ONE promotion
    streams through it at a time (the engine serializes admissions with
    tier hits), so the alternating aio read slots stay coherent.

    Entries pinned via :meth:`pin` (an in-flight promotion's keys) are
    exempt from the spill/drop cascade: a concurrent demotion must not
    delete a file the promotion's aio reads are about to land from.
    """

    def __init__(self, cfg, page_shape: Sequence[int], page_dtype,
                 registry=None):
        self.cfg = cfg
        self.page_shape = tuple(int(s) for s in page_shape)  # (L,KV,ps,Dh)
        self.page_dtype = np.dtype(page_dtype)
        self.entries: Dict[bytes, TierEntry] = {}
        self._tick = 0
        self.host_bytes = 0
        self.nvme_bytes = 0
        self._pinned: Dict[bytes, int] = {}   # key -> pin count
        self._host_n = 0
        self._nvme_n = 0
        # age order per location (oldest first; touch() refreshes):
        # the cascade pops victims in O(pinned-skips), not O(entries) —
        # a 64 GiB host pool holds ~65k cold pages and a linear scan
        # per displaced entry would go quadratic under churn
        self._order: Dict[str, "collections.OrderedDict"] = {
            "host": collections.OrderedDict(),
            "nvme": collections.OrderedDict()}
        # degraded state: a circuit breaker (the engine calls
        # :meth:`disable` after repeated promote failures) turns the
        # pool inert — lookups miss, demotes become plain evictions —
        # without touching entries an in-flight promotion still reads
        self.disabled: Optional[str] = None
        # write-path degradation accounting
        self.spill_failures = 0
        self.write_retries = 0

        def _note_write_retry(_a, _e):
            self.write_retries += 1
            self._c_write_retries.inc()

        self._nvme: Optional[_KVNvmeChannel] = None
        if cfg.nvme_dir:
            self._nvme = _KVNvmeChannel(
                cfg.nvme_dir, n_threads=cfg.aio_threads,
                retries=getattr(cfg, "io_retries", 2),
                backoff_s=getattr(cfg, "io_retry_backoff_s", 0.05),
                on_retry=_note_write_retry)
        # cooperative aio priority (set by the ZI engine when KV
        # promotion shares the disk with layer-weight streams)
        self._prio_group = None
        self._prio = 0
        # lifetime accounting
        self.spilled_pages = 0
        self.dropped_pages = 0
        if registry is None or not registry.enabled:
            from deepspeed_tpu.telemetry import NULL_METRIC

            self._c_spill_bytes = self._c_dropped = NULL_METRIC
            self._g_host = self._g_host_b = NULL_METRIC
            self._g_nvme = self._g_nvme_b = NULL_METRIC
            self._c_write_retries = self._c_spill_fail = NULL_METRIC
        else:
            self._c_spill_bytes = registry.counter(
                "kv_tier_spilled_bytes",
                "bytes cascaded host pool -> NVMe")
            self._c_dropped = registry.counter(
                "kv_tier_dropped_pages",
                "demoted pages dropped off the end of the tier "
                "cascade (no capacity left anywhere)")
            self._g_host = registry.gauge(
                "kv_tier_host_pages", "demoted pages host-resident")
            self._g_host_b = registry.gauge(
                "kv_tier_host_bytes", "host-pool bytes in use")
            self._g_nvme = registry.gauge(
                "kv_tier_nvme_pages", "demoted pages NVMe-resident")
            self._g_nvme_b = registry.gauge(
                "kv_tier_nvme_bytes", "NVMe spill bytes in use")
            self._c_write_retries = registry.counter(
                "kv_tier_write_retries",
                "spill writes retried after a transient aio error")
            self._c_spill_fail = registry.counter(
                "kv_tier_spill_failures",
                "spill writes that exhausted their retries (the entry "
                "dropped instead of spilling — capacity degradation, "
                "never incorrectness)")

    # ------------------------------------------------------- accounting
    @property
    def uses_aio(self) -> bool:
        return self._nvme is not None

    def _counts(self) -> Tuple[int, int]:
        # maintained incrementally like the byte totals: gauges refresh
        # on every demote/spill/discard, and an O(entries) scan there
        # would make batch sweeps quadratic in pool size
        return self._host_n, self._nvme_n

    def _refresh_gauges(self) -> None:
        h, n = self._counts()
        self._g_host.set(h)
        self._g_host_b.set(self.host_bytes)
        self._g_nvme.set(n)
        self._g_nvme_b.set(self.nvme_bytes)

    def occupancy(self) -> Dict[str, int]:
        h, n = self._counts()
        return {"host_pages": h, "host_bytes": int(self.host_bytes),
                "nvme_pages": n, "nvme_bytes": int(self.nvme_bytes),
                "spilled_pages": int(self.spilled_pages),
                "dropped_pages": int(self.dropped_pages),
                "spill_failures": int(self.spill_failures),
                "write_retries": int(self.write_retries),
                "disabled": self.disabled}

    # --------------------------------------------------- degraded state
    def disable(self, reason: str) -> None:
        """Circuit-break the tier: lookups miss (``has`` → False) and
        demotes become plain evictions, while entries stay intact for
        any promotion already streaming them.  Idempotent; surfaced by
        ``/healthz`` as a degraded reason."""
        if self.disabled is None:
            self.disabled = str(reason)
            logger.warning("kv_tier: tier DISABLED (%s) — demotes "
                           "become evictions, tier hits become misses",
                           reason)

    # --------------------------------------------------------- priority
    def set_priority(self, group, priority: int = 0) -> None:
        """Join an :class:`~deepspeed_tpu.io.aio.AioPriorityGroup`:
        promotion submission defers while a higher-priority member
        (e.g. the ZI layer-weight stream) has reads in flight."""
        self._prio_group = group
        self._prio = int(priority)
        if group is not None and self._nvme is not None:
            group.register(self._nvme.reads_pending, self._prio)

    def may_submit(self) -> bool:
        """False while a higher-priority aio user is mid-flight — the
        engine then defers the promotion presubmit (bounded: its
        deferral cap guarantees eventual submission)."""
        return self._prio_group is None or \
            not self._prio_group.busy_above(self._prio)

    # ------------------------------------------------------------ index
    def has(self, key: bytes) -> bool:
        return self.disabled is None and key in self.entries

    def location(self, key: bytes) -> Optional[str]:
        e = self.entries.get(key)
        return e.location if e is not None else None

    def touch(self, key: bytes) -> Optional[str]:
        """Refresh an entry's cascade age (a re-demote of a span whose
        payload is still spilled is free — no copy, no write)."""
        e = self.entries.get(key)
        if e is None:
            return None
        self._tick += 1
        e.tick = self._tick
        self._order[e.location].move_to_end(key)
        return e.location

    def pin(self, keys) -> None:
        """Refcounted: two concurrent promotions sharing a key must
        BOTH finish before the cascade may touch it — the first
        completion must not strip the other's protection."""
        for k in keys:
            self._pinned[k] = self._pinned.get(k, 0) + 1

    def unpin(self, keys) -> None:
        for k in keys:
            n = self._pinned.get(k, 0) - 1
            if n <= 0:
                self._pinned.pop(k, None)
            else:
                self._pinned[k] = n

    # ----------------------------------------------------------- demote
    def _encode(self, key: bytes, k: np.ndarray,
                v: np.ndarray) -> TierEntry:
        self._tick += 1
        # per-buffer crc32 recorded NOW (inside encode_entry), verified
        # when a promotion decodes the payload back — bit rot, a torn
        # spill write, or injected corruption all surface as
        # ChecksumError there, and the consumer re-prefills instead of
        # serving garbage KV
        return encode_entry(key, k, v,
                            quantize=self.cfg.quantize_cold,
                            page_dtype=self.page_dtype,
                            tick=self._tick)

    def demote(self, key: bytes, k: np.ndarray,
               v: np.ndarray) -> Optional[str]:
        """Capture one page's KV (``k``/``v``: [L, KV, ps, Dh] in the
        cache dtype) under ``key``.  Lands in the host pool, cascading
        older entries down (host → NVMe → drop) to make room; returns
        the landing tier, or None when nothing could hold it (the page
        is then a plain eviction).  A key already resident just
        refreshes its age — re-demoting a promoted page is free."""
        if self.disabled is not None:
            return None             # circuit-broken: plain eviction
        if key in self.entries:
            return self.touch(key)
        entry = self._encode(key, k, v)
        if _faults.active_plan() is not None:
            # kv_corrupt injection: flip a payload byte AFTER the
            # checksum was recorded — the promote-side verify must
            # catch exactly this
            _delay, err = _faults.poll("kv_corrupt", key_hex(key))
            if err is not None:
                _faults.corrupt_array(entry.data[0])
        return self._land(entry)

    def demote_prequantized(self, key: bytes, kq: np.ndarray,
                            ks: np.ndarray, vq: np.ndarray,
                            vs: np.ndarray) -> Optional[str]:
        """Capture one ALREADY-QUANTIZED page (``kq``/``vq``: int8
        codes [L, KV, ps, Dh]; ``ks``/``vs``: f32 scales [L, KV, ps,
        1]) — the quantized-resident engine's demote path, where the
        device planes ARE the codec form so the host-side quantize in
        :meth:`demote` would be a lossy no-op.  Same landing/cascade
        semantics; requires ``quantize_cold`` (the config validates
        the pairing, this guards direct callers)."""
        if self.disabled is not None:
            return None             # circuit-broken: plain eviction
        if key in self.entries:
            return self.touch(key)
        if not self.cfg.quantize_cold:
            raise ValueError(
                "demote_prequantized requires kv_tier.quantize_cold — "
                "a dense-entry pool cannot hold int8 codec payloads")
        self._tick += 1
        entry = encode_prequantized_entry(
            key, kq, ks, vq, vs, page_dtype=self.page_dtype,
            tick=self._tick)
        if _faults.active_plan() is not None:
            _delay, err = _faults.poll("kv_corrupt", key_hex(key))
            if err is not None:
                _faults.corrupt_array(entry.data[0])
        return self._land(entry)

    def admit_entry(self, entry: TierEntry) -> Optional[str]:
        """Admit an ALREADY-SERIALIZED entry (a fabric migration: the
        payload was encoded — and checksummed — on another replica;
        quantized cold pages ride as-is).  Record AND payload are
        copied — this pool's lifetime must never alias a shared
        transit buffer (a later in-fabric corruption or eviction
        cannot reach pages already admitted here).  Returns the
        landing tier like :meth:`demote`; the original checksums carry
        over, so a payload corrupted in transit fails this pool's
        promotion-time verify and the admitting engine re-prefills."""
        if self.disabled is not None:
            return None
        if entry.key in self.entries:
            return self.touch(entry.key)
        self._tick += 1
        clone = dataclasses.replace(
            entry, location="host", tick=self._tick,
            data=tuple(np.array(b, copy=True) for b in entry.data))
        return self._land(clone)

    def entry_payload(self, key: bytes) -> TierEntry:
        """A host-form view of one entry for export: host entries
        return as-is; an NVMe entry's buffers are read back
        synchronously (export is off the decode critical path).  The
        ORIGINAL checksums ride along — the importer's decode verifies
        them, so corruption anywhere between the demote that recorded
        them and the remote promotion is caught there."""
        e = self.entries[key]
        if e.location == "host":
            return e
        bufs = tuple(
            _faults.read_file_sync(self._nvme._path(name), shape,
                                   dtype, key=name)
            for name, shape, dtype in e.buffers)
        return dataclasses.replace(e, location="host", data=bufs)

    def _land(self, entry: TierEntry) -> Optional[str]:
        """Place a freshly encoded (or fabric-admitted) entry: host
        pool first, cascading older entries down (host → NVMe → drop)
        to make room; an entry bigger than the whole host pool goes
        straight to NVMe."""
        key = entry.key
        if entry.nbytes > self.cfg.host_pool_bytes:
            # bigger than the whole host pool: straight to NVMe (the
            # entry was never host-accounted — accounted=False keeps
            # host_bytes from going negative)
            if self._spill_entry(entry, accounted=False):
                self.entries[key] = entry
                self._refresh_gauges()
                return entry.location
            self.dropped_pages += 1
            self._c_dropped.inc()
            return None
        while self.host_bytes + entry.nbytes > self.cfg.host_pool_bytes:
            if not self._cascade_one():
                self.dropped_pages += 1
                self._c_dropped.inc()
                return None
        self.entries[key] = entry
        self.host_bytes += entry.nbytes
        self._host_n += 1
        self._order["host"][key] = None
        self._refresh_gauges()
        return "host"

    def _oldest(self, location: str) -> Optional[TierEntry]:
        for key in self._order[location]:
            if key not in self._pinned:
                return self.entries[key]
        return None

    def _cascade_one(self) -> bool:
        """Push the oldest unpinned host entry down one tier (NVMe when
        configured, else drop).  Returns False when the host pool holds
        only pinned entries — the caller's demote then drops."""
        victim = self._oldest("host")
        if victim is None:
            return False
        if self._spill_entry(victim):
            return True
        self._discard(victim, count_drop=True)
        return True

    def _spill_entry(self, e: TierEntry, accounted: bool = True) -> bool:
        """Write ``e``'s payload to NVMe files and retag it.
        ``accounted=False`` for an entry that never entered the host
        pool (demote's direct-to-NVMe path) — only pool residents may
        decrement ``host_bytes``."""
        if self._nvme is None:
            return False
        cap = self.cfg.nvme_pool_bytes
        while cap is not None and self.nvme_bytes + e.nbytes > cap:
            old = self._oldest("nvme")
            if old is None:
                return False
            self._discard(old, count_drop=True)
        try:
            for (name, _s, _d), buf in zip(e.buffers, e.data):
                self._nvme.write(name, buf)
        except (IOError, OSError):
            # retries exhausted: unlink any partial files (a later
            # same-key spill must not find a torn payload) and degrade
            # — the entry drops instead of spilling
            for name in e.names:
                self._nvme.unlink(name)
            self.spill_failures += 1
            self._c_spill_fail.inc()
            logger.warning("kv_tier: spill write of %s failed after "
                           "retries — dropping the entry",
                           key_hex(e.key)[:12])
            return False
        if accounted and e.location == "host":
            self.host_bytes -= e.nbytes
            self._host_n -= 1
        self._order["host"].pop(e.key, None)
        e.location = "nvme"
        e.data = None
        self.nvme_bytes += e.nbytes
        self._nvme_n += 1
        self._order["nvme"][e.key] = None
        self.spilled_pages += 1
        self._c_spill_bytes.inc(e.nbytes)
        self._refresh_gauges()
        return True

    def _discard(self, e: TierEntry, count_drop: bool = False) -> None:
        self.entries.pop(e.key, None)
        self._order[e.location].pop(e.key, None)
        if e.location == "host":
            self.host_bytes -= e.nbytes
            self._host_n -= 1
        else:
            self.nvme_bytes -= e.nbytes
            self._nvme_n -= 1
            if self._nvme is not None:
                for name in e.names:
                    self._nvme.unlink(name)
        if count_drop:
            self.dropped_pages += 1
            self._c_dropped.inc()
        self._refresh_gauges()

    def discard(self, key: bytes) -> None:
        e = self.entries.get(key)
        if e is not None:
            self._discard(e)

    def host_view(self) -> "_HostOnlyView":
        """A channel-free read view for promotions whose keys are ALL
        host-resident (pinned, so they cannot spill mid-flight): its
        fence/slot operations are no-ops, so any number of such
        promotions run concurrently without touching — or blocking
        on — the single NVMe aio channel another promotion may own."""
        return _HostOnlyView(self)

    # ------------------------------------- _Tier read interface (promote)
    # (consumed by param_stream.TierPageReader; the NVMe channel is
    # single-consumer — the engine serializes promotions that need it,
    # host-resident promotions ride host_view() instead.  The DEVICE
    # half of a promotion — the scatter of these payloads into HBM
    # pages — is what devprof's "promote" phase samples; the host read
    # side stays visible through the kv_tier promote-stall histogram)
    def entry_meta(self, key: bytes):
        """(names, shapes, dtypes) of ``key``'s spilled buffers — the
        read plan a TierPageReader submits."""
        e = self.entries[key]
        return (list(e.names), [b[1] for b in e.buffers],
                [b[2] for b in e.buffers])

    def get_submit(self, name: str, shape, dtype, out=None):
        hexk, i = name[len("kv_"):].rsplit("_", 1)
        e = self.entries[bytes.fromhex(hexk)]
        if e.location == "host":
            # zero-copy: the stored array IS the fenced buffer (the
            # cascade may spill it to NVMe mid-promotion, but spilling
            # keeps the array alive in the file — and the returned
            # reference stays valid regardless)
            return e.data[int(i)]
        buf = np.empty(shape, np.dtype(dtype)) if out is None else out
        self._nvme.submit_read(name, buf)
        return buf

    def reads_pending(self) -> int:
        return self._nvme.reads_pending() if self._nvme is not None else 0

    def fence_reads(self) -> None:
        if self._nvme is not None:
            self._nvme.fence_reads()

    def next_read_slot(self) -> None:
        if self._nvme is not None:
            self._nvme.next_read_slot()

    def fence_all_reads(self) -> None:
        if self._nvme is not None:
            self._nvme.fence_all_reads()

    def read_sync(self, name: str, shape, dtype) -> np.ndarray:
        """Synchronous fallback read of one spilled buffer — the
        degradation rung below the aio channel (``TierLayerReader``
        falls here when a fence exhausted its retries): host entries
        return their stored array, NVMe entries read their file through
        the plain OS path, bypassing the aio pool entirely."""
        hexk, i = name[len("kv_"):].rsplit("_", 1)
        e = self.entries[bytes.fromhex(hexk)]
        if e.location == "host":
            _faults.inject("sync_read", key=name)
            return e.data[int(i)]
        return _faults.read_file_sync(self._nvme._path(name), shape,
                                      dtype, key=name)

    # ----------------------------------------------------------- decode
    def _host_buffer(self, name: str) -> np.ndarray:
        """Resolve ``name`` strictly from host storage (the
        channel-free view's read path — an NVMe entry here means a pin
        failed to hold the entry host-resident, which must fail loudly
        rather than fence a channel this promotion does not own)."""
        hexk, i = name[len("kv_"):].rsplit("_", 1)
        e = self.entries[bytes.fromhex(hexk)]
        if e.location != "host":
            raise RuntimeError(
                f"channel-free promotion read of {name} found the "
                f"entry on {e.location!r} — pinned entries must stay "
                "host-resident")
        return e.data[int(i)]

    def _verify(self, key: bytes, e: TierEntry, bufs) -> None:
        """Check every fenced buffer against the checksum recorded at
        demote time — corrupt payloads must raise
        :class:`~deepspeed_tpu.faults.ChecksumError` BEFORE anything
        scatters into live HBM pages."""
        if e.checksums is None:
            return
        for (name, _s, _d), buf, want in zip(e.buffers, bufs,
                                             e.checksums):
            got = _crc(buf)
            if got != want:
                raise ChecksumError(
                    f"KV-tier page {key_hex(key)[:12]} buffer "
                    f"{name}: payload checksum mismatch "
                    f"({got:#x} != {want:#x}) — spilled copy is "
                    "corrupt")

    def decode(self, key: bytes, bufs) -> Tuple[np.ndarray, np.ndarray]:
        """Fenced buffers → the page's (k, v) in the cache dtype
        (dequantizing cold pages).  Checksum-verified FIRST."""
        e = self.entries[key]
        self._verify(key, e, bufs)
        if e.quantized:
            kq, ks, vq, vs = bufs
            return (dequantize_page(kq, ks, self.page_dtype),
                    dequantize_page(vq, vs, self.page_dtype))
        k, v = bufs
        return (np.asarray(k, self.page_dtype),
                np.asarray(v, self.page_dtype))

    def decode_quantized(self, key: bytes, bufs):
        """Fenced buffers → the page's RAW int8 codec form ``(kq, ks,
        vq, vs)``, checksum-verified first — the quantized-resident
        publish path scatters these straight into the device's
        code/scale planes, skipping the dense dequantize entirely (the
        whole point of ``kv_tier.quantized_resident``).  Raises on a
        dense (unquantized) entry: there are no codes to publish."""
        e = self.entries[key]
        if not e.quantized:
            raise ValueError(
                f"KV-tier page {key_hex(key)[:12]} is a dense entry — "
                "quantized-resident promotion needs "
                "kv_tier.quantize_cold payloads")
        self._verify(key, e, bufs)
        kq, ks, vq, vs = bufs
        return (np.asarray(kq, np.int8), np.asarray(ks, np.float32),
                np.asarray(vq, np.int8), np.asarray(vs, np.float32))


class _HostOnlyView:
    """Channel-free ``_Tier`` read facade over a :class:`KVTierPool`:
    host-array reads with no-op fencing, so a host-resident promotion
    never blocks on (or corrupts the slot state of) the NVMe channel a
    concurrent promotion owns."""

    def __init__(self, pool: KVTierPool):
        self._pool = pool

    def entry_meta(self, key: bytes):
        return self._pool.entry_meta(key)

    def get_submit(self, name: str, shape, dtype, out=None):
        return self._pool._host_buffer(name)

    def read_sync(self, name: str, shape, dtype):
        return self._pool.read_sync(name, shape, dtype)

    def reads_pending(self) -> int:
        return 0

    def fence_reads(self) -> None:
        pass

    def next_read_slot(self) -> None:
        pass
