"""ZeRO-Inference for TPU serving: serve models LARGER than HBM by
streaming layer weights host→HBM under the decode sweep.

Reference: DeepSpeed ZeRO-Inference (arXiv:2206.01861, built on
ZeRO-Infinity's parameter offload, arXiv:2104.07857 +
deepspeed/runtime/swap_tensor/partitioned_param_swapper.py): model
weights live on a host-RAM or NVMe tier; per-layer weights are fetched
into device memory just ahead of their layer's compute and released
after, so GPU/TPU residency is O(layers-in-flight), not O(model), and
throughput is bound by link bandwidth × batch, not by HBM capacity.

TPU design.  The serving stack already factors per request phase into
static-shape programs (:class:`~deepspeed_tpu.inference.serving.
ServingEngine`); this module re-factors the MODEL the same way the
training :class:`~deepspeed_tpu.param_stream.ParamStreamEngine` does —
per-LAYER jits instead of one whole-model jit:

    stem:   (stem, tokens, start) -> (x, cos, sin)       [resident]
    block:  (lp, x, cos, sin, kp, vp, table, start)
            -> (x, kp, vp)                               [one layer]
    head:   (head, x) -> logits                          [resident]

The HOST drives the layer sweep.  Streamed layers ride the shared
:class:`~deepspeed_tpu.param_stream.TierLayerReader` pipeline: while
layer ``l``'s block program computes, layer ``l+1``'s tier read (NVMe
aio on alternating slots, or host buffers) and its async H2D upload are
already in flight — the same double-buffered phase overlap the training
engine uses, re-targeted at decode.  The KV cache is stored as
PER-LAYER page arrays (a tuple, not a stacked [L, ...] block) so each
block program donates and updates exactly one layer's pages in place —
no cross-layer cache copies on the hot path.

An HBM-budget planner (:func:`plan_residency`) charges stem + head +
the KV cache + the ``(prefetch_depth + 1)``-layer streaming working set
against ``hbm_budget_bytes`` and pins as many leading layers resident
as still fit; the rest stream.  ``hbm_budget_bytes: null`` streams
every layer (the serve-anything default).  Composes with:

- the paged-KV decode kernels: block programs call the same
  :func:`~deepspeed_tpu.inference.kernels.paged_attention_step` the
  whole-model forward uses — token-identical output;
- int8 weight-only quantization: the tier holds int8 codes + group
  scales and each block program traces its own dequant;
- tensor/expert parallelism: streamed uploads land pre-sharded via the
  model's own PartitionSpecs (per-layer, layer axis dropped), the KV
  head axis shards over ``model``;
- the continuous-batching scheduler: admission, paging, split-fuse and
  chunked decode run unchanged — only the three compiled entry points
  are swapped for host-driven streamed executors;
- automatic prefix caching (``prefix_cache=``): matching, sharing, and
  warm-pool eviction live in the base scheduler's refcounted allocator
  and page-table bookkeeping, so streamed block programs read shared
  pages through the same per-layer page arrays — a cache-hit admission
  runs the "chunk" phase over the uncached suffix only;
- speculative decoding (``speculative=``): the verify pass is the
  SAME host-driven "chunk" executor, so one full layer-weight stream
  scores K+1 positions per slot and the streamed bytes per generated
  token drop by the mean acceptance length — the single biggest lever
  on a decode loop whose throughput is pinned to stream bandwidth
  (``zi_bytes_uploaded`` / generated tokens is the contract metric;
  SPEC_BENCH.json carries the A/B).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.config import KVTierConfig, ZeroInferenceConfig
from deepspeed_tpu.infinity import _NvmeTier, _RamTier
from deepspeed_tpu.inference.kernels import PagedKVCache
from deepspeed_tpu.inference.serving import (_WIRE_MIN_ELEMS,
                                             ServingEngine,
                                             _resolve_kernels_for_builder)
from deepspeed_tpu.param_stream import TierLayerReader
from deepspeed_tpu.utils.logging import logger


def _unused_program(*_a, **_k):  # pragma: no cover - must never run
    raise AssertionError(
        "ZeroInferenceServingEngine replaces the whole-model programs "
        "with host-driven streamed executors")


def plan_residency(*, n_layers: int, layer_bytes: int,
                   stem_head_bytes: int, cache_bytes: int,
                   budget: Optional[int],
                   prefetch_depth: int) -> Dict[str, Any]:
    """HBM-budget planner: how many leading layers stay resident.

    Fixed charges come first — stem + head weights, the paged KV cache,
    and (whenever anything streams) the ``(prefetch_depth + 1)``-layer
    double-buffer working set.  Whatever budget remains pins layers
    resident.  ``budget=None`` streams everything; a budget that cannot
    even hold the fixed charges is a config error, not a silent OOM.
    """
    total_resident = stem_head_bytes + cache_bytes + n_layers * layer_bytes
    working = (prefetch_depth + 1) * layer_bytes
    if budget is None:
        n_res = 0
    elif budget >= total_resident:
        n_res = n_layers
    else:
        floor = stem_head_bytes + cache_bytes + working
        if floor > budget:
            raise ValueError(
                f"zero_inference.hbm_budget_bytes={budget} cannot hold "
                f"the streaming floor: stem+head {stem_head_bytes} B + "
                f"KV cache {cache_bytes} B + {prefetch_depth + 1}-layer "
                f"working set {working} B = {floor} B")
        n_res = min(n_layers - 1, (budget - floor) // max(layer_bytes, 1))
    ws = stem_head_bytes + cache_bytes + n_res * layer_bytes + (
        0 if n_res == n_layers else working)
    return {
        "n_layers": n_layers,
        "n_resident": int(n_res),
        "n_streamed": int(n_layers - n_res),
        "layer_bytes": int(layer_bytes),
        "stem_head_bytes": int(stem_head_bytes),
        "cache_bytes": int(cache_bytes),
        "weight_image_bytes": int(stem_head_bytes
                                  + n_layers * layer_bytes),
        "hbm_budget_bytes": budget,
        "prefetch_depth": int(prefetch_depth),
        "hbm_working_set_bytes": int(ws),
    }


class ZeroInferenceServingEngine(ServingEngine):
    """Weight-streamed continuous-batching serving engine.

    Drop-in for :class:`ServingEngine` — same ``submit``/``step``/
    ``run`` surface, same scheduler — with the three compiled entry
    points replaced by host drivers that sweep per-layer programs and
    stream non-resident layer weights from ``self.tier``.  ``plan``
    carries the residency decision;
    :meth:`hbm_weight_working_set_bytes` is the streaming contract
    (compare: the full weight image for the resident engine).
    """

    def __init__(self, *, stem, blocks, head, fns, zi: ZeroInferenceConfig,
                 n_layers: int, n_kv: int, head_dim: int, mesh=None,
                 stem_specs=None, head_specs=None, layer_specs=None,
                 **kw):
        self._zi = zi
        kvt = KVTierConfig.coerce(kw.get("kv_tier"))
        if kvt.enabled and kvt.quantized_resident:
            # the streamed engine's cache is a per-layer TUPLE of dense
            # pages (block programs donate one layer in place); it has
            # no int8 code/scale planes to publish into — fail loudly,
            # never silently serve dense pages under a quantized-
            # resident config
            raise NotImplementedError(
                "kv_tier.quantized_resident is not wired for the "
                "weight-streamed (zero_inference) engine — serve "
                "resident, or drop quantized_resident")
        self._stem_fn, self._block_fn, self._head_fn = fns
        self._layer_specs = layer_specs
        self._stem_specs = stem_specs
        self._head_specs = head_specs
        self._L = n_layers

        # ---- per-layer leaf records from the stacked blocks tree.
        # Leaves stay host-side VIEWS of the caller's arrays where
        # possible: inference never mutates weights, so the tier can
        # alias them (unlike the training engine's mutating tier).
        leaves, self._btree = jax.tree_util.tree_flatten(blocks)
        leaves = [np.asarray(a) for a in leaves]
        for a in leaves:
            if a.shape[0] != n_layers:
                raise ValueError(
                    f"stacked block leaf {a.shape} does not carry the "
                    f"layer axis (n_layers={n_layers}) in dim 0")
        self._bshapes = [a.shape[1:] for a in leaves]
        self._bdtypes = [a.dtype for a in leaves]
        layer_bytes = sum(a.nbytes // n_layers for a in leaves)

        # ---- residency plan.  Cache geometry mirrors ServingEngine's
        # signature defaults (kw is forwarded verbatim to super()).
        num_pages = kw.get("num_pages", 128)
        page_size = kw.get("page_size", 16)
        cache_dtype = kw.get("cache_dtype", jnp.bfloat16)
        cache_bytes = (2 * n_layers * n_kv * num_pages * page_size
                       * head_dim * jnp.dtype(cache_dtype).itemsize)
        # dedupe shared leaves by identity: tied-embedding models alias
        # ONE table between stem and head — charging it twice would
        # overstate the fixed charge by the largest resident tensor
        seen_ids = set()
        stem_head_bytes = 0
        for x in jax.tree.leaves((stem, head)):
            if id(x) not in seen_ids:
                seen_ids.add(id(x))
                stem_head_bytes += x.nbytes
        self.plan = plan_residency(
            n_layers=n_layers, layer_bytes=layer_bytes,
            stem_head_bytes=stem_head_bytes, cache_bytes=cache_bytes,
            budget=zi.hbm_budget_bytes, prefetch_depth=zi.prefetch_depth)
        n_res = self.plan["n_resident"]
        self._streamed_ids = list(range(n_res, n_layers))

        # ---- tier ingest for the streamed suffix
        if zi.tier == "nvme" and self._streamed_ids:
            self.tier = _NvmeTier(
                os.path.join(zi.nvme_path, "zero_inference"))
        else:
            self.tier = _RamTier()
        for l in self._streamed_ids:
            for i, a in enumerate(leaves):
                self.tier.put(f"zi_p_{l}_{i}", np.ascontiguousarray(a[l]))
        if isinstance(self.tier, _NvmeTier):
            self.tier.fence_all()

        # the scheduler never touches params in streamed mode — stem and
        # head live on device here, blocks on the tier
        super().__init__(None, _unused_program, _unused_program,
                         n_layers=n_layers, n_kv=n_kv, head_dim=head_dim,
                         mesh=mesh, chunk_prefill_fn=_unused_program,
                         **kw)

        # streaming telemetry on the engine's registry (created by the
        # base ctor): upload/sweep counters, bytes moved, the exposed
        # (non-hidden) prefetch wait distribution, and an achieved-
        # bandwidth gauge — the observability ZeRO-Inference needs to
        # answer "is the NVMe->host->HBM latency actually hidden?"
        self._layer_bytes = int(layer_bytes)
        r = self.registry
        self._c_h2d = r.counter(
            "zi_layer_h2d_uploads", "per-layer host->HBM weight uploads")
        self._c_sweeps = r.counter(
            "zi_layer_sweeps", "full layer-stack sweeps driven")
        self._c_bytes = r.counter(
            "zi_bytes_uploaded", "weight bytes shipped host->HBM")
        self._h_wait = r.histogram(
            "zi_prefetch_wait_seconds",
            "time the sweep blocked on a tier fence (exposed IO cost; "
            "0-heavy distribution means prefetch fully hides the link)")
        self._g_bw = r.gauge(
            "zi_h2d_bandwidth_bytes_per_s",
            "streamed bytes / sweep wall time (lower bound: the sweep "
            "window includes the compute the stream hides behind)")
        # int8 layer broadcast (comm.quantized_serving, ISSUE 18): every
        # upload — the resident pins below AND the steady-state tier
        # stream — packs float leaves host-side so the H2D link carries
        # int8 codes + f32 scales (the training gradient wire's codec,
        # comm/collectives.py).  The serving_rtol gate runs once per
        # layer, on its first upload.
        self._wire_on = self._comm.quantized_serving
        self._wire_checked: set = set()
        if self._wire_on:
            # the serving_rtol gate runs at BUILD over every layer's
            # leaves: a config the codec cannot honor must fail the
            # constructor, not surface later as swallowed per-request
            # admission failures from the reader thread (request
            # isolation treats a mid-stream exception as one bad
            # request, which a config error is not)
            for a in leaves:
                for l in range(n_layers):
                    self._wire_check(a[l], l)
            self._wire_checked.update(range(n_layers))
        self._c_comm_int8 = r.counter(
            "comm_bytes_on_wire_int8",
            "bytes actually shipped on the quantized wire (int8 codes "
            "+ f32 scales)")
        self._c_comm_f32 = r.counter(
            "comm_bytes_on_wire_f32",
            "bytes a flat f32 wire would have shipped for the same "
            "payload")
        # incident wiring (PR 15): a streamed engine's trajectory
        # pathology of interest is the tier fence — watch the
        # prefetch-wait p95 history series so a developing stall trend
        # trips an anomaly bundle before the burn alert fires.  Only
        # when the detector set is the DEFAULT one: an operator's
        # explicit `detect` list (incl. the hard-triggers-only `()`)
        # must not be re-armed behind their back
        if self.incident_mgr.enabled and self._detect_defaulted:
            self.incident_mgr.watch_series(
                "zi_prefetch_wait_seconds:p95")
        self._resident = {
            l: self._upload_layer([a[l] for a in leaves], l)
            for l in range(n_res)}
        # capture only the COUNT: a lambda closing over `leaves` would
        # pin the full host weight image for the engine's lifetime —
        # defeating the NVMe tier, whose whole point is that the host
        # drops the image once the per-layer files are fenced
        n_leaves = len(leaves)
        self._reader = TierLayerReader(
            self.tier,
            names_fn=lambda l: [f"zi_p_{l}_{i}"
                                for i in range(n_leaves)],
            shapes=self._bshapes, dtypes=self._bdtypes,
            to_device=self._upload_layer, depth=zi.prefetch_depth,
            registry=self.registry, prefix="zi_stream",
            # layer fetch-issue/arrive/stall events land in the same
            # flight recorder as the request lifecycle (base ctor built
            # the tracer): a slow request's trace shows WHICH layer's
            # tier fence it sat behind
            tracer=self.tracer,
            # graceful stream degradation: transient read failures
            # retry (resubmit + backoff), then fall over to synchronous
            # tier-file reads; only an unrecoverable failure raises the
            # structured fatal — after a flight-recorder postmortem
            retries=zi.io_retries,
            retry_backoff_s=zi.io_retry_backoff_s)
        # KV-tier promotion and the layer-weight stream share the same
        # storage device when both tiers are NVMe: register the weight
        # read pools ABOVE the KV pool in a cooperative priority group,
        # so a KV promote defers (bounded by the engine's deferral cap)
        # while layer fetches are in flight — the decode sweep's
        # double-buffered weight reads are a whole-batch stall if
        # starved, a deferred promotion only delays one admission
        if self._kv_pool is not None and isinstance(self.tier, _NvmeTier):
            from deepspeed_tpu.io.aio import AioPriorityGroup

            grp = AioPriorityGroup()
            for h in self.tier.rpools:
                grp.register(h.pending, 1)
            self._kv_pool.set_priority(grp, 0)
        self._stem_dev = self._place(stem, stem_specs)
        if "embed" in head and head["embed"] is stem["embed"]:
            # tied embeddings: hand head the ALREADY-PLACED table so the
            # device holds one copy (device_put of a placed array with
            # the same sharding is a no-op, not a second upload)
            head = dict(head, embed=self._stem_dev["embed"])
        self._head_dev = self._place(head, head_specs)
        logger.info(
            "zero-inference: %d/%d layers resident (%.1f MB/layer), "
            "tier=%s depth=%d, HBM weight working set %.1f MB of a "
            "%.1f MB image",
            n_res, n_layers, layer_bytes / 1e6, zi.tier,
            self._reader.depth,
            self.plan["hbm_working_set_bytes"] / 1e6,
            self.plan["weight_image_bytes"] / 1e6)

    # ------------------------------------------------------- placement
    def _place(self, tree, specs):
        if specs is not None:
            from deepspeed_tpu.inference.quantized import shard_quantized

            return shard_quantized(tree, specs, self._mesh)
        return jax.device_put(tree)

    def _upload_layer(self, bufs: List[np.ndarray], _l: int):
        """Fenced host buffers → device tree for ONE layer (the async
        H2D the reader keeps in flight behind the sweep); TP/EP uploads
        land pre-sharded under the model's own per-layer specs.  Under
        ``comm.quantized_serving`` float leaves cross the link as int8
        codes + scales and dequantize device-side."""
        if self._wire_on:
            bufs = [self._wire_put(a, _l) for a in bufs]
            self._wire_checked.add(_l)
        tree = jax.tree_util.tree_unflatten(self._btree, list(bufs))
        self._c_h2d.inc()
        self._c_bytes.inc(self._layer_bytes)
        return self._place(tree, self._layer_specs)

    def _wire_check(self, buf, l: int) -> None:
        """serving_rtol gate for one leaf of layer ``l`` — exact
        host-side round-trip error of the wire codec, raising on a
        config the codec cannot honor.  Build runs it over every layer;
        :meth:`_wire_put` re-runs it only for layers the build never
        saw (``_wire_checked`` is the ledger)."""
        from deepspeed_tpu.comm.collectives import quantize_for_wire_np

        a = np.asarray(buf)
        if a.dtype.kind != "f" or a.size < _WIRE_MIN_ELEMS:
            return
        q, s, _ = quantize_for_wire_np(a)
        af32 = a.astype(np.float32)
        deq = (q.astype(np.float32).reshape(s.size, -1)
               * s[:, None]).reshape(a.shape)
        rel = float(np.abs(deq - af32).max()) \
            / (float(np.abs(af32).max()) or 1.0)
        if rel > self._comm.serving_rtol:
            raise ValueError(
                f"comm.quantized_serving: layer {l} leaf {a.shape} "
                f"round-trips at rel err {rel:.3e} > serving_rtol "
                f"{self._comm.serving_rtol:g} — raise the tolerance "
                "or stream this model unquantized")

    def _wire_put(self, buf, l: int):
        """One leaf onto the int8 wire: host-side pack → H2D of codes +
        scales → device-side dequant to the leaf's dtype.  Non-float and
        tiny leaves ship exact (same threshold as the TP placement
        path).  The stream re-ships the same bytes every sweep, so the
        build-time gate covers the engine's lifetime without taxing the
        hot path."""
        from deepspeed_tpu.comm.collectives import (dequantize_from_wire,
                                                    quantize_for_wire_np)

        a = np.asarray(buf)
        if a.dtype.kind != "f" or a.size < _WIRE_MIN_ELEMS:
            return buf
        if l not in self._wire_checked:
            self._wire_check(a, l)
        q, s, dt = quantize_for_wire_np(a)
        self._c_comm_int8.inc(q.nbytes + s.nbytes)
        self._c_comm_f32.inc(a.size * 4)
        return dequantize_from_wire(jnp.asarray(q), jnp.asarray(s),
                                    jnp.dtype(dt))

    # ---------------------------------------------------- program hooks
    def _alloc_cache(self, n_layers, n_kv, num_pages, page_size,
                     head_dim, cache_dtype) -> PagedKVCache:
        # PER-LAYER page arrays: each block program donates and returns
        # one layer's [KV, P, ps, Dh] pages — a stacked cache would turn
        # every layer's update into a whole-cache copy under streaming
        from jax.sharding import PartitionSpec as P

        kv_sh = None
        if self._mesh is not None and self._mesh.size("model") > 1:
            kv_sh = self._mesh.sharding(P("model", None, None, None))

        def kv():
            z = jnp.zeros((n_kv, num_pages, page_size, head_dim),
                          cache_dtype)
            return jax.device_put(z, kv_sh) if kv_sh is not None else z

        return PagedKVCache(
            k=tuple(kv() for _ in range(n_layers)),
            v=tuple(kv() for _ in range(n_layers)),
            table=self._put(jnp.full(
                (self.max_batch, self.max_pages_per_seq),
                self.trash_page, jnp.int32)),
            seq_lens=self._put(jnp.zeros((self.max_batch,), jnp.int32)),
            page_size=page_size)

    def _build_programs(self, prefill_fn, decode_fn,
                        chunk_prefill_fn) -> None:
        self._stem_jit = jax.jit(self._stem_fn)
        self._head_jit = jax.jit(self._head_fn)
        self._bjits: Dict[Any, Any] = {}
        self._prefill = self._streamed_prefill
        self._chunk_prefill = self._streamed_chunk_prefill
        self._decode_chunk_fn = self._streamed_decode_chunk

    def _devprof_cost_analyze(self) -> None:
        """The streamed executors are host-driven per-layer sweeps, not
        whole-model jits — there is no single lowered program whose
        ``cost_analysis()`` describes a dispatch, so the roofline
        numerators stay unregistered (MFU/MBU read 0).  Devprof's
        compile sentinel and device-time attribution still work: the
        sentinel wrappers count dispatches on the streamed callables
        (``_cache_size`` absent → dispatch accounting only, per-block
        compiles are caught by the process-wide monitoring listener)."""
        return

    def _devprof_warmup(self) -> None:
        """No build-time precompile either: a streamed-executor
        "dispatch" is a full host-driven layer sweep through the NVMe
        reader pipeline — running one at build would read every layer
        off disk before the first request.  The per-block jits compile
        lazily on the first sweep instead; the steady-state boundary
        (first token) already sits after that sweep."""
        return

    def _block_jit(self, phase: str):
        """Per-phase block program.  Only the pages donate (they update
        in place); the layer weights do NOT — no block output matches a
        weight leaf's shape, so weight donation could never be honored
        (it only warns), and a streamed layer's buffer frees the moment
        the sweep drops its last reference anyway."""
        if phase not in self._bjits:
            f = functools.partial(self._block_fn,
                                  continuation=phase == "chunk",
                                  prefill=phase == "prefill")
            self._bjits[phase] = jax.jit(f, donate_argnums=(4, 5))
        return self._bjits[phase]

    # ------------------------------------------------------ layer sweep
    # dstpu: hot-path
    def _layer_sweep(self):
        """Yield ``(l, layer_params)`` over all layers in order;
        streamed layers come off the double-buffered reader pipeline
        with the next layer's read + upload already in flight."""
        self._c_sweeps.inc()
        gen = (self._reader.sweep(self._streamed_ids,
                                  on_wait=self._note_wait)
               if self._streamed_ids else iter(()))
        # PRIME the pipeline before the resident prefix computes:
        # generators are lazy, and without this the first streamed
        # layer's tier read + upload would only start at layer
        # n_resident — one fully exposed fetch per sweep
        pending = next(gen, None)
        for l in range(self._L):
            if l in self._resident:
                yield l, self._resident[l]
            else:
                cur, pending = pending, next(gen, None)
                yield cur

    def _note_wait(self, dt: float) -> None:
        self._h_wait.observe(dt)

    # (the `stats` shim override was removed with the base shim on its
    # announced PR 9 schedule — read `engine.registry.snapshot()`)

    # ------------------------------------------------ streamed executors
    # dstpu: hot-path
    def _run_blocks(self, phase, x, cos, sin, k_list, v_list, table,
                    start):
        bj = self._block_jit(phase)
        t0 = time.perf_counter() if self._tel_on else 0.0
        for l, lp in self._layer_sweep():
            x, k_list[l], v_list[l] = bj(
                lp, x, cos, sin, k_list[l], v_list[l], table, start)
        if self._tel_on and self._streamed_ids:
            dt = time.perf_counter() - t0
            if dt > 0:
                self._g_bw.set(
                    len(self._streamed_ids) * self._layer_bytes / dt)
        return x

    # dstpu: hot-path
    def _forward_view(self, phase, toks, view):
        k_list, v_list = list(view.k), list(view.v)
        start = view.seq_lens
        x, cos, sin = self._stem_jit(self._stem_dev, toks, start)
        x = self._run_blocks(phase, x, cos, sin, k_list, v_list,
                             view.table, start)
        logits = self._head_jit(self._head_dev, x)
        return logits, view._replace(k=tuple(k_list), v=tuple(v_list))

    def _streamed_prefill(self, _params, toks, view):
        # a bucket-1 single-token "prefill" takes the decode path, like
        # forward_paged's prelude (prefill = T > 1) — same kernels, same
        # tokens as the resident engine
        phase = "prefill" if toks.shape[1] > 1 else "decode"
        return self._forward_view(phase, toks, view)

    def _streamed_chunk_prefill(self, _params, toks, view):
        # doubles as the speculative VERIFY executor: the scheduler
        # hands it [B, K+1] draft windows over the full cache, so one
        # layer-stack sweep (= one full weight stream for the streamed
        # suffix) scores every position of every active slot
        return self._forward_view("chunk", toks, view)

    # dstpu: hot-path
    def _streamed_decode_chunk(self, _params, toks, cache, keys, temps):
        """K decode steps, host-driven: each step sweeps the layer
        stack (streamed weights double-buffered ahead), samples on
        device, and feeds the token to the next step — tokens never
        visit the host inside the chunk, so the one-sync-per-K-tokens
        contract of the compiled path is preserved."""
        K = self.decode_chunk
        k_list, v_list = list(cache.k), list(cache.v)
        lens = cache.seq_lens
        tok = toks
        cols = []
        for j in range(K):
            start = lens + j if j else lens
            x, cos, sin = self._stem_jit(self._stem_dev, tok, start)
            x = self._run_blocks("decode", x, cos, sin, k_list, v_list,
                                 cache.table, start)
            logits = self._head_jit(self._head_dev, x)
            # the policy-resolved sampler (base ctor): the fused pallas
            # argmax when kernels.fused_sampling resolved "on", the
            # jitted XLA twin otherwise — bit-identical greedy tokens
            nxt = self._sample_fn(logits[:, -1], keys[j], temps)
            cols.append(nxt)
            tok = nxt[:, None]
        cache = cache._replace(k=tuple(k_list), v=tuple(v_list),
                               seq_lens=lens + K)
        return jnp.stack(cols, axis=1), cache

    # ----------------------------------------------- KV tier page moves
    # (the base engine's demote/promote data paths assume the stacked
    # [L, KV, P, ps, Dh] cache; this engine's cache is a per-layer
    # TUPLE so block programs can donate one layer's pages — the tier
    # payload layout [L, KV, n, ps, Dh] stays identical, only the
    # gather/scatter changes)
    def _fetch_pages_host(self, pages):
        idx, n = self._fetch_idx(pages)
        ks = jax.device_get(tuple(k[:, idx] for k in self.cache.k))
        vs = jax.device_get(tuple(v[:, idx] for v in self.cache.v))
        return (np.stack([np.asarray(k) for k in ks])[:, :, :n],
                np.stack([np.asarray(v) for v in vs])[:, :, :n])

    def _upload_promoted(self, pages, k_host, v_host) -> None:
        idx, k_host, v_host = self._promote_idx(pages, k_host, v_host)
        k_list, v_list = list(self.cache.k), list(self.cache.v)
        for l in range(len(k_list)):
            k_list[l] = k_list[l].at[:, idx].set(
                jnp.asarray(k_host[l]), mode="drop")
            v_list[l] = v_list[l].at[:, idx].set(
                jnp.asarray(v_host[l]), mode="drop")
        self.cache = self.cache._replace(k=tuple(k_list),
                                         v=tuple(v_list))

    # -------------------------------------------- streamed→resident flip
    # (the elastic fleet's warm cold-start: a new replica spawns in
    # streamed mode — serving immediately while its weight image lives
    # on the host/NVMe tier — and the autoscaler promotes layers into
    # HBM residency between scheduler steps until the engine is fully
    # resident: the ZeRO-Inference paging made the replica cheap to
    # add, the flip makes it as fast as a resident one)
    @property
    def fully_resident(self) -> bool:
        """True once every layer's weights are HBM-resident (no tier
        reads left on the decode path)."""
        return not self._streamed_ids

    @property
    def resident_flip_blocked(self) -> bool:
        """True when ``hbm_budget_bytes`` cannot hold another resident
        layer: streaming IS this engine's steady state (the normal
        ZeRO-Inference operating point for a >HBM model) — a cold-start
        promoter should stop here, not wait for a flip that can never
        land."""
        return bool(self._streamed_ids) and not self._promote_budget_ok()

    def _promote_budget_ok(self) -> bool:
        budget = self._zi.hbm_budget_bytes
        if budget is None:
            return True
        n_res = len(self._resident)
        still_streaming = len(self._streamed_ids) > 1
        working = ((self._reader.depth + 1) * self._layer_bytes
                   if still_streaming else 0)
        after = (self.plan["stem_head_bytes"] + self.plan["cache_bytes"]
                 + (n_res + 1) * self._layer_bytes + working)
        return after <= budget

    def promote_resident_layers(self, n: int = 1) -> int:
        """Pull up to ``n`` streamed layers' weights into HBM residency
        (synchronous tier read + upload; call BETWEEN scheduler steps —
        the host drives the sweep, so nothing is mid-flight then).
        Stops early when ``hbm_budget_bytes`` cannot hold another
        resident layer.  Returns the number promoted; the engine is
        fully resident once :attr:`fully_resident` reports True."""
        done = 0
        while self._streamed_ids and done < n:
            if not self._promote_budget_ok():
                break
            l = self._streamed_ids[0]
            bufs = [self.tier.read_sync(f"zi_p_{l}_{i}", s, d)
                    for i, (s, d) in enumerate(
                        zip(self._bshapes, self._bdtypes))]
            self._resident[l] = self._upload_layer(bufs, l)
            self._streamed_ids.pop(0)
            done += 1
        return done

    # --------------------------------------------------- weight swap
    def swap_params(self, new_params, version=None) -> None:
        raise NotImplementedError(
            "the streamed engine serves a decomposed weight image "
            "(resident stem/head + tiered blocks) — use swap_weights("
            "stem, blocks, head, version=) with trees prepared like "
            "the constructor's (same quantization/sharding)")

    def swap_weights(self, stem, blocks, head, version=None) -> None:
        """Rolling-update weight swap for the streamed engine: refresh
        the tier entries of every streamed layer, re-upload the
        resident layers, re-place stem/head, and invalidate the warm
        prefix pages (old-version KV must never serve new-version
        requests).  Same drained-engine contract as
        :meth:`~deepspeed_tpu.inference.serving.ServingEngine.
        swap_params`."""
        from deepspeed_tpu.inference.serving import EngineClosed

        if self._closed:
            raise EngineClosed(
                "swap_weights on a shut-down engine"
                + (f" (replica {self.replica_id})"
                   if self.replica_id else ""))
        if self.has_work:
            raise RuntimeError(
                "swap_weights needs a drained engine (queue and slots "
                "empty) — drain the replica first so no in-flight "
                "request mixes weight versions")
        leaves, btree = jax.tree_util.tree_flatten(blocks)
        leaves = [np.asarray(a) for a in leaves]
        if btree != self._btree or any(
                a.shape[1:] != s or a.dtype != d
                for a, s, d in zip(leaves, self._bshapes,
                                   self._bdtypes)):
            raise ValueError(
                "swap_weights: new block tree does not match the "
                "served one (structure/shape/dtype) — rebuild the "
                "engine for an architecture change")
        for what, new, ref in (("stem", stem, self._stem_dev),
                               ("head", head, self._head_dev)):
            nl, nt = jax.tree_util.tree_flatten(new)
            rl, rt = jax.tree_util.tree_flatten(ref)
            if nt != rt or any(
                    getattr(a, "shape", None) != getattr(b, "shape",
                                                         None)
                    or getattr(a, "dtype", None) != getattr(b, "dtype",
                                                            None)
                    for a, b in zip(nl, rl)):
                raise ValueError(
                    f"swap_weights: new {what} tree does not match "
                    "the served one (structure/shape/dtype) — rebuild "
                    "the engine for an architecture change")
        for l in self._streamed_ids:
            for i, a in enumerate(leaves):
                self.tier.put(f"zi_p_{l}_{i}",
                              np.ascontiguousarray(a[l]))
        if isinstance(self.tier, _NvmeTier):
            self.tier.fence_all()
        for l in list(self._resident):
            self._resident[l] = self._upload_layer(
                [a[l] for a in leaves], l)
        self._stem_dev = self._place(stem, self._stem_specs)
        if "embed" in head and head["embed"] is stem["embed"]:
            head = dict(head, embed=self._stem_dev["embed"])
        self._head_dev = self._place(head, self._head_specs)
        self._invalidate_warm_pages()
        if version is not None:
            self.weights_version = version
        if self._trace_on:
            self.tracer.event("weights_swap", attrs={
                "version": str(self.weights_version)})

    # ------------------------------------------------------- inspection
    def statusz(self) -> Dict[str, Any]:
        """Base snapshot + the weight-streaming view: the residency
        plan, bytes shipped, and the stall totals that attribute a
        blown TTFT budget to the tier fence it sat behind (the
        ZeRO-Infinity / ZeRO-Offload stall-attribution question)."""
        s = ServingEngine.statusz(self)
        s["zero_inference"] = {
            "tier": self._zi.tier,
            "plan": dict(self.plan),
            # live residency (promote_resident_layers moves layers out
            # of the streamed set after the plan was stamped): the
            # elastic cold-start flip is visible here
            "n_streamed_now": len(self._streamed_ids),
            "n_resident_now": len(self._resident),
            "fully_resident": self.fully_resident,
            "layer_h2d_uploads": int(self._c_h2d.value),
            "layer_sweeps": int(self._c_sweeps.value),
            "bytes_uploaded": int(self._c_bytes.value),
            "stream_stalls": int(self._h_wait.count),
            "stream_stall_s": round(float(self._h_wait.sum), 6),
            "h2d_bandwidth_bytes_per_s": float(self._g_bw.value),
            # degradation accounting: retried fences and synchronous
            # fallback reads (nonzero = the aio channel misbehaved and
            # the stream limped on; a fatal would have postmortem'd)
            "stream_retries": int(self._reader.io_retries),
            "stream_sync_fallbacks": int(self._reader.sync_fallbacks),
        }
        return s

    def hbm_weight_working_set_bytes(self) -> int:
        """Peak weight bytes resident in HBM under the plan: stem +
        head + pinned layers + the streaming double buffer — the
        ZeRO-Inference contract (the full image never lands)."""
        return self.plan["hbm_working_set_bytes"]


# --------------------------------------------------------------- builders
_FAMILY_SKIPS = {
    # same exact-leaf sets as the resident serving builders — the
    # quantization grid must match or streamed/resident outputs diverge
    "llama": ("attn_norm", "mlp_norm", "final_norm"),
    "mixtral": ("gate", "attn_norm", "mlp_norm", "final_norm"),
}


def zero_inference_serving_engine(params, cfg, zi, *, family: str,
                                  weight_dtype: str = "bfloat16",
                                  quant_group_size: int = 128,
                                  mesh=None, **kw
                                  ) -> ZeroInferenceServingEngine:
    """Build the weight-streamed serving engine for a layered decoder
    family (ref: deepspeed-inference's init_inference with ZeRO-
    Inference offload enabled).  ``zi.dtype`` overrides
    ``weight_dtype``; int8 quantizes on the SAME per-leaf grid as the
    resident builders, so streamed int8 serving is token-identical to
    resident int8 serving."""
    zi = ZeroInferenceConfig.coerce(zi)
    if family not in _FAMILY_SKIPS:
        raise NotImplementedError(
            f"zero-inference streaming supports llama/mixtral, got "
            f"{family!r}")
    tp = mesh is not None and mesh.size("model") > 1
    sharded = mesh is not None and any(
        mesh.size(ax) > 1 for ax in ("model", "expert"))
    # one kernel-policy resolution per build, like the resident
    # builders: the per-layer block programs bake the resolved
    # paged_kernel and the engine reports the same policy in /statusz
    kw["kernels"] = _resolve_kernels_for_builder(kw.get("kernels"), mesh)
    pk = kw["kernels"].paged_attention
    if family == "mixtral":
        from deepspeed_tpu.models import mixtral as fam

        if sharded and cfg.num_experts % mesh.size("expert"):
            raise ValueError(
                f"num_experts {cfg.num_experts} not divisible by "
                f"expert-axis size {mesh.size('expert')}")
        fns = fam.paged_layered_fns(cfg, tp=sharded, paged_kernel=pk)
    else:
        from deepspeed_tpu.models import llama as fam

        fns = fam.paged_layered_fns(cfg, tp=tp, paged_kernel=pk)

    stem = {"embed": params["embed"]}
    head = {"final_norm": params["final_norm"]}
    if getattr(cfg, "tie_embeddings", False):
        head["embed"] = params["embed"]
    else:
        head["lm_head"] = params["lm_head"]
    blocks = params["blocks"]

    wd = zi.dtype or weight_dtype
    if wd != "bfloat16":
        if wd != "int8":
            raise NotImplementedError(
                f"weight-only quantized inference supports 'int8' only, "
                f"got {wd!r}")
        from deepspeed_tpu.inference.quantized import quantize_params

        skips = _FAMILY_SKIPS[family]
        q = lambda t: quantize_params(t, group_size=quant_group_size,
                                      skip_paths=skips)
        stem, blocks = q(stem), q(blocks)
        # tied embeddings: quantize the shared table ONCE and alias the
        # object — the engine dedupes shared leaves by identity, both
        # for the planner's byte accounting and the device placement
        head = q({k: v for k, v in head.items() if k != "embed"})
        if getattr(cfg, "tie_embeddings", False):
            head["embed"] = stem["embed"]

    stem_specs = head_specs = layer_specs = None
    if sharded:
        from jax.sharding import PartitionSpec as P

        specs = fam.param_specs(cfg)

        def drop_layer_dim(spec):
            if spec is None:
                return None
            if len(spec) and spec[0] is not None:
                raise ValueError(
                    f"stacked block spec {spec} shards the layer axis — "
                    "the streaming engine owns that axis (host schedule)")
            return P(*tuple(spec)[1:])

        layer_specs = jax.tree.map(
            drop_layer_dim, specs["blocks"],
            is_leaf=lambda s: s is None or isinstance(s, P))
        stem_specs = {"embed": specs["embed"]}
        head_specs = {"final_norm": specs["final_norm"]}
        if getattr(cfg, "tie_embeddings", False):
            head_specs["embed"] = specs["embed"]
        else:
            head_specs["lm_head"] = specs["lm_head"]

    return ZeroInferenceServingEngine(
        stem=stem, blocks=blocks, head=head, fns=fns, zi=zi,
        n_layers=cfg.n_layers, n_kv=cfg.n_kv_heads,
        head_dim=cfg.head_dim, mesh=mesh, stem_specs=stem_specs,
        head_specs=head_specs, layer_specs=layer_specs, **kw)
