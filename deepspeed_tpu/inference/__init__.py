"""Inference subsystem (ref: deepspeed/inference/)."""

from deepspeed_tpu.inference.engine import (InferenceEngine,
                                            init_inference, init_serving)
