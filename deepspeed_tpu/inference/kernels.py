"""Decode-optimized paged-KV attention (ref: deepspeed/ops/transformer/
inference — the decode attention kernels behind init_inference's kernel
injection, which read a preallocated KV workspace; paging per vLLM-style
block tables is the modern equivalent contract).

TPU design: KV lives in fixed-size **pages** [KV, num_pages, page_size,
Dh]; each sequence owns a list of page ids (the page table).  Decode
attention is HBM-bandwidth-bound, so the pallas kernel streams exactly
the live pages of each sequence: the page table is a **scalar-prefetch**
operand and the K/V BlockSpec index maps dereference it, so the grid's
page axis walks `table[b, p]` — gathers happen in the DMA engine, never
materialising a contiguous copy of the sequence.  Online softmax (m, l,
acc in VMEM scratch) accumulates across the page sweep; pages at or past
the sequence length are masked (their DMA reads page 0 — cheap and safe).

The jnp reference path (`paged_attention_reference`) materialises the
gather and is the numerics oracle for tests/CPU.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# ------------------------------------------------------------- page store
class PagedKVCache(NamedTuple):
    """Paged KV store for one layer stack.

    k/v: [L, KV, num_pages, page_size, Dh]; table: [B, max_pages] int32
    page ids; seq_lens: [B] int32 valid token counts.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    table: jnp.ndarray
    seq_lens: jnp.ndarray
    page_size: int
    # int8-resident mode (kv_tier.quantized_resident): k/v hold int8
    # codes and these hold the per-token-row f32 scales
    # [L, KV, num_pages, page_size, 1]; None on the plain path.
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @classmethod
    def alloc(cls, n_layers: int, n_kv: int, num_pages: int, page_size: int,
              head_dim: int, batch: int, max_seq: int,
              dtype=jnp.bfloat16) -> "PagedKVCache":
        max_pages = -(-max_seq // page_size)
        if num_pages < batch * max_pages:
            raise ValueError(
                f"num_pages {num_pages} < batch*max_pages {batch * max_pages}")
        shape = (n_layers, n_kv, num_pages, page_size, head_dim)
        # static round-robin page assignment: sequence b, slot p → page id.
        # (A dynamic free-list allocator lives host-side in PageAllocator.)
        table = (np.arange(batch)[:, None] * max_pages
                 + np.arange(max_pages)[None]).astype(np.int32)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   table=jnp.asarray(table),
                   seq_lens=jnp.zeros((batch,), jnp.int32),
                   page_size=page_size)

    def write_token(self, layer: int, new_k: jnp.ndarray,
                    new_v: jnp.ndarray) -> "PagedKVCache":
        """Append one token's K/V ([B, KV, Dh]) at each sequence's frontier.

        Raises when a sequence is at capacity (concrete seq_lens); under a
        jit trace an overflowing sequence's write is *dropped* (validity
        predicate inside ``write_token_pages``) so live KV is never
        corrupted — overflow degrades to stale attention on the final
        token rather than silently overwriting the last slot.
        """
        pos = self.seq_lens                          # [B]
        capacity = self.table.shape[1] * self.page_size
        try:
            if int(jnp.max(pos)) >= capacity:
                raise ValueError(
                    f"KV cache overflow: seq_len {int(jnp.max(pos))} at "
                    f"capacity {capacity}")
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            pass  # traced: bounded by the caller's decode-loop length
        k_l, v_l = write_token_pages(self.k[layer], self.v[layer],
                                     new_k, new_v, self.table, pos,
                                     self.page_size)
        return self._replace(k=self.k.at[layer].set(k_l),
                             v=self.v.at[layer].set(v_l))

    def bump(self) -> "PagedKVCache":
        return self._replace(seq_lens=self.seq_lens + 1)


class PageAllocator:
    """Host-side refcounted page allocator with an optional
    content-addressed warm pool (continuous-batching bookkeeping +
    automatic prefix caching).

    Every page handed out carries a reference count: ``allocate`` mints
    pages at refcount 1, ``share`` maps already-cached pages into
    another sequence with a refcount bump, and ``release`` drops one
    reference per owned page — a page is only reclaimable when its LAST
    owner releases it.  Full pages whose token span has been
    content-addressed via ``publish`` do not return to the free list on
    their last release: they enter a warm pool (capped at
    ``cache_pages``, eviction-ordered ``lru`` or ``fifo``) where their
    KV stays resident and matchable, and are only reclaimed when
    ``allocate`` finds the free list dry — allocation pressure, not
    request completion, is what erases cache.

    ``cache_pages=0`` (the default) disables publishing entirely and
    restores the classic free-list semantics: one owner per page,
    release returns pages immediately.

    Tiering (the ZeRO-Infinity idea applied to KV pages): with a
    ``spill`` pool installed (:class:`~deepspeed_tpu.inference.kv_tier.
    KVTierPool`) and a ``demote_hook``, a warm page reclaimed by
    ``_evict_one`` is offered to the hook first — on success the page's
    KV survives on a host/NVMe tier and its content key keeps matching
    through :meth:`lookup_tiered`, so eviction demotes instead of
    forgetting.  Tier hits re-enter HBM through PROMOTION: the engine
    allocates a fresh page, marks it with :meth:`begin_promotion`
    (unmatchable and unreclaimable until the payload lands), and
    :meth:`finish_promotion` publishes it once the upload completes.
    ``available`` excludes in-flight promotions so admission can never
    double-count a page as both warm and free.
    """

    def __init__(self, num_pages: int, cache_pages: int = 0,
                 eviction: str = "lru"):
        if eviction not in ("lru", "fifo"):
            raise ValueError(
                f"eviction must be 'lru' or 'fifo', got {eviction!r}")
        self.free = list(range(num_pages - 1, -1, -1))
        self.owned = {}           # seq_id -> [page, ...]
        self.refs = {}            # page -> live reference count
        self.index = {}           # content key -> page (published)
        self.key_of = {}          # page -> content key
        self.pool = {}            # page -> eviction priority (refs == 0)
        self.cache_pages = int(cache_pages)
        self.eviction = eviction
        self._published_at = {}   # page -> publish tick (fifo priority)
        self._tick = 0
        self.evicted = 0          # lifetime evicted-page count
        self.published = 0        # lifetime published-page count
        # ---- KV tiering (installed by the engine when kv_tier is on)
        self.spill = None         # KVTierPool: demoted-page index
        self.demote_hook = None   # (page, key) -> bool: capture to tier
        self.promoting = {}       # page -> key, promotion in flight
        self._parked = []         # promoting pages released pre-landing
        self.demoted = 0          # lifetime demoted-page count
        self.promoted = 0         # lifetime promoted-page count

    @property
    def available(self) -> int:
        """Pages an ``allocate`` could obtain right now: the free list
        plus the warm pool (reclaimed on demand).  Pages with an
        in-flight promotion are structurally excluded — they are owned
        (never in either list), ``_publish_full_pages`` skips them so
        they cannot enter the warm pool, and ``release`` PARKS rather
        than frees them — so an async upload can never land in a page
        this count let someone else re-allocate."""
        return len(self.free) + len(self.pool)

    def allocate(self, seq_id, n: int = 1):
        """Mint ``n`` fresh pages (refcount 1) for ``seq_id``, evicting
        warm-pool pages oldest-first when the free list runs dry."""
        if self.available < n:
            raise MemoryError(f"out of KV pages (need {n}, "
                              f"free {len(self.free)}, "
                              f"cached {len(self.pool)})")
        got = []
        for _ in range(n):
            p = self.free.pop() if self.free else self._evict_one()
            self.refs[p] = 1
            got.append(p)
        self.owned.setdefault(seq_id, []).extend(got)
        return got

    def _evict_one(self) -> int:
        p = min(self.pool, key=self.pool.get)
        del self.pool[p]
        key = self.key_of.pop(p)
        del self.index[key]
        self._published_at.pop(p, None)
        # demote instead of drop: the hook copies the page's KV to the
        # spill tier (device->host), and the key keeps matching there —
        # the physical page is reclaimed either way
        if self.demote_hook is not None and self.demote_hook(p, key):
            self.demoted += 1
        else:
            self.evicted += 1
        return p

    def oldest_warm(self, n: int):
        """The ``n`` oldest warm-pool pages with their keys — the
        watermark-demotion candidates (bookkeeping untouched; pair with
        :meth:`reclaim_warm` after the engine captured their KV)."""
        order = sorted(self.pool, key=self.pool.get)[:max(n, 0)]
        return [(p, self.key_of[p]) for p in order]

    def reclaim_warm(self, pages, demoted: bool) -> None:
        """Remove warm pages from the pool + index and free them,
        counting them demoted (their KV lives on the spill tier now) or
        evicted (dropped).  Pages that left the pool since
        :meth:`oldest_warm` (revived by a share) are skipped."""
        for p in pages:
            if p not in self.pool:
                continue
            del self.pool[p]
            del self.index[self.key_of.pop(p)]
            self._published_at.pop(p, None)
            self.free.append(p)
            if demoted:
                self.demoted += 1
            else:
                self.evicted += 1

    def lookup(self, keys):
        """Longest cached prefix: walk the chained keys in order and
        return the matched pages up to the first miss."""
        pages = []
        for k in keys:
            p = self.index.get(k)
            if p is None:
                break
            pages.append(p)
        return pages

    def lookup_tiered(self, keys):
        """Longest cached prefix across ALL tiers: walk the chained
        keys and return ``("hbm", page)`` / ``("tier", key)`` matches
        up to the first total miss.  HBM wins when a span is in both
        (a promoted page's spill copy is kept as a free re-demote)."""
        out = []
        for k in keys:
            p = self.index.get(k)
            if p is not None:
                out.append(("hbm", p))
                continue
            if self.spill is not None and self.spill.has(k):
                out.append(("tier", k))
                continue
            break
        return out

    # ------------------------------------------------------- promotion
    # (tier hit -> fresh HBM page; the engine streams the payload back
    # and calls finish; the page is quarantined from reclaim meanwhile)
    def begin_promotion(self, page: int, key: bytes) -> None:
        """Mark an allocated page as receiving a tier promotion: it
        must not be published (content hasn't landed) nor ever handed
        back out before :meth:`finish_promotion` or
        :meth:`cancel_promotion` resolves it."""
        if page not in self.refs:
            raise ValueError(f"begin_promotion of unowned page {page}")
        self.promoting[page] = key

    def finish_promotion(self, page: int, key: bytes) -> bool:
        """Payload landed: publish the page under its content key so
        concurrent same-prefix admissions share it.  A page whose owner
        vanished mid-flight (parked by ``release``) just frees.
        Returns True when the page was newly indexed."""
        self.promoting.pop(page, None)
        if page in self._parked:
            self._parked.remove(page)
            self.free.append(page)
            return False
        self.promoted += 1
        return self.publish(page, key)

    def cancel_promotion(self, page: int) -> None:
        """Abandon an in-flight promotion (preemption): the page stays
        owned by its sequence (released through the normal path) unless
        it was already parked, in which case it frees now."""
        self.promoting.pop(page, None)
        if page in self._parked:
            self._parked.remove(page)
            self.free.append(page)

    def share(self, seq_id, pages) -> None:
        """Map already-cached pages into ``seq_id``'s ownership with a
        refcount bump each; warm-pool pages revive (leave the pool) —
        the prefix-hit path.  Shared pages are READ-ONLY by contract:
        the engine only ever writes at a sequence's own frontier, which
        lies past every shared page."""
        for p in pages:
            if p in self.pool:
                del self.pool[p]
                self.refs[p] = 1
            else:
                self.refs[p] += 1
        if pages:
            self.owned.setdefault(seq_id, []).extend(pages)

    def publish(self, page: int, key: bytes) -> bool:
        """Content-address a live FULL page so future prompts can match
        it.  Dedup keeps the first publisher (an identical span already
        indexed under ``key`` wins); a page publishes at most once.
        Returns True when the page was newly indexed."""
        if self.cache_pages <= 0 or key in self.index \
                or page in self.key_of:
            return False
        if page not in self.refs:
            raise ValueError(f"publish of unowned page {page}")
        self.index[key] = page
        self.key_of[page] = key
        self._tick += 1
        self._published_at[page] = self._tick
        self.published += 1
        return True

    def writable(self, page: int) -> bool:
        """True when ``page`` may be written in place: exactly one live
        reference and never published.  A published page's CONTENT is
        pinned by its content key (a write would poison the index for
        every future match), and a shared page belongs to other
        sequences too.  Structurally the engine only ever writes at a
        sequence's own frontier, which lies past every shared/published
        page — the speculative verify sweep asserts this invariant on
        each page its K+1-position write window touches before any
        rejected-draft garbage can land (the COW-rollback guarantee)."""
        return self.refs.get(page, 0) == 1 and page not in self.key_of

    def release(self, seq_id) -> None:
        """Drop one reference per page owned by ``seq_id``.  Pages
        hitting refcount 0 return to the free list — unless published,
        in which case they enter the warm pool and keep their KV
        matchable until allocation pressure (or the pool cap) evicts
        them."""
        for p in reversed(self.owned.pop(seq_id, [])):
            self.refs[p] -= 1
            if self.refs[p]:
                continue
            del self.refs[p]
            if p in self.key_of:
                self._tick += 1
                self.pool[p] = (self._published_at[p]
                                if self.eviction == "fifo" else self._tick)
                while len(self.pool) > self.cache_pages:
                    self.free.append(self._evict_one())
            elif p in self.promoting:
                # released mid-promotion (preempt raced the upload):
                # park until the promotion resolves — freeing now could
                # hand the page to a new owner while the payload lands
                self._parked.append(p)
            else:
                self.free.append(p)


# ----------------------------------------------- per-layer page writers
# (scan-friendly: operate on ONE layer's pages [KV, P, ps, Dh] with a
# static page_size, so models can lax.scan over the layer axis)
def write_token_pages(pages_k, pages_v, new_k, new_v, table, seq_lens,
                      page_size: int):
    """Append one token's K/V ([B, KV, Dh]) at each sequence frontier.

    One vectorized scatter over the batch (no per-b unroll — decode B can
    be large under continuous batching).  A sequence at capacity writes
    its *existing* value back (no-op) instead of clamping onto the last
    live slot, so overflow never corrupts attention (advisor finding r1).
    """
    max_pages = table.shape[1]
    num_pages = pages_k.shape[1]
    capacity = max_pages * page_size
    valid = seq_lens < capacity                              # [B]
    page_slot = jnp.minimum(seq_lens // page_size, max_pages - 1)
    in_page = seq_lens % page_size
    page_id = jnp.take_along_axis(table, page_slot[:, None], axis=1)[:, 0]
    # overflow → point the scatter out of range and drop it (free: no
    # gather/blend on the hot path, the scatter itself skips the write)
    page_id = jnp.where(valid, page_id, num_pages)

    def upd(store, new):
        # store: [KV, P, ps, Dh]; new: [B, KV, Dh] → scatter [KV, B, Dh]
        vals = new.transpose(1, 0, 2).astype(store.dtype)
        return store.at[:, page_id, in_page].set(vals, mode="drop")

    return upd(pages_k, new_k), upd(pages_v, new_v)


def write_prompt_pages(pages_k, pages_v, new_k, new_v, table,
                       page_size: int):
    """Bulk-write a fresh prompt's K/V ([B, T, KV, Dh]) into pages,
    starting at position 0 (prefill of an empty cache)."""
    B, T, KV, Dh = new_k.shape
    np_used = -(-T // page_size)
    pad = np_used * page_size - T

    def upd(store, new):
        if pad:
            new = jnp.concatenate(
                [new, jnp.zeros((B, pad, KV, Dh), new.dtype)], axis=1)
        # [B, np, ps, KV, Dh] → [KV, B*np, ps, Dh]
        blocks = new.reshape(B, np_used, page_size, KV, Dh) \
            .transpose(3, 0, 1, 2, 4).reshape(KV, B * np_used,
                                              page_size, Dh)
        ids = table[:, :np_used].reshape(-1)            # [B*np]
        return store.at[:, ids].set(blocks.astype(store.dtype))

    return upd(pages_k, new_k), upd(pages_v, new_v)


def write_chunk_pages(pages_k, pages_v, new_k, new_v, table, start,
                      page_size: int):
    """Write a mid-sequence chunk's K/V ([B, C, KV, Dh]) at each row's
    frontier ``start`` ([B] i32) — the chunked-prefill generalization of
    :func:`write_prompt_pages` (arbitrary, per-row, non-page-aligned
    offsets) built from the :func:`write_token_pages` scatter, vectorized
    over the chunk axis.  Positions past a row's capacity are dropped."""
    B, C, KV, Dh = new_k.shape
    max_pages = table.shape[1]
    num_pages = pages_k.shape[1]
    capacity = max_pages * page_size
    pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None]   # [B, C]
    valid = pos < capacity
    page_slot = jnp.minimum(pos // page_size, max_pages - 1)
    in_page = pos % page_size
    page_id = jnp.take_along_axis(table, page_slot, axis=1)       # [B, C]
    page_id = jnp.where(valid, page_id, num_pages)  # out-of-range → drop

    def upd(store, new):
        # store: [KV, P, ps, Dh]; new: [B, C, KV, Dh] → [KV, B*C, Dh]
        vals = new.transpose(2, 0, 1, 3).reshape(KV, B * C, Dh)
        return store.at[:, page_id.reshape(-1), in_page.reshape(-1)].set(
            vals.astype(store.dtype), mode="drop")

    return upd(pages_k, new_k), upd(pages_v, new_v)


# ------------------------------------------- int8-resident page helpers
# (kv_tier.quantized_resident: the resident pool holds the SAME symmetric
# per-token-row int8 codec kv_tier.quantize_page uses on demote, so a
# promotion publishes stored codes directly and the attention kernel
# dequantizes in VMEM.  These are the jnp twins of the numpy codec in
# deepspeed_tpu/inference/kv_tier.py — keep the rounding identical or the
# lossless demote→promote→demote round trip breaks.)
# dstpu: hot-path
def quantize_kv_rows(x):
    """Symmetric per-last-dim-row int8 quantization of K/V rows on
    device: ``x [..., Dh]`` → ``(codes int8 [..., Dh], scales f32
    [..., 1])``.  Matches ``kv_tier.quantize_page`` bit-for-bit
    (``scale = amax/127``, zero rows get scale 1.0, round-half-even)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    codes = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return codes, scale


# dstpu: hot-path
def dequantize_pages(codes, scales, dtype):
    """Dequantize int8 page codes with their per-row scales back to
    ``dtype`` — the XLA twin of the in-kernel VMEM dequant (and the
    oracle the quant-kernel identity tests reference against)."""
    return (codes.astype(jnp.float32) * scales).astype(dtype)


def write_token_pages_quant(pages_k, pages_ks, pages_v, pages_vs,
                            new_k, new_v, table, seq_lens,
                            page_size: int):
    """:func:`write_token_pages` for the int8-resident store: quantize
    the appended rows on device and scatter codes + scales with the
    same frontier/overflow-drop math.  Scale stores are
    ``[KV, P, ps, 1]`` f32."""
    max_pages = table.shape[1]
    num_pages = pages_k.shape[1]
    capacity = max_pages * page_size
    valid = seq_lens < capacity
    page_slot = jnp.minimum(seq_lens // page_size, max_pages - 1)
    in_page = seq_lens % page_size
    page_id = jnp.take_along_axis(table, page_slot[:, None], axis=1)[:, 0]
    page_id = jnp.where(valid, page_id, num_pages)

    def upd(store, sstore, new):
        codes, scale = quantize_kv_rows(new)          # [B, KV, Dh/1]
        return (store.at[:, page_id, in_page].set(
                    codes.transpose(1, 0, 2), mode="drop"),
                sstore.at[:, page_id, in_page].set(
                    scale.transpose(1, 0, 2), mode="drop"))

    pk, pks = upd(pages_k, pages_ks, new_k)
    pv, pvs = upd(pages_v, pages_vs, new_v)
    return pk, pks, pv, pvs


def write_prompt_pages_quant(pages_k, pages_ks, pages_v, pages_vs,
                             new_k, new_v, table, page_size: int):
    """:func:`write_prompt_pages` for the int8-resident store (prefill
    of an empty cache, quantizing per token row)."""
    B, T, KV, Dh = new_k.shape
    np_used = -(-T // page_size)
    pad = np_used * page_size - T

    def upd(store, sstore, new):
        codes, scale = quantize_kv_rows(new)     # [B,T,KV,Dh], [B,T,KV,1]
        if pad:
            codes = jnp.concatenate(
                [codes, jnp.zeros((B, pad, KV, Dh), codes.dtype)], axis=1)
            # zero rows carry scale 1.0 by the codec's convention
            scale = jnp.concatenate(
                [scale, jnp.ones((B, pad, KV, 1), scale.dtype)], axis=1)
        ids = table[:, :np_used].reshape(-1)

        def blocks(x, d):
            return x.reshape(B, np_used, page_size, KV, d) \
                .transpose(3, 0, 1, 2, 4).reshape(KV, B * np_used,
                                                  page_size, d)

        return (store.at[:, ids].set(blocks(codes, Dh)),
                sstore.at[:, ids].set(blocks(scale, 1)))

    pk, pks = upd(pages_k, pages_ks, new_k)
    pv, pvs = upd(pages_v, pages_vs, new_v)
    return pk, pks, pv, pvs


def write_chunk_pages_quant(pages_k, pages_ks, pages_v, pages_vs,
                            new_k, new_v, table, start, page_size: int):
    """:func:`write_chunk_pages` for the int8-resident store (split-fuse
    continuation chunks at per-row frontiers)."""
    B, C, KV, Dh = new_k.shape
    max_pages = table.shape[1]
    num_pages = pages_k.shape[1]
    capacity = max_pages * page_size
    pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
    valid = pos < capacity
    page_slot = jnp.minimum(pos // page_size, max_pages - 1)
    in_page = pos % page_size
    page_id = jnp.take_along_axis(table, page_slot, axis=1)
    page_id = jnp.where(valid, page_id, num_pages)

    def upd(store, sstore, new):
        codes, scale = quantize_kv_rows(new)
        cvals = codes.transpose(2, 0, 1, 3).reshape(KV, B * C, Dh)
        svals = scale.transpose(2, 0, 1, 3).reshape(KV, B * C, 1)
        ids, ip = page_id.reshape(-1), in_page.reshape(-1)
        return (store.at[:, ids, ip].set(cvals, mode="drop"),
                sstore.at[:, ids, ip].set(svals, mode="drop"))

    pk, pks = upd(pages_k, pages_ks, new_k)
    pv, pvs = upd(pages_v, pages_vs, new_v)
    return pk, pks, pv, pvs


# -------------------------------------------------------- numerics oracle
def paged_chunk_attention_reference(q, k_pages, v_pages, table, start,
                                    scale: Optional[float] = None):
    """Chunked-prefill attention: q [B, C, H, Dh] at positions
    ``start + 0..C-1`` attends causally over the gathered pages (which
    must already contain the chunk's own K/V).  Returns [B, C, H, Dh].

    This is the split-fuse read path: history + chunk in one masked
    gather, so a long prompt can be absorbed ``C`` tokens per iteration
    between decode steps."""
    B, C, H, Dh = q.shape
    KV, _, ps, _ = k_pages.shape
    G = H // KV
    scale = scale if scale is not None else Dh ** -0.5
    mp = table.shape[1]
    kg = k_pages[:, table].transpose(1, 0, 2, 3, 4).reshape(
        B, KV, mp * ps, Dh)
    vg = v_pages[:, table].transpose(1, 0, 2, 3, 4).reshape(
        B, KV, mp * ps, Dh)
    qg = q.reshape(B, C, KV, G, Dh)
    s = jnp.einsum("bckgd,bksd->bckgs", qg.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale
    kpos = jnp.arange(mp * ps)[None, None]                  # [1, 1, S]
    qpos = (start[:, None] + jnp.arange(C)[None])[:, :, None]  # [B, C, 1]
    s = jnp.where((kpos <= qpos)[:, :, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bckgs,bksd->bckgd", p, vg.astype(jnp.float32))
    return out.reshape(B, C, H, Dh).astype(q.dtype)


def paged_attention_reference(q, k_pages, v_pages, table, seq_lens,
                              scale: Optional[float] = None):
    """q: [B, H, Dh]; k/v_pages: [KV, P, ps, Dh]; table: [B, max_pages];
    seq_lens: [B]. Returns [B, H, Dh]."""
    B, H, Dh = q.shape
    KV, _, ps, _ = k_pages.shape
    G = H // KV
    scale = scale if scale is not None else Dh ** -0.5
    kg = k_pages[:, table]                     # [KV, B, mp, ps, Dh]
    vg = v_pages[:, table]
    mp = table.shape[1]
    kg = kg.transpose(1, 0, 2, 3, 4).reshape(B, KV, mp * ps, Dh)
    vg = vg.transpose(1, 0, 2, 3, 4).reshape(B, KV, mp * ps, Dh)
    qg = q.reshape(B, KV, G, Dh)
    s = jnp.einsum("bkgd,bksd->bkgs", qg.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale
    valid = jnp.arange(mp * ps)[None] < seq_lens[:, None]   # [B, S]
    s = jnp.where(valid[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgs,bksd->bkgd", p, vg.astype(jnp.float32))
    # empty sequences (continuous batching admits them): zero, not mean-of-V
    out = jnp.where(seq_lens[:, None, None, None] > 0, out, 0.0)
    return out.reshape(B, H, Dh).astype(q.dtype)


# ------------------------------------------------------------ pallas kernel
def paged_decode_attention(q, k_pages, v_pages, table, seq_lens,
                           scale: Optional[float] = None,
                           interpret: bool = False):
    """Pallas paged decode attention; same contract as the reference fn.

    q: [B, H, Dh] (one decode step), k/v_pages: [KV, P, ps, Dh].

    Decode IS the C=1 chunked-prefill case — the query sits at position
    ``seq_lens - 1`` and attends ``kpos <= seq_lens - 1`` — so one kernel
    (:func:`_chunk_kernel`) serves both paths and any accumulator fix
    lands exactly once.  Empty rows (seq_lens == 0) resolve to start -1:
    every position masks out and the finalize's l==0 guard yields zeros,
    matching the reference's empty-sequence contract.
    """
    return paged_chunk_attention(
        q[:, None], k_pages, v_pages, table, seq_lens - 1, scale=scale,
        interpret=interpret)[:, 0]


# --------------------------------------- multi-page-per-step decode kernel
def paged_decode_attention_v2(q, k_pages, v_pages, table, seq_lens,
                              scale: Optional[float] = None,
                              pages_per_block: int = 8,
                              interpret: bool = False):
    """Multi-page-per-step paged decode attention (same contract as
    :func:`paged_attention_reference` / :func:`paged_decode_attention`).

    q: [B, H, Dh] (one decode step), k/v_pages: [KV, P, ps, Dh],
    table: [B, mp] int32, seq_lens: [B] int32.  Pages live in HBM
    (``pl.ANY``) and are DMA-streamed ``pages_per_block`` at a time per
    (batch, kv_head) grid step with double buffering; only live pages
    are read, and stale table entries past seq_len are never
    dereferenced.  This is the fix for the measured v1 failure
    (KERNEL_BENCH r5: one 16-token page per GRID step = B*KV*mp tiny
    dispatches, 145 ms where the XLA gather runs 5.8 ms).

    Decode IS the C=1 chunked case (v1 makes the same delegation): the
    query sits at position ``seq_lens - 1`` and attends
    ``kpos <= seq_lens - 1``, so ONE kernel serves both paths and any
    accumulator/DMA fix lands exactly once."""
    return paged_chunk_attention_v2(
        q[:, None], k_pages, v_pages, table, seq_lens - 1, scale=scale,
        pages_per_block=pages_per_block, interpret=interpret)[:, 0]


# ----------------------------------- multi-page chunked-prefill kernel (v2)
def _chunk_v2_kernel(table_ref, start_ref, q_ref, k_hbm, v_hbm, o_ref, *,
                     scale, ps, kv_heads, max_pages, cg8, group, chunk,
                     ppcb):
    """The multi-page v2 kernel (decode shares it:
    :func:`paged_decode_attention_v2` delegates here as the C=1 chunked
    case — there is no separate decode kernel): one grid step
    per (batch, kv_head); K/V pages stream ppcb at a time through a
    double-buffered VMEM scratch, and the page sweep stops at the last
    page holding any position ``<= start + C - 1`` (history + chunk),
    so pages past the frontier are never read.  Rows are the flattened
    [C*G] chunk queries; row r sits at position start + r // G."""
    bk = pl.program_id(0)
    b = bk // kv_heads
    h = bk % kv_heads
    start = start_ref[b]
    live = start + chunk                            # positions 0..live-1
    pages_live = (live + ps - 1) // ps
    nch = (pages_live + ppcb - 1) // ppcb

    def body(kb, vb, sem):
        def chunk_dmas(c, slot):
            dmas = []
            for j in range(ppcb):                   # static unroll
                p = c * ppcb + j
                psafe = jnp.minimum(p, max_pages - 1)
                pid = jnp.where(p < pages_live, table_ref[b, psafe], 0)
                dmas.append(pltpu.make_async_copy(
                    k_hbm.at[h, pid], kb.at[slot, pl.ds(j * ps, ps), :],
                    sem.at[slot, 0]))
                dmas.append(pltpu.make_async_copy(
                    v_hbm.at[h, pid], vb.at[slot, pl.ds(j * ps, ps), :],
                    sem.at[slot, 1]))
            return dmas

        @pl.when(nch > 0)
        def _():
            for d in chunk_dmas(0, 0):
                d.start()

        q = q_ref[0].astype(jnp.float32)            # [cg8, Dh]

        def loop(c, carry):
            m, l, acc = carry
            slot = jax.lax.rem(c, 2)

            @pl.when(c + 1 < nch)
            def _():
                for d in chunk_dmas(c + 1, jax.lax.rem(c + 1, 2)):
                    d.start()

            for d in chunk_dmas(c, slot):
                d.wait()
            k = kb[slot].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            kpos = c * (ppcb * ps) + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            qpos = start + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0) // group
            s = jnp.where(kpos <= qpos, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            pr = jnp.exp(s - m_new)
            # defensive: no current masking pattern can leave a whole
            # block masked while m == NEG_INF (block 0 always holds
            # kpos=0 <= qpos, and empty rows skip the loop via nch=0),
            # but a future mask (e.g. segments) would turn that corner
            # into pr == 1 row-wide — keep exp's masked entries at 0
            pr = jnp.where(s > NEG_INF / 2, pr, 0.0)
            l = l * alpha + jnp.sum(pr, axis=1, keepdims=True)
            acc = acc * alpha + jax.lax.dot_general(
                pr, vb[slot].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l, acc

        init = (jnp.full((cg8, 1), NEG_INF, jnp.float32),
                jnp.zeros((cg8, 1), jnp.float32),
                jnp.zeros((cg8, q_ref.shape[2]), jnp.float32))
        m, l, acc = jax.lax.fori_loop(0, nch, loop, init)
        l = jnp.where(l == 0.0, 1.0, l)             # empty rows → zeros
        o_ref[0] = (acc / l).astype(o_ref.dtype)

    pl.run_scoped(
        body,
        kb=pltpu.VMEM((2, ppcb * ps, q_ref.shape[2]), k_hbm.dtype),
        vb=pltpu.VMEM((2, ppcb * ps, q_ref.shape[2]), v_hbm.dtype),
        sem=pltpu.SemaphoreType.DMA((2, 2)),
    )


def paged_chunk_attention_v2(q, k_pages, v_pages, table, start,
                             scale: Optional[float] = None,
                             pages_per_block: int = 8,
                             interpret: bool = False):
    """Multi-page chunked-prefill attention — same contract as
    :func:`paged_chunk_attention_reference`, built like
    :func:`paged_decode_attention_v2` (HBM-resident pages, explicit
    double-buffered DMA, live-pages-only sweep)."""
    B, C, H, Dh = q.shape
    KV, P, ps, _ = k_pages.shape
    G = H // KV
    mp = table.shape[1]
    scale = scale if scale is not None else Dh ** -0.5
    ppcb = max(1, min(pages_per_block, mp))
    CG = C * G
    cg8 = -(-CG // 8) * 8
    qg = q.reshape(B, C, KV, G, Dh).transpose(0, 2, 1, 3, 4) \
        .reshape(B * KV, CG, Dh)
    if cg8 != CG:
        qg = jnp.concatenate(
            [qg, jnp.zeros((B * KV, cg8 - CG, Dh), q.dtype)], axis=1)

    kernel = functools.partial(
        _chunk_v2_kernel, scale=scale, ps=ps, kv_heads=KV, max_pages=mp,
        cg8=cg8, group=G, chunk=C, ppcb=ppcb)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,   # table, start
            grid=(B * KV,),
            in_specs=[
                pl.BlockSpec((1, cg8, Dh), lambda bk, tbl, st: (bk, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, cg8, Dh), lambda bk, tbl, st: (bk, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B * KV, cg8, Dh), q.dtype),
        interpret=interpret,
    )(table, start, qg, k_pages, v_pages)
    out = out[:, :CG].reshape(B, KV, C, G, Dh).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, C, H, Dh)


# ------------------------- int8-dequant-fused multi-page chunked kernel
def _chunk_v2_quant_kernel(table_ref, start_ref, q_ref, kq_hbm, ks_hbm,
                           vq_hbm, vs_hbm, o_ref, *, scale, ps, kv_heads,
                           max_pages, cg8, group, chunk, ppcb):
    """:func:`_chunk_v2_kernel` over int8-resident pages: per page the
    DMA streams the int8 codes AND the per-token-row f32 scales
    (``[ps, 1]`` — the same (N, 1) VMEM layout the v1 kernel's m/l
    scratch uses), and the dequant ``codes * scale`` happens in VMEM
    right before the dot — the gathered f32 K/V transient never exists
    in HBM.  Everything else (double buffering, live-page sweep, online
    softmax, masking) is the v2 kernel unchanged."""
    bk = pl.program_id(0)
    b = bk // kv_heads
    h = bk % kv_heads
    start = start_ref[b]
    live = start + chunk
    pages_live = (live + ps - 1) // ps
    nch = (pages_live + ppcb - 1) // ppcb

    def body(kqb, ksb, vqb, vsb, sem):
        def chunk_dmas(c, slot):
            dmas = []
            for j in range(ppcb):                   # static unroll
                p = c * ppcb + j
                psafe = jnp.minimum(p, max_pages - 1)
                pid = jnp.where(p < pages_live, table_ref[b, psafe], 0)
                dmas.append(pltpu.make_async_copy(
                    kq_hbm.at[h, pid],
                    kqb.at[slot, pl.ds(j * ps, ps), :], sem.at[slot, 0]))
                dmas.append(pltpu.make_async_copy(
                    ks_hbm.at[h, pid],
                    ksb.at[slot, pl.ds(j * ps, ps), :], sem.at[slot, 1]))
                dmas.append(pltpu.make_async_copy(
                    vq_hbm.at[h, pid],
                    vqb.at[slot, pl.ds(j * ps, ps), :], sem.at[slot, 2]))
                dmas.append(pltpu.make_async_copy(
                    vs_hbm.at[h, pid],
                    vsb.at[slot, pl.ds(j * ps, ps), :], sem.at[slot, 3]))
            return dmas

        @pl.when(nch > 0)
        def _():
            for d in chunk_dmas(0, 0):
                d.start()

        q = q_ref[0].astype(jnp.float32)            # [cg8, Dh]

        def loop(c, carry):
            m, l, acc = carry
            slot = jax.lax.rem(c, 2)

            @pl.when(c + 1 < nch)
            def _():
                for d in chunk_dmas(c + 1, jax.lax.rem(c + 1, 2)):
                    d.start()

            for d in chunk_dmas(c, slot):
                d.wait()
            # VMEM dequant: per-token-row scales broadcast over Dh
            k = kqb[slot].astype(jnp.float32) * ksb[slot]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            kpos = c * (ppcb * ps) + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            qpos = start + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0) // group
            s = jnp.where(kpos <= qpos, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            pr = jnp.exp(s - m_new)
            pr = jnp.where(s > NEG_INF / 2, pr, 0.0)
            l = l * alpha + jnp.sum(pr, axis=1, keepdims=True)
            v = vqb[slot].astype(jnp.float32) * vsb[slot]
            acc = acc * alpha + jax.lax.dot_general(
                pr, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l, acc

        init = (jnp.full((cg8, 1), NEG_INF, jnp.float32),
                jnp.zeros((cg8, 1), jnp.float32),
                jnp.zeros((cg8, q_ref.shape[2]), jnp.float32))
        m, l, acc = jax.lax.fori_loop(0, nch, loop, init)
        l = jnp.where(l == 0.0, 1.0, l)             # empty rows → zeros
        o_ref[0] = (acc / l).astype(o_ref.dtype)

    pl.run_scoped(
        body,
        kqb=pltpu.VMEM((2, ppcb * ps, q_ref.shape[2]), kq_hbm.dtype),
        ksb=pltpu.VMEM((2, ppcb * ps, 1), jnp.float32),
        vqb=pltpu.VMEM((2, ppcb * ps, q_ref.shape[2]), vq_hbm.dtype),
        vsb=pltpu.VMEM((2, ppcb * ps, 1), jnp.float32),
        sem=pltpu.SemaphoreType.DMA((2, 4)),
    )


# dstpu: hot-path
def paged_chunk_attention_v2_quant(q, kq_pages, ks_pages, vq_pages,
                                   vs_pages, table, start,
                                   scale: Optional[float] = None,
                                   pages_per_block: int = 8,
                                   interpret: bool = False):
    """Int8-dequant-fused chunked-prefill attention: same contract as
    :func:`paged_chunk_attention_reference` over
    ``dequantize_pages(kq, ks) / (vq, vs)``, but the dequant happens in
    VMEM inside the page sweep — the ~2x-smaller int8 pages are what
    crosses HBM.  ``kq/vq_pages``: int8 ``[KV, P, ps, Dh]``;
    ``ks/vs_pages``: f32 ``[KV, P, ps, 1]`` per-token-row scales (the
    ``kv_tier.quantize_page`` codec)."""
    B, C, H, Dh = q.shape
    KV, P, ps, _ = kq_pages.shape
    G = H // KV
    mp = table.shape[1]
    scale = scale if scale is not None else Dh ** -0.5
    ppcb = max(1, min(pages_per_block, mp))
    CG = C * G
    cg8 = -(-CG // 8) * 8
    qg = q.reshape(B, C, KV, G, Dh).transpose(0, 2, 1, 3, 4) \
        .reshape(B * KV, CG, Dh)
    if cg8 != CG:
        qg = jnp.concatenate(
            [qg, jnp.zeros((B * KV, cg8 - CG, Dh), q.dtype)], axis=1)

    kernel = functools.partial(
        _chunk_v2_quant_kernel, scale=scale, ps=ps, kv_heads=KV,
        max_pages=mp, cg8=cg8, group=G, chunk=C, ppcb=ppcb)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,   # table, start
            grid=(B * KV,),
            in_specs=[
                pl.BlockSpec((1, cg8, Dh), lambda bk, tbl, st: (bk, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, cg8, Dh), lambda bk, tbl, st: (bk, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B * KV, cg8, Dh), q.dtype),
        interpret=interpret,
    )(table, start, qg, kq_pages, ks_pages, vq_pages, vs_pages)
    out = out[:, :CG].reshape(B, KV, C, G, Dh).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, C, H, Dh)


# dstpu: hot-path
def paged_decode_attention_v2_quant(q, kq_pages, ks_pages, vq_pages,
                                    vs_pages, table, seq_lens,
                                    scale: Optional[float] = None,
                                    pages_per_block: int = 8,
                                    interpret: bool = False):
    """Int8-dequant-fused paged decode attention — the C=1 chunked case,
    exactly as :func:`paged_decode_attention_v2` delegates."""
    return paged_chunk_attention_v2_quant(
        q[:, None], kq_pages, ks_pages, vq_pages, vs_pages, table,
        seq_lens - 1, scale=scale, pages_per_block=pages_per_block,
        interpret=interpret)[:, 0]


# ------------------------------------------- pallas chunked-prefill kernel
def _chunk_kernel(table_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale, page_size, kv_heads,
                  max_pages, group, chunk):
    """Chunk rows are flattened [C*G, Dh]; row r is query position
    r // G of the chunk.  Causal frontier per row: start + r//G."""
    bk = pl.program_id(0)
    p = pl.program_id(1)
    b = bk // kv_heads

    @pl.when(p == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = lens_ref[b]
    # page live iff it holds any position <= start + C - 1
    @pl.when(p * page_size < start + chunk)
    def _():
        q = q_ref[0]                        # [CG, Dh]
        k = k_ref[0]                        # [ps, Dh]
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [CG, ps]
        kpos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // group
        s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pr = jnp.exp(s - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(pr, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pr, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(p == max_pages - 1)
    def _():
        l = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def paged_chunk_attention(q, k_pages, v_pages, table, start,
                          scale: Optional[float] = None,
                          interpret: bool = False):
    """Pallas chunked-prefill attention — same contract as
    :func:`paged_chunk_attention_reference` but streaming pages through
    the DMA engine instead of materializing the gather.

    q: [B, C, H, Dh] at positions ``start + 0..C-1`` (the chunk's K/V
    must already be written into the pages).  NOTE: correctness is pinned
    by interpret-mode tests; the on-chip win over the gather reference is
    to be confirmed in KERNEL_BENCH before this becomes the small-shape
    default (the decode kernel's measured policy applies meanwhile).
    """
    B, C, H, Dh = q.shape
    KV, P, ps, _ = k_pages.shape
    G = H // KV
    mp = table.shape[1]
    scale = scale if scale is not None else Dh ** -0.5
    CG = C * G
    pad = (-CG) % 8                      # sublane alignment
    qg = q.reshape(B, C, KV, G, Dh).transpose(0, 2, 1, 3, 4) \
        .reshape(B * KV, CG, Dh)
    if pad:
        qg = jnp.concatenate(
            [qg, jnp.zeros((B * KV, pad, Dh), q.dtype)], axis=1)

    kernel = functools.partial(
        _chunk_kernel, scale=scale, page_size=ps, kv_heads=KV,
        max_pages=mp, group=G, chunk=C)

    def kv_map(bk, p, tbl, lens):
        b = bk // KV
        pid = jnp.where(p * ps < lens[b] + C, tbl[b, p], 0)
        return ((bk % KV) * P + pid, 0, 0)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,   # table, start
            grid=(B * KV, mp),
            in_specs=[
                pl.BlockSpec((1, CG + pad, Dh),
                             lambda bk, p, tbl, lens: (bk, 0, 0)),
                pl.BlockSpec((1, ps, Dh), kv_map),
                pl.BlockSpec((1, ps, Dh), kv_map),
            ],
            out_specs=pl.BlockSpec(
                (1, CG + pad, Dh), lambda bk, p, tbl, lens: (bk, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((CG + pad, 1), jnp.float32),
                pltpu.VMEM((CG + pad, 1), jnp.float32),
                pltpu.VMEM((CG + pad, Dh), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * KV, CG + pad, Dh), q.dtype),
        interpret=interpret,
    )(table, start, qg, k_pages.reshape(KV * P, ps, Dh),
      v_pages.reshape(KV * P, ps, Dh))
    out = out[:, :CG].reshape(B, KV, C, G, Dh).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, C, H, Dh)


# --------------------------------------------- shared per-layer dispatch
# Crossover for the auto policy: total live-KV bytes (K+V pages a full-
# occupancy decode sweep reads) below which the XLA gather composition
# wins.  Anchored on KERNEL_BENCH.json: the r5 v5e rows show the gather
# at ~1-6 ms across every small/mid decode shape (nothing for a kernel
# to claw back below ~256 MiB of live KV), and the paged_v2_vs_xla
# crossover-sweep rows carry the forced-on v2 arm next to the gather at
# each shape so the threshold is re-derivable from committed evidence.
# The v2 kernel fixes the measured v1 failure (one 16-token page per
# grid step = B*KV*mp tiny dispatches, 25x slower at the largest shape)
# by streaming ppcb pages per inner iteration through double-buffered
# DMA — the regime where that pays is big-KV decode, where the sweep is
# HBM-bandwidth-bound and the gather's materialized transient stops
# fitting anywhere useful.  Re-stamp the sweep on chip before lowering
# this.
_PAGED_V2_MIN_KV_BYTES = 1 << 28


def pallas_paged_gate(B: int, n_kv: int, head_dim: int, page_size: int,
                      max_pages: int, kv_itemsize: int,
                      interpret: bool, tp: bool) -> bool:
    """The shape-dependent ``auto`` policy for the paged Pallas kernels,
    shared by every model's paged forward: True when the multi-page v2
    kernel should replace the XLA gather composition for this shape.

    Pure shape math — no env reads.  Env/config overrides are resolved
    ONCE at engine build by :func:`resolve_serving_kernels` (which
    passes an explicit ``paged_kernel`` down, bypassing this gate), so
    an already-compiled program can never disagree with the visible
    policy.  The crossover is total live-KV bytes per decode sweep
    (``_PAGED_V2_MIN_KV_BYTES``, see the comment above): below it the
    measured XLA gather is already ~ms-fast; above it the double-
    buffered DMA sweep streams the pages the gather would materialize.

    ``interpret`` (CPU) always takes the reference path — interpret-mode
    kernels are a correctness harness, not a fast path.  ``tp`` also
    returns False: the kernel is per-device and the serving engines
    surface that demotion VISIBLY (``serving_kernel_fallbacks`` counter
    + a ``/statusz`` reason via :func:`resolve_serving_kernels`) rather
    than silently as before."""
    if interpret or tp:
        return False
    live_kv_bytes = (2 * B * n_kv * max_pages * page_size * head_dim
                     * kv_itemsize)
    return live_kv_bytes >= _PAGED_V2_MIN_KV_BYTES


class ServingKernelPolicy(NamedTuple):
    """The kernel-dispatch policy an engine build resolved — baked into
    the compiled programs and surfaced verbatim in ``/statusz``."""

    paged_attention: str            # auto | xla | pallas_v1 | pallas_v2
    fused_sampling: str             # off | on
    # (field, value, source) for every env var that overrode the config
    env_overrides: Tuple[Tuple[str, str, str], ...] = ()
    # (field, demoted_to, reason) for forced choices the build demoted
    fallbacks: Tuple[Tuple[str, str, str], ...] = ()

    def as_dict(self) -> dict:
        return {
            "paged_attention": self.paged_attention,
            "fused_sampling": self.fused_sampling,
            "env_overrides": [list(o) for o in self.env_overrides],
            "fallbacks": [{"field": f, "demoted_to": d, "reason": r}
                          for f, d, r in self.fallbacks],
        }


def resolve_serving_kernels(kernels=None, *, tp: bool = False,
                            interpret: bool = False) -> ServingKernelPolicy:
    """Resolve the serving kernel-dispatch policy ONCE, at engine build.

    ``kernels``: a ``KernelsConfig`` / dict / None (all-auto).  Env vars
    are the overrides of last resort and are read HERE — never again at
    trace time — so the policy a program compiled with is exactly the
    policy ``/statusz`` reports: ``DSTPU_PAGED_ATTENTION`` /
    ``DSTPU_FUSED_SAMPLING`` name a mode directly, and the legacy
    spellings ``DSTPU_FORCE_PAGED_PALLAS=1`` (→ ``pallas_v2``, or
    ``pallas_v1`` when ``DSTPU_PAGED_V1=1`` rides along) and
    ``DSTPU_FORCE_FUSED_SAMPLING=1`` (→ ``on``) keep working.

    A forced Pallas paged kernel under tensor parallelism is demoted to
    ``xla`` with a recorded reason — the kernel dereferences the full
    page table per (batch, kv_head) grid step and KV heads are sharded
    over the mesh, so per-device it would read pages it does not hold;
    the demotion is VISIBLE (``fallbacks`` row + the engine's
    ``serving_kernel_fallbacks`` counter), fixing the old silent
    ``tp → False``.

    An already-resolved :class:`ServingKernelPolicy` passes through
    untouched — the model builders resolve once and hand the SAME
    policy to the engine, so the kernels the closures baked and the
    policy ``/statusz`` reports can never drift."""
    from deepspeed_tpu.config import KernelsConfig

    if isinstance(kernels, ServingKernelPolicy):
        return kernels
    cfg = KernelsConfig.coerce(kernels)
    paged = cfg.paged_attention
    fused = cfg.fused_sampling
    env_overrides = []
    env_pa = os.environ.get("DSTPU_PAGED_ATTENTION", "")
    if env_pa:
        if env_pa not in ("auto", "xla", "pallas_v1", "pallas_v2"):
            raise ValueError(
                f"DSTPU_PAGED_ATTENTION must be auto|xla|pallas_v1|"
                f"pallas_v2, got {env_pa!r}")
        paged = env_pa
        env_overrides.append(
            ("paged_attention", env_pa, "DSTPU_PAGED_ATTENTION"))
    elif os.environ.get("DSTPU_FORCE_PAGED_PALLAS", "") == "1":
        paged = ("pallas_v1"
                 if os.environ.get("DSTPU_PAGED_V1", "") == "1"
                 else "pallas_v2")
        env_overrides.append(
            ("paged_attention", paged, "DSTPU_FORCE_PAGED_PALLAS"))
    env_fs = os.environ.get("DSTPU_FUSED_SAMPLING", "")
    if env_fs:
        if env_fs not in ("auto", "off", "on"):
            raise ValueError(
                f"DSTPU_FUSED_SAMPLING must be auto|off|on, got "
                f"{env_fs!r}")
        fused = env_fs
        env_overrides.append(
            ("fused_sampling", env_fs, "DSTPU_FUSED_SAMPLING"))
    elif os.environ.get("DSTPU_FORCE_FUSED_SAMPLING", "") == "1":
        fused = "on"
        env_overrides.append(
            ("fused_sampling", "on", "DSTPU_FORCE_FUSED_SAMPLING"))

    fallbacks = []
    if tp and paged in ("pallas_v1", "pallas_v2"):
        fallbacks.append((f"paged_attention={paged}", "xla",
                          "tp_unsupported: KV heads are sharded over "
                          "the mesh; the kernel reads the full page "
                          "table per device"))
        paged = "xla"
    if fused == "auto":
        # the measured policy (KERNEL_BENCH.json fused_sample_vs_xla):
        # sampling is one [B, V] argmax — the jitted XLA twin wins at
        # every serving shape in the committed sweep, so auto resolves
        # off and the fused kernel stays a forced arm until a chip
        # re-stamp says otherwise (see ops/sampling_pallas.py)
        from deepspeed_tpu.ops.sampling_pallas import pallas_sample_gate

        fused = "on" if pallas_sample_gate(interpret=interpret) else "off"
    return ServingKernelPolicy(
        paged_attention=paged, fused_sampling=fused,
        env_overrides=tuple(env_overrides), fallbacks=tuple(fallbacks))


def paged_attention_step(q, k, v, kp, vp, table, start, page_size: int, *,
                         continuation: bool, prefill: bool,
                         paged_kernel: str,
                         flash_force_reference: bool,
                         interpret: bool = False,
                         kps=None, vps=None):
    """The per-layer paged-attention step every model family shares:
    page writes + the right attention for the phase.

    q: [B, T, H, Dh]; k/v: [B, T, KV, Dh]; kp/vp: one layer's pages.
    ``paged_kernel`` is the RESOLVED dispatch ("xla" | "pallas_v1" |
    "pallas_v2" — the gate/policy decided before the trace; no env
    reads here).  A forced Pallas kernel with ``interpret=True`` runs
    in interpret mode — that is an explicit request and exactly how the
    CPU identity gates exercise the kernels.  ``kps``/``vps`` non-None
    selects the int8-resident path: kp/vp hold int8 codes, kps/vps the
    per-token-row f32 scales, writes quantize on device, and
    "pallas_v2" dispatches the dequant-fused kernel ("xla" dequantizes
    with :func:`dequantize_pages` and runs the references; there is no
    quantized v1).  Phases: chunked-prefill continuation (split-fuse),
    whole-prompt prefill (empty cache), or single-token decode.
    Returns (attn [B, T, H, Dh], kp, vp, kps, vps)."""
    from deepspeed_tpu.ops.attention import flash_attention

    quant = kps is not None
    if quant and paged_kernel == "pallas_v1":
        raise ValueError("int8-resident pages have no pallas_v1 kernel "
                         "(use xla or pallas_v2)")
    if continuation and q.shape[1] > 1:
        if quant:
            kp, kps, vp, vps = write_chunk_pages_quant(
                kp, kps, vp, vps, k, v, table, start, page_size)
            if paged_kernel == "pallas_v2":
                attn = paged_chunk_attention_v2_quant(
                    q, kp, kps, vp, vps, table, start,
                    interpret=interpret)
            else:
                attn = paged_chunk_attention_reference(
                    q, dequantize_pages(kp, kps, q.dtype),
                    dequantize_pages(vp, vps, q.dtype), table, start)
        else:
            kp, vp = write_chunk_pages(kp, vp, k, v, table, start,
                                       page_size)
            if paged_kernel == "pallas_v1":
                attn = paged_chunk_attention(q, kp, vp, table, start,
                                             interpret=interpret)
            elif paged_kernel == "pallas_v2":
                attn = paged_chunk_attention_v2(q, kp, vp, table, start,
                                                interpret=interpret)
            else:
                attn = paged_chunk_attention_reference(q, kp, vp, table,
                                                       start)
    elif prefill:
        attn = flash_attention(q, k, v, causal=True,
                               force_reference=flash_force_reference)
        if quant:
            kp, kps, vp, vps = write_prompt_pages_quant(
                kp, kps, vp, vps, k, v, table, page_size)
        else:
            kp, vp = write_prompt_pages(kp, vp, k, v, table, page_size)
    else:
        if quant:
            kp, kps, vp, vps = write_token_pages_quant(
                kp, kps, vp, vps, k[:, 0], v[:, 0], table, start,
                page_size)
            if paged_kernel == "pallas_v2":
                attn = paged_decode_attention_v2_quant(
                    q[:, 0], kp, kps, vp, vps, table, start + 1,
                    interpret=interpret)[:, None]
            else:
                attn = paged_attention_reference(
                    q[:, 0], dequantize_pages(kp, kps, q.dtype),
                    dequantize_pages(vp, vps, q.dtype), table,
                    start + 1)[:, None]
        else:
            kp, vp = write_token_pages(kp, vp, k[:, 0], v[:, 0], table,
                                       start, page_size)
            if paged_kernel == "pallas_v1":
                attn = paged_decode_attention(
                    q[:, 0], kp, vp, table, start + 1,
                    interpret=interpret)[:, None]
            elif paged_kernel == "pallas_v2":
                attn = paged_decode_attention_v2(
                    q[:, 0], kp, vp, table, start + 1,
                    interpret=interpret)[:, None]
            else:
                attn = paged_attention_reference(
                    q[:, 0], kp, vp, table, start + 1)[:, None]
    return attn, kp, vp, kps, vps


def paged_forward_prelude(cache, tokens, interpret, tp,
                          continuation: bool):
    """Shared preamble for every model's ``forward_paged``: resolve the
    interpret/tp defaults (ambient mesh consulted only when tp is None —
    serving closures pass it explicitly), derive the page size and
    ragged per-row start offsets, and guard the whole-prompt prefill
    against a non-empty cache.  Returns (interpret, tp, ps, start,
    prefill)."""
    import jax as _jax

    ps = cache.k.shape[3]
    if interpret is None:
        interpret = _jax.default_backend() != "tpu"
    if tp is None:
        from deepspeed_tpu.topology import current_mesh as _cm

        _ms = _cm()
        tp = _ms is not None and _ms.size("model") > 1
    start = cache.seq_lens
    prefill = tokens.shape[1] > 1 and not continuation
    if prefill:
        try:
            if int(jnp.max(start)) != 0:
                raise ValueError(
                    "forward_paged prefill (T>1) requires an empty "
                    "cache; pass continuation=True for chunked prefill")
        except (_jax.errors.TracerArrayConversionError,
                _jax.errors.ConcretizationTypeError):
            pass  # traced: caller's responsibility
    return interpret, tp, ps, start, prefill
