"""Kernel/module injection (ref: deepspeed/module_inject/*).

The reference walks a torch module tree and swaps HF layers for fused
CUDA "DeepSpeedTransformer" blocks, guided by per-architecture policies
(ref: module_inject/replace_policy.py, containers/llama.py, bert.py …).

TPU design: our models are pure functions, so "injection" is (a) a policy
registry mapping architecture names → our model family + weight-layout
converter + TP spec tree, and (b) kernel selection flags (attn_impl →
pallas flash / ring / ulysses) applied to the model config.  The public
``inject`` entrypoint takes an HF-style config dict + state dict and
returns (apply_fn, params, specs) ready for the InferenceEngine — the
functional equivalent of ``replace_transformer_layer``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class InjectionPolicy:
    """Per-architecture policy (ref: module_inject/replace_policy.py
    DSPolicy subclasses)."""

    arch: str
    build_config: Callable      # hf_config dict -> model config
    convert_weights: Callable   # (state_dict, cfg) -> params pytree
    apply_fn: Callable          # (params, tokens, cfg) -> logits
    param_specs: Callable       # cfg -> TP spec tree


_REGISTRY: Dict[str, InjectionPolicy] = {}


def register_policy(policy: InjectionPolicy) -> None:
    _REGISTRY[policy.arch.lower()] = policy


def get_policy(arch: str) -> InjectionPolicy:
    try:
        return _REGISTRY[arch.lower()]
    except KeyError:
        raise ValueError(
            f"no injection policy for architecture {arch!r}; "
            f"registered: {sorted(_REGISTRY)}") from None


def inject(arch: str, hf_config: Dict[str, Any], state_dict=None,
           attn_impl: str = "auto", dtype=jnp.bfloat16):
    """ref: module_inject.replace_module — returns (apply_fn, params, cfg,
    specs); ``state_dict`` maps HF tensor names → numpy arrays (pass the
    result of integrations/hf.py load_safetensors)."""
    pol = get_policy(arch)
    cfg = pol.build_config(hf_config)
    if hasattr(cfg, "attn_impl"):
        cfg.attn_impl = attn_impl
    params = None
    if state_dict is not None:
        params = pol.convert_weights(state_dict, cfg)
        params = _cast_floating(params, dtype)
    fn = lambda p, tokens: pol.apply_fn(p, tokens, cfg)
    return fn, params, cfg, pol.param_specs(cfg)


def _cast_floating(tree, dtype):
    import jax

    return jax.tree.map(
        lambda x: jnp.asarray(x, dtype)
        if np.issubdtype(np.asarray(x).dtype, np.floating) else jnp.asarray(x),
        tree)


# ----------------------------------------------------------- llama policy
def _llama_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=hf.get("vocab_size", 32000),
        dim=hf.get("hidden_size", 4096),
        n_layers=hf.get("num_hidden_layers", 32),
        n_heads=hf.get("num_attention_heads", 32),
        n_kv_heads=hf.get("num_key_value_heads",
                          hf.get("num_attention_heads", 32)),
        ffn_dim=hf.get("intermediate_size"),
        max_seq_len=hf.get("max_position_embeddings", 2048),
        rope_theta=hf.get("rope_theta", 10000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )


def _llama_weights(sd: Dict[str, np.ndarray], cfg):
    """HF Llama layout → our stacked pytree (torch Linear stores W^T:
    HF [out, in] → ours [in, out])."""
    L = cfg.n_layers
    t = lambda name: np.asarray(sd[name]).T
    stack = lambda fmt: np.stack(
        [t(fmt.format(i)) for i in range(L)])
    stack_raw = lambda fmt: np.stack(
        [np.asarray(sd[fmt.format(i)]) for i in range(L)])
    params = {
        "embed": np.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": stack_raw("model.layers.{}.input_layernorm.weight"),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack_raw(
                "model.layers.{}.post_attention_layernorm.weight"),
            "w1": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w3": stack("model.layers.{}.mlp.up_proj.weight"),
            "w2": stack("model.layers.{}.mlp.down_proj.weight"),
        },
        "final_norm": np.asarray(sd["model.norm.weight"]),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = np.asarray(sd["lm_head.weight"]).T
    return params


def _mixtral_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.mixtral import MixtralConfig

    return MixtralConfig(
        vocab_size=hf.get("vocab_size", 32000),
        dim=hf.get("hidden_size", 4096),
        n_layers=hf.get("num_hidden_layers", 32),
        n_heads=hf.get("num_attention_heads", 32),
        n_kv_heads=hf.get("num_key_value_heads", 8),
        ffn_dim=hf.get("intermediate_size"),
        num_experts=hf.get("num_local_experts", 8),
        top_k=hf.get("num_experts_per_tok", 2),
        max_seq_len=hf.get("max_position_embeddings", 4096),
        rope_theta=hf.get("rope_theta", 1e6),
        norm_eps=hf.get("rms_norm_eps", 1e-5),
    )


def _mixtral_weights(sd: Dict[str, np.ndarray], cfg):
    """HF Mixtral layout → stacked [L, ...] / [L, E, ...] pytree."""
    L, E = cfg.n_layers, cfg.num_experts
    t = lambda name: np.asarray(sd[name]).T
    stack = lambda fmt: np.stack([t(fmt.format(i)) for i in range(L)])
    stack_raw = lambda fmt: np.stack(
        [np.asarray(sd[fmt.format(i)]) for i in range(L)])
    estack = lambda fmt: np.stack(
        [np.stack([t(fmt.format(i, e)) for e in range(E)])
         for i in range(L)])
    moe = "model.layers.{}.block_sparse_moe"
    return {
        "embed": np.asarray(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": stack_raw("model.layers.{}.input_layernorm.weight"),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack_raw(
                "model.layers.{}.post_attention_layernorm.weight"),
            "gate": stack(moe + ".gate.weight"),
            "w1": estack(moe + ".experts.{}.w1.weight"),
            "w3": estack(moe + ".experts.{}.w3.weight"),
            "w2": estack(moe + ".experts.{}.w2.weight"),
        },
        "final_norm": np.asarray(sd["model.norm.weight"]),
        "lm_head": np.asarray(sd["lm_head.weight"]).T,
    }


def _register_builtin():
    from deepspeed_tpu.models import llama, mixtral

    for arch in ("llama", "llamaforcausallm"):
        register_policy(InjectionPolicy(
            arch=arch,
            build_config=_llama_config,
            convert_weights=_llama_weights,
            apply_fn=lambda p, tokens, cfg: llama.forward(p, tokens, cfg),
            param_specs=lambda cfg: llama.param_specs(cfg),
        ))
    for arch in ("mixtral", "mixtralforcausallm"):
        register_policy(InjectionPolicy(
            arch=arch,
            build_config=_mixtral_config,
            convert_weights=_mixtral_weights,
            # eval forward: capacity-free dense top-k combine — injected
            # inference must never drop tokens on router imbalance
            apply_fn=lambda p, tokens, cfg: mixtral.forward_eval(
                p, tokens, cfg),
            param_specs=lambda cfg: mixtral.param_specs(cfg),
        ))


_register_builtin()
