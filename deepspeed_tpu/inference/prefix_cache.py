"""Content-addressing for paged-KV prefix caching (ref: vLLM automatic
prefix caching / SGLang RadixAttention — block-hash prefix reuse over a
paged KV pool).

A full KV page holds the K/V of one ``page_size``-token span.  Under
causal attention, that span's K/V depends ONLY on the tokens at and
before it — so a page written for tokens ``t[0:ps]`` of one request is
bit-identical to the page any other request with the same leading
tokens would write, and can be mapped read-only into that request's
page table instead of being recomputed.

Keys are CHAINED hashes: page ``k``'s key digests page ``k-1``'s key
plus page ``k``'s own token span.  The chain makes the flat
``{key: page}`` index behave as a radix trie over page-aligned token
prefixes — walking a prompt's keys in order and stopping at the first
miss yields exactly the longest cached page-aligned prefix, and two
prompts sharing a span mid-sequence but not the tokens before it can
never alias (their chains diverged earlier).

blake2b/16-byte digests: collisions are negligible (~2^-64 at any
realistic pool size), and a collision would require an adversarially
constructed token sequence, not traffic.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Sequence, Tuple

_SEED = b"dstpu-prefix-cache-v1"


@dataclasses.dataclass
class TierEntry:
    """Index record of ONE demoted page: which tier holds its KV and in
    what encoding.  The HBM index (``PageAllocator.index``) maps key →
    physical page; everything evicted OUT of HBM keeps matching through
    these records in the :class:`~deepspeed_tpu.inference.kv_tier.
    KVTierPool` — the chained-key walk treats an entry in ANY tier as a
    hit, it just re-admits through promotion instead of a refcount bump.

    ``buffers`` is the per-buffer geometry of the spilled payload —
    ``(name, shape, dtype)`` triples; 2 buffers (k, v) on the bit-exact
    path, 4 (k codes, k scales, v codes, v scales) when the page was
    quantized cold.  ``data`` holds the host arrays while the entry is
    host-resident; an NVMe-resident entry's payload lives in the files
    named by ``buffers`` and ``data`` is None."""

    key: bytes
    location: str                 # "host" | "nvme"
    quantized: bool
    dtype: str                    # the PAGE dtype promotion restores
    buffers: Tuple[Tuple[str, tuple, str], ...]
    nbytes: int
    data: Optional[tuple] = None  # host arrays iff location == "host"
    tick: int = 0                 # age for the host->nvme->drop cascade
    # per-buffer crc32 recorded at demote time and verified on promote:
    # a mismatch (bit rot, torn spill write, injected corruption) drops
    # the entry and the consumer re-prefills instead of serving garbage
    checksums: Optional[Tuple[int, ...]] = None

    @property
    def names(self) -> List[str]:
        return [b[0] for b in self.buffers]


def key_hex(key: bytes) -> str:
    """Canonical short form of a page key for file names / trace
    attrs."""
    return key.hex()


def page_key(prev_key: bytes, span: Sequence[int]) -> bytes:
    """Key of one full page: digest of the previous page's key (the
    prefix chain) + this page's token span."""
    h = hashlib.blake2b(prev_key, digest_size=16)
    h.update(b"".join(int(t).to_bytes(4, "little", signed=True)
                      for t in span))
    return h.digest()


def extend_page_keys(keys: List[bytes], tokens: Sequence[int],
                     n_pages: int, page_size: int) -> List[bytes]:
    """Extend a chained key list IN PLACE to cover the first
    ``n_pages`` full pages of ``tokens``.  The chain only ever grows
    (token prefixes are immutable), so callers cache the list on the
    request and each publish/match event hashes just the new pages
    instead of re-walking the whole sequence."""
    prev = keys[-1] if keys else _SEED
    for k in range(len(keys), n_pages):
        prev = page_key(prev, tokens[k * page_size:(k + 1) * page_size])
        keys.append(prev)
    return keys


def page_keys(tokens: Sequence[int], page_size: int) -> List[bytes]:
    """Chained keys for every FULL page of ``tokens`` (the trailing
    partial page has no key — only immutable full pages are shareable).
    """
    return extend_page_keys([], tokens, len(tokens) // page_size,
                            page_size)


def matchable_pages(prompt_len: int, page_size: int) -> int:
    """How many leading full pages of a ``prompt_len``-token prompt are
    eligible to match: at least ONE prompt token must always go through
    prefill (the engine needs logits at the last prompt position to
    sample the first generated token), so a fully page-aligned prompt
    gives up its final page.  This is the vLLM rule (cap the match at
    ``len(prompt) - 1`` tokens), page-aligned."""
    return max(prompt_len - 1, 0) // page_size
