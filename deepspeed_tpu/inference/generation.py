"""Autoregressive generation: KV cache, prefill/decode split, sampling.

Reference behavior: deepspeed/inference/engine.py generate path +
ops/transformer/inference kernels (decode attention over a KV cache,
static cache allocation, greedy/temperature sampling).

TPU design: the cache is a static-shape ``[L, B, max_seq, KV, Dh]`` pytree
(XLA needs static shapes — no dynamic growth); prefill and decode are two
separately-jitted programs.  Prefill processes the whole prompt at once
(MXU-friendly big matmuls); decode steps one token with
``lax.dynamic_update_slice`` cache writes and masked attention up to the
current length.  Sampling (greedy/temperature/top-k/top-p) runs on-device
inside the decode jit so generation never round-trips to host per token.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    """Static-shape KV cache; ``length`` = number of valid positions."""

    k: jnp.ndarray          # [L, B, maxT, KV, Dh]
    v: jnp.ndarray          # [L, B, maxT, KV, Dh]
    length: jnp.ndarray     # i32 scalar

    @classmethod
    def alloc(cls, n_layers: int, batch: int, max_seq: int, n_kv: int,
              head_dim: int, dtype=jnp.bfloat16) -> "KVCache":
        shape = (n_layers, batch, max_seq, n_kv, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))


def cached_attention(q, k_cache, v_cache, new_k, new_v, start_pos,
                     scale: Optional[float] = None):
    """Attention of q against cache[:start_pos+T] (ref: the reference's
    decode-attention kernel contract: softmax(q @ K^T) @ V with the causal
    frontier at start_pos + local position).

    q: [B, T, H, Dh]; caches [B, maxT, KV, Dh]; new_k/v: [B, T, KV, Dh].
    Returns (out [B, T, H, Dh], k_cache, v_cache) with new_k/v written at
    ``start_pos``.
    """
    B, T, H, Dh = q.shape
    maxT, KV = k_cache.shape[1], k_cache.shape[2]
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, new_k.astype(k_cache.dtype), (0, start_pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, new_v.astype(v_cache.dtype), (0, start_pos, 0, 0))
    if KV != H:
        rep = H // KV
        k = jnp.repeat(k_cache, rep, axis=2)
        v = jnp.repeat(v_cache, rep, axis=2)
    else:
        k, v = k_cache, v_cache
    scale = scale if scale is not None else Dh ** -0.5
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    kpos = jnp.arange(maxT)
    qpos = start_pos + jnp.arange(T)
    mask = kpos[None, :] <= qpos[:, None]          # [T, maxT]
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs,
                     v.astype(jnp.float32)).astype(q.dtype)
    return out, k_cache, v_cache


def sample_logits(logits, rng, temperature: float = 1.0,
                  top_k: int = 0, top_p: float = 1.0):
    """logits: [B, V] → token ids [B].  temperature==0 → greedy."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; cutoff logit value
        keep = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class Generator:
    """Model-agnostic generation loop over jitted prefill/decode.

    prefill_fn(params, tokens, cache) -> (logits [B,T,V], cache)
    decode_fn(params, token [B,1], cache) -> (logits [B,1,V], cache)
    alloc_cache(batch, max_seq) -> KVCache
    """

    def __init__(self, params, prefill_fn, decode_fn, alloc_cache,
                 eos_token_id: Optional[int] = None):
        self.params = params
        self._prefill = jax.jit(prefill_fn)
        self._decode = jax.jit(decode_fn)
        self._alloc = alloc_cache
        self.eos = eos_token_id

    def generate(self, tokens, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
                 rng: Optional[jax.Array] = None, max_seq: Optional[int] = None):
        """tokens: [B, T] prompt → [B, T + max_new_tokens] (eos-padded)."""
        return generate_loop(
            self.params, self._prefill, self._decode, self._alloc, tokens,
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, rng=rng, max_seq=max_seq, eos=self.eos)


def generate_loop(params, prefill, decode, alloc_cache, tokens,
                  max_new_tokens: int = 32, temperature: float = 0.0,
                  top_k: int = 0, top_p: float = 1.0,
                  rng: Optional[jax.Array] = None,
                  max_seq: Optional[int] = None, eos: Optional[int] = None):
    """The host-side autoregressive loop shared by :class:`Generator` and
    the hybrid engine: prefill once, then decode one token at a time with
    on-device sampling.  ``prefill``/``decode`` must already be jitted.

    Always returns ``[B, T + max_new_tokens]`` — early all-eos exits pad
    with eos so callers (jitted train steps, slicing code) see one static
    shape regardless of where generation stopped.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    B, T = tokens.shape
    total = max_seq or (T + max_new_tokens)
    if T + max_new_tokens > total:
        # dynamic_update_slice CLAMPS out-of-bounds cache writes, so an
        # overrun would silently corrupt the rollout instead of failing
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"KV cache budget (max_seq={total}) — raise max_seq or shorten "
            "the prompt")
    cache = alloc_cache(B, total)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    logits, cache = prefill(params, tokens, cache)
    out = [tokens]
    rng, step_rng = jax.random.split(rng)
    next_tok = sample_logits(logits[:, -1], step_rng, temperature,
                             top_k, top_p)[:, None]
    done = jnp.zeros((B,), bool)
    for produced in range(1, max_new_tokens + 1):
        out.append(next_tok)
        if eos is not None:
            done = done | (next_tok[:, 0] == eos)
            if produced < max_new_tokens and bool(done.all()):
                out.append(jnp.full((B, max_new_tokens - produced), eos,
                                    jnp.int32))
                break
        if produced == max_new_tokens:
            break
        logits, cache = decode(params, next_tok, cache)
        rng, step_rng = jax.random.split(rng)
        nxt = sample_logits(logits[:, -1], step_rng, temperature,
                            top_k, top_p)[:, None]
        if eos is not None:
            nxt = jnp.where(done[:, None], jnp.int32(eos), nxt)
        next_tok = nxt
    return jnp.concatenate(out, axis=1)


def greedy_draft_fn(step, alloc_cache, window: int, k: int):
    """One-dispatch greedy rollout for speculative drafting (see
    :class:`~deepspeed_tpu.inference.speculative.ModelDrafter`): jit of
    ``(params, tokens [B, window]) -> drafts [B, k]`` — prefill the
    (left-padded) history window once, then ``lax.scan`` ``k`` argmax
    decode steps feeding each token forward.  Everything stays on
    device until the caller fetches the k drafts, so a draft proposal
    costs one dispatch + one transfer regardless of ``k``.

    Drafts only gate PERFORMANCE (the verify pass re-scores them under
    the target model), so the fixed window and its padded positions
    trade draft quality for a single compiled shape — never
    correctness."""

    def rollout(params, tokens):
        cache = alloc_cache(tokens.shape[0], window + k)
        logits, cache = step(params, tokens, cache)
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

        def one(carry, _):
            tok, c = carry
            logits, c = step(params, tok[:, None], c)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (nxt, c), tok

        (_, _), toks = jax.lax.scan(one, (first, cache), None, length=k)
        return jnp.swapaxes(toks, 0, 1)                   # [B, k]

    return jax.jit(rollout)


def cached_step_alloc(forward_with_cache, cfg, cache_dtype=jnp.bfloat16):
    """The (step, alloc_cache) pair over any model's
    ``forward_with_cache(params, tokens, cfg, cache)`` — shared by the
    generators and the hybrid engine so the cache wiring lives once."""
    def alloc(batch, max_seq):
        return KVCache.alloc(cfg.n_layers, batch, max_seq, cfg.n_kv_heads,
                             cfg.head_dim, dtype=cache_dtype)

    def step(params, tokens, cache):
        return forward_with_cache(params, tokens, cfg, cache)

    return step, alloc


def llama_step_alloc(cfg, cache_dtype=jnp.bfloat16):
    from deepspeed_tpu.models import llama

    return cached_step_alloc(llama.forward_with_cache, cfg, cache_dtype)


def llama_generator(params, cfg, eos_token_id: Optional[int] = None,
                    cache_dtype=jnp.bfloat16) -> Generator:
    """Build a :class:`Generator` for models/llama.py weights."""
    step, alloc = llama_step_alloc(cfg, cache_dtype)
    return Generator(params, step, step, alloc, eos_token_id=eos_token_id)


def gpt2_generator(params, cfg, eos_token_id: Optional[int] = None,
                   cache_dtype=jnp.bfloat16) -> Generator:
    """Cached-attention generation for models/gpt2.py weights."""
    from deepspeed_tpu.models import gpt2

    step, alloc = cached_step_alloc(gpt2.forward_with_cache, cfg,
                                    cache_dtype)

    def checked_alloc(batch, max_seq):
        # learned positions: a traced wpe gather CLAMPS out-of-range
        # indices, so generating past the table would silently reuse the
        # last position's embedding — fail here instead (RoPE models have
        # no such table and need no check)
        if max_seq > cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens ({max_seq}) exceeds gpt2's "
                f"learned position table ({cfg.max_seq_len})")
        return alloc(batch, max_seq)

    return Generator(params, step, step, checked_alloc,
                     eos_token_id=eos_token_id)


def mixtral_generator(params, cfg, eos_token_id: Optional[int] = None,
                      cache_dtype=jnp.bfloat16) -> Generator:
    """MoE text generation (ref: DeepSpeed-MoE inference): cached
    attention + capacity-free dense top-k expert combine."""
    from deepspeed_tpu.models import mixtral

    step, alloc = cached_step_alloc(mixtral.forward_with_cache, cfg,
                                    cache_dtype)
    return Generator(params, step, step, alloc, eos_token_id=eos_token_id)


def _paged_generator(forward_paged, params, cfg,
                     eos_token_id: Optional[int] = None,
                     page_size: int = 16, num_pages: Optional[int] = None,
                     cache_dtype=jnp.bfloat16) -> Generator:
    """Shared paged-KV generator over any ``forward_paged(params, tokens,
    cfg, cache)`` — cache sizing and wiring live once, model families
    supply only their forward."""
    from deepspeed_tpu.inference.kernels import PagedKVCache

    def alloc(batch, max_seq):
        mp = -(-max_seq // page_size)
        n = num_pages if num_pages is not None else batch * mp
        return PagedKVCache.alloc(cfg.n_layers, cfg.n_kv_heads, n, page_size,
                                  cfg.head_dim, batch, max_seq,
                                  dtype=cache_dtype)

    def step(params, tokens, cache):
        return forward_paged(params, tokens, cfg, cache)

    return Generator(params, step, step, alloc, eos_token_id=eos_token_id)


def llama_paged_generator(params, cfg, **kw) -> Generator:
    """Paged-KV variant: decode streams only live pages via the pallas
    paged-attention kernel (ref contract: deepspeed/ops/transformer/
    inference decode kernels + their preallocated KV workspace)."""
    from deepspeed_tpu.models import llama

    return _paged_generator(llama.forward_paged, params, cfg, **kw)


def mixtral_paged_generator(params, cfg, **kw) -> Generator:
    """Paged-KV MoE generation — the offline oracle for Mixtral serving
    (ref: DeepSpeed-MoE inference engine's generate path)."""
    from deepspeed_tpu.models import mixtral

    return _paged_generator(mixtral.forward_paged, params, cfg, **kw)


def gpt2_paged_generator(params, cfg, **kw) -> Generator:
    """Paged-KV GPT-2 generation — the offline oracle for GPT-2 serving
    (ref: gpt2 kernel-injection container)."""
    from deepspeed_tpu.models import gpt2

    return _paged_generator(gpt2.forward_paged, params, cfg, **kw)
