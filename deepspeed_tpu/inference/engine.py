"""Inference engine (ref: deepspeed/inference/engine.py InferenceEngine).

The reference wraps a torch module, injects fused kernels
(module_inject) and shards weights across GPUs (``mp_size``).  Here the
engine jits the model's apply function over the mesh with TP shardings;
generation (KV cache, prefill/decode split, sampling) lands with the
model families — this core provides the forward path and the
``init_inference`` entrypoint contract.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu import precision
from deepspeed_tpu.config import Config, PrecisionConfig
from deepspeed_tpu.topology import MeshSpec, default_mesh
from deepspeed_tpu.zero import SpecTree, param_shardings


class InferenceEngine:
    """Jitted forward over sharded params.

    ``apply_fn(params, *inputs)`` is the model's pure forward function.
    """

    def __init__(self, apply_fn: Callable, params: Any,
                 mesh: Optional[MeshSpec] = None,
                 param_specs: SpecTree = None,
                 dtype: str = "bfloat16", quant_group_size: int = 128):
        self.mesh = mesh or default_mesh()
        placed = None
        if dtype == "int8":
            # weight-only quantization (ref: init_inference(dtype=int8)):
            # int8 codes + group scales resident in HBM, dequant traced
            # into the forward so it fuses with each weight's consumer
            from deepspeed_tpu.inference.quantized import (
                quantize_for_inference, shard_quantized)
            from deepspeed_tpu.zero import resolve_specs

            # resolve TP specs against the ORIGINAL tree: after
            # quantization the leaves are (codes, scales) pairs
            specs = (None if param_specs is None
                     else resolve_specs(params, param_specs))
            params, apply_fn = quantize_for_inference(
                params, apply_fn, group_size=quant_group_size)
            if specs is not None:
                # int8 composes with TP: codes take the weight's spec,
                # per-row scales shard alongside (ref: module_inject's
                # int8 + mp_size injection)
                placed = shard_quantized(params, specs, self.mesh)
        else:
            pcfg = PrecisionConfig(dtype=dtype)
            params = precision.cast_for_compute(params, pcfg)
        self.apply_fn = apply_fn
        if placed is None:
            # reached with dtype != int8, or int8 + no specs (the int8 +
            # specs case produced `placed` above)
            shardings = param_shardings(params, self.mesh, stage=0,
                                        param_specs=param_specs)
            placed = jax.jit(lambda p: p, out_shardings=shardings)(params)
        self.params = placed

        def fwd(p, *inputs):
            # publish this engine's mesh at trace time (model code may read
            # current_mesh() for ring/ulysses/MoE sharded ops)
            from deepspeed_tpu import topology as _topo

            _topo.set_current_mesh(self.mesh)
            return apply_fn(p, *inputs)

        self._fwd = jax.jit(fwd)

    def __call__(self, *inputs):
        return self._fwd(self.params, *inputs)

    def forward(self, *inputs):
        return self(*inputs)


def init_inference(model: Any = None, *, apply_fn: Optional[Callable] = None,
                   params: Any = None, config: Any = None,
                   mesh: Optional[MeshSpec] = None,
                   param_specs: SpecTree = None,
                   dtype: str = "bfloat16", quant_group_size: int = 128,
                   **_compat) -> InferenceEngine:
    """ref: deepspeed.init_inference(model, config…) → engine.

    ``model`` may be an object with ``.apply``/``.params`` (flax-style) or
    pass ``apply_fn`` + ``params`` explicitly.
    """
    if isinstance(config, dict):
        config = Config.from_dict(config)
    if apply_fn is None:
        if model is None or not hasattr(model, "apply"):
            raise ValueError("provide apply_fn+params or a model with .apply")
        apply_fn = model.apply
        params = params if params is not None else getattr(model, "params", None)
    if params is None:
        raise ValueError("init_inference requires params")
    return InferenceEngine(apply_fn, params, mesh=mesh,
                           param_specs=param_specs, dtype=dtype,
                           quant_group_size=quant_group_size)


def serving_mesh_from_config(config: Any) -> Optional[MeshSpec]:
    """Resolve the serving TP mesh from a config ``mesh`` block.

    Serving shards params/KV over the ``model`` (TP) and ``expert``
    (EP) axes; the ``data`` axis is a training concept (one replica
    serves its whole batch), so a ``data: -1`` left at its default is
    read as 1 here and the engine spans exactly
    ``pipe*expert*seq*model`` devices from the front of
    ``jax.devices()`` — e.g. ``{"mesh": {"model": 2}}`` builds a
    2-device TP replica no matter how many chips the host exposes
    (the fleet hands later device slices to later replicas).  Returns
    None when every non-data axis is 1 (the single-device engine)."""
    mc = config.mesh
    sizes = {"pipe": mc.pipe, "data": mc.data, "expert": mc.expert,
             "seq": mc.seq, "model": mc.model}
    if sizes["data"] not in (1, -1):
        # a reused training config: data parallelism is meaningless for
        # one serving replica (the fleet is the data axis here), so an
        # explicit data>1 must not multiply the device demand 8x or
        # trip the device-count check on a small host
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            "serving mesh: ignoring mesh.data=%s — one serving replica "
            "has no data axis (replicate via the fleet instead)",
            sizes["data"])
    sizes["data"] = 1
    if all(int(v) <= 1 for v in sizes.values()):
        return None
    total = 1
    for v in sizes.values():
        total *= int(v)
    devs = jax.devices()
    if total > len(devs):
        raise ValueError(
            f"serving mesh {sizes} needs {total} devices, host exposes "
            f"{len(devs)}")
    return MeshSpec.build(sizes, devices=devs[:total])


def init_serving(params, model_config, *, config: Any = None,
                 mesh: Optional[MeshSpec] = None, **kw):
    """Serving counterpart of :func:`init_inference` (ref: the reference
    serves through ``init_inference`` + DeepSpeed-MII's serve loop):
    build the continuous-batching engine for a model-family config,
    honoring a DeepSpeed-style JSON config.

    A ``mesh`` block in ``config`` builds a TP/EP-sharded serving
    replica (see :func:`serving_mesh_from_config` for how the axis
    sizes are read); an explicit ``mesh=`` kw still wins.

    A ``zero_inference`` block in ``config`` routes to the weight-
    streamed ZeRO-Inference engine
    (:mod:`deepspeed_tpu.inference.zero_inference`): layer weights live
    on a host/NVMe tier and stream double-buffered through a bounded
    HBM working set, so the served weight image may exceed HBM.  Its
    ``dtype`` field (e.g. ``int8``) overrides ``weight_dtype``.

    A ``prefix_cache`` block enables automatic prefix caching on the
    paged-KV path: full KV pages are content-addressed, prompts sharing
    a page-aligned prefix with earlier traffic skip that prefix's
    prefill compute, and freed pages stay warm until allocation
    pressure reclaims them (token-identical on/off).

    A ``speculative`` block enables draft-and-verify multi-token
    decoding (:mod:`deepspeed_tpu.inference.speculative`): each decode
    iteration drafts up to K cheap tokens per slot, verifies all K+1
    positions in one batched forward, and keeps the accepted span —
    greedy outputs token-identical on/off, and under ``zero_inference``
    one verify sweep amortizes one full layer-weight stream over the
    whole accepted span.

    Remaining ``kw`` (``max_batch``, ``page_size``, ``num_pages``,
    ``decode_chunk``, ``prefill_chunk``, ``weight_dtype``,
    ``prefix_cache``, ``admit_lookahead``, …) pass through to the
    family builder.
    """
    from deepspeed_tpu.inference.serving import serving_engine

    if isinstance(config, dict):
        config = Config.from_dict(config)
    if mesh is None and config is not None:
        # `mesh` block → TP/EP-sharded serving replica (an explicit
        # mesh= kw still wins); see serving_mesh_from_config for the
        # serving reading of the axis sizes
        mesh = serving_mesh_from_config(config)
    if config is not None and config.zero_inference.enabled:
        kw.setdefault("zero_inference", config.zero_inference)
    if config is not None and config.prefix_cache.enabled:
        # `prefix_cache` block → refcounted content-addressed paged-KV
        # prefix caching in the engine (an explicit prefix_cache= kw
        # still wins)
        kw.setdefault("prefix_cache", config.prefix_cache)
    if config is not None and config.kv_tier.enabled:
        # `kv_tier` block → host/NVMe spill + cold-page quantization
        # for the paged prefix pool (an explicit kv_tier= kw still
        # wins); requires the prefix_cache block — the engine validates
        kw.setdefault("kv_tier", config.kv_tier)
    if config is not None and config.speculative.enabled:
        # `speculative` block → draft-and-verify multi-token decode
        # (an explicit speculative= kw still wins; a model drafter
        # instance rides the separate drafter= kw)
        kw.setdefault("speculative", config.speculative)
    if config is not None and config.slo.enabled:
        # `slo` block → per-tier SLO classification, burn-rate alerts
        # and goodput accounting on the engine's registry (an explicit
        # slo= kw still wins)
        kw.setdefault("slo", config.slo)
    if config is not None and config.faults.enabled:
        # `faults` block → deterministic fault injection for the
        # robustness/chaos machinery (an explicit faults= kw still
        # wins); a TEST facility — see CONFIG.md before enabling
        kw.setdefault("faults", config.faults)
    if config is not None and config.history.enabled:
        # `history` block → multi-resolution metric-history rings
        # sampled on the exporter tick (an explicit history= kw still
        # wins); serves /historyz and the incident bundles' pre-trip
        # windows
        kw.setdefault("history", config.history)
    if config is not None and config.incidents.enabled:
        # `incidents` block → the incident engine: trigger-event
        # subscription + EWMA anomaly detectors, deduped atomic
        # incident bundles (an explicit incidents= kw still wins)
        kw.setdefault("incidents", config.incidents)
    if config is not None:
        # `telemetry` config block → the engine's MetricsRegistry (an
        # explicit telemetry= kw still wins)
        kw.setdefault("telemetry", config.telemetry)
        # `tracing` block → the engine's RequestTracer flight recorder
        # (per-request event timelines + hang postmortems)
        kw.setdefault("tracing", config.tracing)
        # `kernels` block → the serving kernel-dispatch policy
        # (paged_attention / fused_sampling), resolved ONCE at engine
        # build with env vars as overrides of last resort.  No
        # .enabled guard: "auto" IS the default policy, so the block
        # always passes through (an explicit kernels= kw still wins)
        kw.setdefault("kernels", config.kernels)
    return serving_engine(params, model_config, mesh=mesh, **kw)
