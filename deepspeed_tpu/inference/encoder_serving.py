"""Encoder-model serving: shape-bucketed micro-batching (ref: the
reference kernel-injects BERT-class encoders and serves them through
``init_inference`` — deepspeed/module_inject/containers/bert.py; its
inference engine covers non-autoregressive models as a first-class
case).

TPU design: an encoder has no decode loop, so FastGen-style
iteration-level scheduling degenerates to LOT BATCHING — queued
requests are grouped into static ``(max_batch, bucket_len)`` lots, one
jit per bucket length, no retraces.  Padding rows/positions are masked
(the pad tokens attend only each other and their outputs are sliced
off on the host), so a request's result is independent of its
lot-mates — the encoder analogue of continuous batching's isolation
guarantee.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _encoder_telemetry(telemetry):
    """Same loose telemetry contract as the decoder ServingEngine:
    None/bool/dict/TelemetryConfig build a registry (plus an exporter
    when sink keys are set); an existing MetricsRegistry is shared and
    the caller owns its sinks.  Returns ``(registry, exporter)``."""
    from deepspeed_tpu.config import TelemetryConfig
    from deepspeed_tpu.telemetry import MetricsRegistry, TelemetryExporter

    if isinstance(telemetry, MetricsRegistry):
        return telemetry, None
    tcfg = TelemetryConfig.coerce(telemetry)
    reg = MetricsRegistry(enabled=tcfg.enabled)
    exp = None
    if reg.enabled and (tcfg.prometheus_path
                        or tcfg.http_port is not None):
        exp = TelemetryExporter(reg, prometheus_path=tcfg.prometheus_path,
                                interval_s=tcfg.interval_s,
                                http_port=tcfg.http_port)
    return reg, exp


class EncoderServingEngine:
    """Batched scoring over a pure ``apply_fn(params, tokens, mask)``.

    ``apply_fn`` returns a per-row array (``[B, ...]``); ``run()`` hands
    each request its own row (sliced to its true length when the output
    carries the sequence axis, i.e. ``per_token=True``).
    """

    def __init__(self, apply_fn: Callable, params: Any, *,
                 buckets: Tuple[int, ...] = (32, 64, 128),
                 max_batch: int = 8, per_token: bool = False,
                 mesh=None, specs_tree=None,
                 weight_dtype: str = "bfloat16",
                 quant_group_size: int = 128, quant_skip_paths=(),
                 telemetry=None):
        if weight_dtype != "bfloat16":
            from deepspeed_tpu.inference.quantized import (
                quantize_for_inference)

            params, apply_fn = quantize_for_inference(
                params, apply_fn, weight_dtype=weight_dtype,
                group_size=quant_group_size,
                skip_paths=quant_skip_paths)
        sharded = mesh is not None and any(
            mesh.size(ax) > 1 for ax in ("model", "expert"))
        if sharded:
            if specs_tree is None:
                raise ValueError(
                    "sharded encoder serving needs the model's "
                    "param_specs (specs_tree)")
            from deepspeed_tpu.inference.serving import (
                _shard_params_for_serving)

            params = _shard_params_for_serving(params, specs_tree, mesh)
        self.params = params
        self.per_token = per_token
        self.max_batch = int(max_batch)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets:
            raise ValueError("need at least one bucket length")
        self._fn = jax.jit(apply_fn)
        self.queue: "collections.deque" = collections.deque()
        self.stats = {"lots": 0, "rows_padded": 0, "requests": 0}
        self.registry, self._tel_exporter = _encoder_telemetry(telemetry)
        self._c_lots = self.registry.counter(
            "encoder_lots", "static-shape lots scored")
        self._c_requests = self.registry.counter(
            "encoder_requests", "requests submitted")
        self._c_rows_padded = self.registry.counter(
            "encoder_rows_padded", "padding rows shipped in lots")

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"request length {n} exceeds the largest bucket "
            f"{self.buckets[-1]}")

    def submit(self, req_id, tokens) -> None:
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError(f"request {req_id}: empty input")
        self._bucket(len(tokens))  # validate now, not at lot time
        self.queue.append((req_id, tokens))
        self.stats["requests"] += 1
        self._c_requests.inc()

    def run(self) -> Dict[Any, np.ndarray]:
        """Drain the queue; returns {req_id: output row}.

        Lots are formed greedily in arrival order from requests sharing
        a bucket — a long request never blocks short ones behind it
        (they board an earlier short-bucket lot)."""
        out: Dict[Any, np.ndarray] = {}
        while self.queue:
            lead_bucket = self._bucket(len(self.queue[0][1]))
            lot, keep = [], collections.deque()
            while self.queue and len(lot) < self.max_batch:
                rid, toks = self.queue.popleft()
                if self._bucket(len(toks)) == lead_bucket:
                    lot.append((rid, toks))
                else:
                    keep.append((rid, toks))
            keep.extend(self.queue)
            self.queue = keep

            B, T = self.max_batch, lead_bucket
            tokens = np.zeros((B, T), np.int32)
            mask = np.zeros((B, T), np.int32)
            for r, (_, toks) in enumerate(lot):
                tokens[r, :len(toks)] = toks
                mask[r, :len(toks)] = 1
            res = np.asarray(self._fn(self.params, jnp.asarray(tokens),
                                      jnp.asarray(mask)))
            self.stats["lots"] += 1
            self.stats["rows_padded"] += B - len(lot)
            self._c_lots.inc()
            self._c_rows_padded.inc(B - len(lot))
            for r, (rid, toks) in enumerate(lot):
                row = res[r]
                out[rid] = row[:len(toks)] if self.per_token else row
        if self._tel_exporter is not None:
            self._tel_exporter.maybe_export()
        return out


def bert_serving_engine(params, cfg, head: str = "pooled", mesh=None,
                        weight_dtype: str = "bfloat16", **kw):
    """Serve a BERT encoder (ref: module_inject/containers/bert.py).

    ``head``: "pooled" ([CLS] pooler vector per request), "mlm"
    (per-token vocab logits), or "hidden" (per-token hidden states).
    Composes with TP over the model axis and with int8 weight-only
    quantization like the decoder builders.
    """
    from deepspeed_tpu.models import bert

    if head not in ("pooled", "mlm", "hidden"):
        raise ValueError(f"unknown head {head!r}: pooled | mlm | hidden")

    def apply(p, tokens, mask):
        hidden = bert.forward(p, tokens, cfg, attention_mask=mask)
        if head == "pooled":
            return bert.pooled_output(p, hidden)
        if head == "mlm":
            return bert.mlm_logits(p, hidden, cfg)
        return hidden

    # every default bucket is clamped to the learned position table —
    # a request the model cannot encode must fail at submit(), not when
    # its lot pads past pos_embed
    kw.setdefault("buckets", tuple(sorted(
        {min(32, cfg.max_seq_len), min(64, cfg.max_seq_len),
         cfg.max_seq_len})))
    return EncoderServingEngine(
        apply, params, per_token=head != "pooled", mesh=mesh,
        specs_tree=bert.param_specs(cfg), weight_dtype=weight_dtype,
        # norm scales/biases, biases, the tiny embeddings tables'
        # companions — everything that is not a matmul weight stays
        # exact (embed itself is the tied MLM decoder: keep it exact
        # so logits stay trustworthy)
        quant_skip_paths=("scale", "bias", "b_in", "b_out", "bqkv", "bo",
                          "attn_norm_scale", "attn_norm_bias",
                          "mlp_norm_scale", "mlp_norm_bias",
                          "embed", "pos_embed", "type_embed", "mlm_bias",
                          "b"),
        **kw)


class CNNServingEngine:
    """Batched image scoring for the CNN family — fixed input shape, so
    the only scheduling is lot formation up to ``max_batch``."""

    def __init__(self, params, *, cfg=None, max_batch: int = 8,
                 image_shape: Tuple[int, int, int] = (32, 32, 3),
                 telemetry=None):
        from deepspeed_tpu.models import cnn

        self.cfg = cfg
        self.params = params
        self.max_batch = int(max_batch)
        self.image_shape = tuple(image_shape)
        self._fn = jax.jit(cnn.forward)
        self.queue: "collections.deque" = collections.deque()
        self.stats = {"lots": 0, "requests": 0}
        self.registry, self._tel_exporter = _encoder_telemetry(telemetry)
        self._c_lots = self.registry.counter(
            "encoder_lots", "static-shape lots scored")
        self._c_requests = self.registry.counter(
            "encoder_requests", "requests submitted")

    def submit(self, req_id, image) -> None:
        image = np.asarray(image, np.float32)
        if image.shape != self.image_shape:
            raise ValueError(
                f"request {req_id}: image shape {image.shape} != "
                f"{self.image_shape}")
        self.queue.append((req_id, image))
        self.stats["requests"] += 1
        self._c_requests.inc()

    def run(self) -> Dict[Any, np.ndarray]:
        out: Dict[Any, np.ndarray] = {}
        while self.queue:
            lot = [self.queue.popleft()
                   for _ in range(min(self.max_batch, len(self.queue)))]
            batch = np.zeros((self.max_batch,) + self.image_shape,
                             np.float32)
            for r, (_, img) in enumerate(lot):
                batch[r] = img
            logits = np.asarray(self._fn(self.params, jnp.asarray(batch)))
            self.stats["lots"] += 1
            self._c_lots.inc()
            for r, (rid, _) in enumerate(lot):
                out[rid] = logits[r]
        if self._tel_exporter is not None:
            self._tel_exporter.maybe_export()
        return out
