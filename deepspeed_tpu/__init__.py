"""deepspeed_tpu — a TPU-native large-scale training framework.

Re-implements the capabilities of DeepSpeed (reference:
``deepspeed/__init__.py``) with a JAX/XLA/Pallas architecture designed for
TPU hardware: one SPMD device mesh, GSPMD shardings in place of NCCL
process groups, a single jitted train step in place of imperative
forward/backward/step, and Pallas kernels in place of CUDA extensions.

Public entrypoints mirror the reference:

- :func:`initialize` — build a :class:`~deepspeed_tpu.engine.TrainingEngine`
  from a model + DeepSpeed-style JSON config (ref: deepspeed/__init__.py
  ``initialize``).
- :func:`init_distributed` — multi-host bring-up over
  ``jax.distributed`` (ref: deepspeed/comm/comm.py ``init_distributed``).
- :func:`init_inference` — build an inference engine
  (ref: deepspeed/inference/engine.py).
"""

__version__ = "0.1.0"

# FIRST import: resolves shard_map across JAX versions and publishes the
# portable wrapper at ``jax.shard_map`` when the pinned JAX lacks the
# top-level entrypoint (mesh.install()), so every module below — and
# modern-idiom user code — can use one spelling.
from deepspeed_tpu import mesh
from deepspeed_tpu.config import Config
from deepspeed_tpu.topology import MeshSpec, default_mesh
from deepspeed_tpu.engine import TrainingEngine, TrainState, initialize
from deepspeed_tpu.comm import init_distributed
from deepspeed_tpu import comm
from deepspeed_tpu import ops
from deepspeed_tpu import zero
from deepspeed_tpu import lr_schedules
from deepspeed_tpu import telemetry
from deepspeed_tpu import request_trace


def init_inference(*args, **kwargs):
    """Build an InferenceEngine (ref: deepspeed/inference/engine.py)."""
    from deepspeed_tpu.inference.engine import init_inference as _ii

    return _ii(*args, **kwargs)


def init_hybrid_engine(engine, model_cfg, **kw):
    """Build a train+generate :class:`~deepspeed_tpu.hybrid.HybridEngine`
    for RLHF loops (ref: deepspeed/runtime/hybrid_engine.py)."""
    from deepspeed_tpu.hybrid import llama_hybrid_engine

    return llama_hybrid_engine(engine, model_cfg, **kw)


def add_config_arguments(parser):
    """Add ``--deepspeed``-style CLI args (ref: deepspeed/__init__.py)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "configuration")
    group.add_argument(
        "--deepspeed_config", default=None, type=str,
        help="Path to the framework JSON config file.",
    )
    group.add_argument(
        "--local_rank", default=0, type=int,
        help="Accepted for launcher compatibility; ranks come from JAX.",
    )
    return parser
