"""Synchronized timers + throughput accounting (ref: deepspeed/utils/timers.py).

The reference's ``SynchronizedWallClockTimer`` calls
``torch.cuda.synchronize`` around ``time.time``; on TPU the analogue is
``jax.block_until_ready`` on a sentinel array (XLA dispatch is async).
``ThroughputTimer`` mirrors the reference's samples/sec + TFLOPs
reporting and adds MFU against the chip's peak FLOPs.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp

# Peak bf16 FLOP/s per chip by TPU generation (public spec sheets).
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal, so MFU math never divides by zero off-TPU
}


def device_peak_flops() -> float:
    """Best-effort peak bf16 FLOP/s of the attached chip."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return PEAK_FLOPS["cpu"]
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return PEAK_FLOPS["v5e"] if "tpu" in kind else PEAK_FLOPS["cpu"]


# Peak HBM bandwidth per chip by TPU generation, bytes/s (public spec
# sheets) — the MBU denominator, parallel to PEAK_FLOPS for MFU.
PEAK_HBM_BW = {
    "v4": 1.2e12,
    "v5e": 0.82e12,
    "v5p": 2.77e12,
    "v6e": 1.64e12,
    "cpu": 0.1e12,  # nominal, so MBU math never divides by zero off-TPU
}


def device_peak_bandwidth() -> float:
    """Best-effort peak HBM bandwidth (bytes/s) of the attached chip."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return PEAK_HBM_BW["cpu"]
    for key, val in PEAK_HBM_BW.items():
        if key in kind:
            return val
    return PEAK_HBM_BW["v5e"] if "tpu" in kind else PEAK_HBM_BW["cpu"]


def _sync() -> None:
    """Drain the async dispatch queue so wall-clock brackets device work."""
    jax.block_until_ready(jnp.zeros(()))


class _Timer:
    """One named timer (ref: timers.py ``SynchronizedWallClockTimer.Timer``)."""

    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self.count = 0

    def start(self) -> None:
        if self.started:
            raise RuntimeError(f"timer {self.name} already started")
        _sync()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, reset: bool = False) -> None:
        if not self.started:
            raise RuntimeError(f"timer {self.name} not started")
        _sync()
        dt = time.perf_counter() - self._start
        self._elapsed = dt if reset else self._elapsed + dt
        self.count += 1
        self.started = False

    def elapsed(self, reset: bool = True) -> float:
        e = self._elapsed
        if reset:
            self._elapsed = 0.0
            self.count = 0
        return e

    def mean(self) -> float:
        return self._elapsed / max(self.count, 1)


class SynchronizedWallClockTimer:
    """Named-timer registry (ref: deepspeed/utils/timers.py)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names=None, reset: bool = True) -> str:
        names = names if names is not None else sorted(self.timers)
        parts = []
        for n in names:
            if n in self.timers:
                ms = self.timers[n].elapsed(reset=reset) * 1000.0
                parts.append(f"{n}: {ms:.2f}ms")
        msg = " | ".join(parts)
        from deepspeed_tpu.utils.logging import log_dist

        log_dist(f"time: {msg}")
        return msg


class ThroughputTimer:
    """Samples/sec, tokens/sec, TFLOPs, MFU (ref: timers.py ThroughputTimer).

    ``flops_per_sample`` (if given) enables TFLOPs + MFU reporting; use
    :func:`deepspeed_tpu.profiler.transformer_train_flops` to estimate it.
    """

    def __init__(self, batch_size: int, seq_len: int = 1,
                 flops_per_sample: Optional[float] = None,
                 start_step: int = 2):
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.flops_per_sample = flops_per_sample
        self.start_step = start_step  # skip compile/warmup steps
        self.step_count = 0
        self.total_time = 0.0
        self.total_samples = 0
        self._t0 = 0.0

    def start(self) -> None:
        _sync()
        self._t0 = time.perf_counter()

    def stop(self) -> None:
        _sync()
        dt = time.perf_counter() - self._t0
        self.step_count += 1
        if self.step_count > self.start_step:
            self.total_time += dt
            self.total_samples += self.batch_size

    @property
    def samples_per_sec(self) -> float:
        return self.total_samples / self.total_time if self.total_time else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self.samples_per_sec * self.seq_len

    @property
    def tflops(self) -> float:
        if not self.flops_per_sample:
            return 0.0
        return self.samples_per_sec * self.flops_per_sample / 1e12

    @property
    def mfu(self) -> float:
        if not self.flops_per_sample:
            return 0.0
        return self.samples_per_sec * self.flops_per_sample / device_peak_flops()

    def summary(self) -> Dict[str, float]:
        return {
            "samples_per_sec": self.samples_per_sec,
            "tokens_per_sec": self.tokens_per_sec,
            "tflops": self.tflops,
            "mfu": self.mfu,
            "steps": float(max(self.step_count - self.start_step, 0)),
        }
