"""Unified runtime telemetry: one process-wide metrics registry wired
across serving, streaming, comm, and training.

ZeRO-Infinity-style designs (arXiv:2104.07857) are bandwidth-centric —
whether the param-stream / ZeRO-Inference pipelines actually hide
NVMe→host→HBM latency is an empirical question, and the answer used to
live in ad-hoc ``stats`` dicts and scattered ``time.perf_counter()``
calls no backend ever saw.  This module is the one place those numbers
now flow through:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` primitives,
  thread-safe (streaming drain workers and the serving scheduler write
  concurrently) with Prometheus semantics (cumulative ``le`` buckets,
  implicit ``+Inf``).
- :meth:`MetricsRegistry.span`: a context manager that records wall
  time into a histogram *and* opens a
  ``jax.profiler.TraceAnnotation`` (bridging to ``utils/trace.py``), so
  a host-side phase shows up both as a latency distribution and as a
  named range in a captured device timeline.
- Three sinks: a periodic bridge into the existing
  :class:`~deepspeed_tpu.monitor.MonitorMaster`
  (tensorboard/wandb/csv/comet), a Prometheus text-exposition writer
  (atomic file via ``utils/evidence.atomic_write_text``, plus an
  optional stdlib-http ``/metrics`` endpoint), and the on-demand JSON
  :meth:`MetricsRegistry.snapshot`.

Disabled-path contract: a registry built with ``enabled=False`` hands
out shared no-op singletons — no lock, no ``perf_counter``, no
``TraceAnnotation`` on any hot path.  Instrumented code holds metric
OBJECTS (resolved once at construction), so the disabled cost is one
no-op method call per event.  The serving decode loop additionally
guards its timestamp-taking behind ``registry.enabled`` so even the
``perf_counter`` reads vanish when telemetry is off.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.evidence import atomic_write_text

# Latency buckets (seconds) spanning sub-ms host bookkeeping to
# multi-second NVMe sweeps — the Prometheus defaults stretched one
# decade down (serving TTFT on-chip sits in the single-digit ms).
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset: [a-zA-Z0-9_:]."""
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


class Counter:
    """Monotonic counter.  ``inc`` is thread-safe (``+=`` on a Python
    float is not atomic — the drain workers proved it)."""

    kind = "counter"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins scalar (queue depth, bandwidth, occupancy)."""

    kind = "gauge"
    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        # single store: atomic under the GIL, no lock on hot paths
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with Prometheus ``le`` semantics.

    ``buckets`` are inclusive upper bounds; a value exactly on a
    boundary lands in that bucket, values above the last bound land in
    the implicit ``+Inf`` bucket.  Exposition emits CUMULATIVE bucket
    counts, ``sum`` and ``count`` — the standard histogram contract.
    """

    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_lock", "_counts", "_sum",
                 "_count")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        b = tuple(float(x) for x in buckets)
        if not b or list(b) != sorted(set(b)):
            raise ValueError(
                f"histogram {name}: buckets must be strictly increasing "
                f"and non-empty, got {buckets}")
        self.name = name
        self.help = help
        self.buckets = b
        self._lock = threading.Lock()
        self._counts = [0] * (len(b) + 1)      # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs ending with ``(inf, count)``."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for le, c in zip(self.buckets + (float("inf"),), counts):
            acc += c
            out.append((le, acc))
        return out


class _NullMetric:
    """Shared no-op stand-in for every primitive when telemetry is
    disabled: no lock, no state, one method-call of overhead.  It
    answers the full read surface of all three kinds (``value``,
    ``sum``, ``count``, ``bucket_counts``) so shims like the serving
    engines' ``stats`` read zeros instead of raising."""

    kind = "null"
    __slots__ = ()

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    @property
    def value(self) -> float:
        return 0.0

    @property
    def sum(self) -> float:
        return 0.0

    @property
    def count(self) -> int:
        return 0

    def bucket_counts(self) -> List[Tuple[float, int]]:
        return []


NULL_METRIC = _NullMetric()


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Span:
    """Wall-time → histogram + ``jax.profiler.TraceAnnotation`` range.

    The annotation makes the host phase visible in captured device
    timelines next to the XLA ops it overlaps — the bridge between this
    registry and ``utils/trace.py``'s Tracer captures.
    """

    __slots__ = ("_hist", "_label", "_ann", "_t0")

    def __init__(self, hist: Histogram, label: str):
        self._hist = hist
        self._label = label
        self._ann = None

    def __enter__(self):
        import jax

        self._ann = jax.profiler.TraceAnnotation(self._label)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        self._ann.__exit__(*exc)
        return False


class MetricsRegistry:
    """Thread-safe named-metric registry with three export surfaces.

    ``counter``/``gauge``/``histogram`` are get-or-create (re-requesting
    a name returns the same object; a kind mismatch raises — two
    subsystems silently sharing a name as different types is a bug).
    When ``enabled=False`` every accessor returns :data:`NULL_METRIC`
    and ``span`` returns a no-op context manager.
    """

    def __init__(self, enabled: bool = True, namespace: str = "dstpu"):
        self.enabled = bool(enabled)
        self.namespace = _sanitize(namespace)
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}       # insertion-ordered
        self._comms_seen: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------ create
    def _get_or_create(self, cls, name: str, help: str, **kw):
        name = _sanitize(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            elif kw.get("buckets") is not None and \
                    tuple(float(b) for b in kw["buckets"]) != m.buckets:
                raise ValueError(
                    f"histogram {name!r} already registered with buckets "
                    f"{m.buckets}, requested {tuple(kw['buckets'])}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        if not self.enabled:
            return NULL_METRIC
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        if not self.enabled:
            return NULL_METRIC
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS_S
                  ) -> Histogram:
        if not self.enabled:
            return NULL_METRIC
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def span(self, name: str, help: str = "",
             buckets: Sequence[float] = LATENCY_BUCKETS_S):
        """Context manager: wall time into ``{name}_seconds`` + a
        ``TraceAnnotation`` named ``{namespace}/{phase}``, where
        ``phase`` is ``name`` normalized through the devprof phase
        vocabulary — so captured device timelines use the same
        prefill/decode/spec_verify/promote/sample names the
        ``devprof_device_seconds_*`` counters report under.  The
        histogram keeps the caller's literal name (metric families are
        a stable exposition contract)."""
        if not self.enabled:
            return _NULL_SPAN
        from deepspeed_tpu.devprof import canonical_phase

        h = self.histogram(f"{name}_seconds", help, buckets)
        return Span(h, f"{self.namespace}/{canonical_phase(name)}")

    # ----------------------------------------------------------- export
    def snapshot(self) -> Dict[str, Any]:
        """On-demand JSON-serializable view of every metric."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Any] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.kind == "counter":
                counters[m.name] = m.value
            elif m.kind == "gauge":
                gauges[m.name] = m.value
            else:
                hists[m.name] = {
                    "buckets": {_fmt_le(le): c
                                for le, c in m.bucket_counts()},
                    "sum": m.sum,
                    "count": m.count,
                    "mean": m.sum / m.count if m.count else 0.0,
                }
        return {"enabled": self.enabled, "namespace": self.namespace,
                "counters": counters, "gauges": gauges,
                "histograms": hists}

    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the registry."""
        lines: List[str] = []
        ns = self.namespace
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            full = f"{ns}_{m.name}"
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            lines.append(f"# TYPE {full} {m.kind}")
            if m.kind in ("counter", "gauge"):
                lines.append(f"{full} {_fmt(m.value)}")
            else:
                for le, c in m.bucket_counts():
                    lines.append(
                        f'{full}_bucket{{le="{_fmt_le(le)}"}} {c}')
                lines.append(f"{full}_sum {_fmt(m.sum)}")
                lines.append(f"{full}_count {m.count}")
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> None:
        """Atomic exposition-file write (temp + ``os.replace``, like the
        JSON evidence writers): a scraper or a kill mid-write can only
        ever see the previous complete file."""
        atomic_write_text(self.prometheus_text(), path)

    def publish_to_monitor(self, monitor, step: int) -> None:
        """One bridge tick into a MonitorMaster: counters and gauges as
        scalars, histograms as ``_count``/``_sum``/``_mean``."""
        if monitor is None or not monitor.enabled:
            return
        scalars: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            tag = f"Telemetry/{m.name}"
            if m.kind in ("counter", "gauge"):
                scalars[tag] = float(m.value)
            else:
                scalars[f"{tag}_count"] = float(m.count)
                scalars[f"{tag}_sum"] = float(m.sum)
                scalars[f"{tag}_mean"] = (m.sum / m.count
                                          if m.count else 0.0)
        monitor.write_scalars(scalars, step)

    # ----------------------------------------------------------- fan-in
    def fan_in_comms(self, comms_logger, prefix: str = "comm") -> None:
        """Fold a :class:`~deepspeed_tpu.utils.trace.CommsLogger`
        summary into per-op counters (``{prefix}_{op}_calls`` /
        ``_bytes`` / ``_seconds``).  Delta-tracked against the last
        fan-in, so calling this every publish tick never double-counts
        (and a logger ``reset()`` between ticks just contributes
        nothing, it cannot drive a counter backwards)."""
        if not self.enabled:
            return
        for op, rec in comms_logger.summary().items():
            last = self._comms_seen.get(op, {})
            for key, cname in (("count", "calls"), ("bytes", "bytes"),
                               ("time_s", "seconds")):
                d = rec[key] - last.get(key, 0.0)
                if d > 0:
                    self.counter(f"{prefix}_{op}_{cname}").inc(d)
            self._comms_seen[op] = dict(rec)


def _fmt(v: float) -> str:
    v = float(v)
    # non-finite gauges are legal (a diverged loss, an overflow grad
    # norm) and must export, not crash the tick — Prometheus spellings
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v) if v != int(v) else str(int(v))


def _fmt_le(le: float) -> str:
    return "+Inf" if le == float("inf") else _fmt(le)


def parse_prometheus_text(text: str) -> Dict[str, Any]:
    """Parse the exposition this module emits back into
    ``{metric: {"type": ..., "samples": {sample_name_or_le: value}}}``
    — the round-trip half of the Prometheus sink (tests parse what we
    emit; an external scraper sees the same grammar)."""
    out: Dict[str, Any] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            out[name] = {"type": kind, "samples": {}}
            continue
        if line.startswith("#"):
            continue
        sample, value = line.rsplit(None, 1)
        if "{" in sample:
            base, label = sample.split("{", 1)
            le = label[:-1].split("=", 1)[1].strip('"')
            key = f"{base}|le={le}"
        else:
            key = sample
        # samples belong to the most recent TYPE'd family whose name
        # prefixes them (histogram emits base_bucket/_sum/_count)
        fam = next((n for n in reversed(list(out))
                    if key.startswith(n)), None)
        if fam is None:
            raise ValueError(f"sample {sample!r} before any # TYPE line")
        out[fam]["samples"][key] = float(value)
    return out


class TelemetryExporter:
    """Periodic sink driver + live introspection server.

    Sinks: rate-limited MonitorMaster bridge + Prometheus file.
    ``maybe_export(step)`` is safe to call every iteration — it is one
    ``time.monotonic()`` compare until ``interval_s`` elapses.

    The HTTP server (``http_port``; 0 picks an ephemeral port, see
    ``.port``) renders ``/metrics`` on demand in a daemon thread, and
    doubles as the engine introspection surface: providers registered
    via :meth:`register_provider` serve ``/statusz`` (live engine
    snapshot), ``/healthz`` (liveness/readiness; returns 503 when the
    provider reports unready), and ``/requestz?id=`` (one request's
    flight-recorder events).  Unregistered introspection paths 404 —
    a bare exporter is still just a metrics endpoint.

    Lifecycle: the socket binds with ``SO_REUSEADDR`` and
    :meth:`close` is idempotent (shutdown + close + thread join), so
    back-to-back engine constructions in one process can reuse a fixed
    port without ``EADDRINUSE`` or leaking the serving thread.
    """

    def __init__(self, registry: MetricsRegistry, *, monitor=None,
                 prometheus_path: Optional[str] = None,
                 interval_s: float = 10.0,
                 http_port: Optional[int] = None):
        self.registry = registry
        self.monitor = monitor
        self.prometheus_path = prometheus_path
        self.interval_s = max(float(interval_s), 0.0)
        # None, not 0.0: monotonic() is time-since-boot, so on a host
        # up for less than interval_s a 0.0 sentinel would suppress
        # the first export entirely
        self._last: Optional[float] = None    # first call always exports
        self._step = 0
        self._httpd = None
        self._http_thread = None
        self.port: Optional[int] = None
        # introspection providers: name -> zero-arg callable returning a
        # JSON-serializable dict ("statusz", "healthz") or a one-arg
        # callable taking the request id ("requestz").  Read via a dict
        # lookup per GET — registration order and timing are free.
        self._providers: Dict[str, Any] = {}
        # additional registries appended to the /metrics exposition —
        # the fleet router registers each replica engine's registry
        # here (distinct namespaces keep the families collision-free),
        # so ONE scrape carries the rollup plus every per-replica view
        self._sources: List[MetricsRegistry] = []
        # tick hooks: the shared timed pass driven from maybe_export
        # (SLO refresh, history sampling, incident evaluation) — each
        # entry is [fn, interval_s, last_t, name, alive]
        self._tick_hooks: List[list] = []
        if http_port is not None and registry.enabled:
            self._start_http(int(http_port))
        # postmortem flushing: the watchdog's timeout path (and any
        # other abort path) force-flushes every live exporter so the
        # last scrape on disk reflects the moment of death, not the
        # last interval tick; weak so dead engines release theirs
        _exporters.add(self)

    # dstpu: hot-path
    def run_tick_hooks(self, now: Optional[float] = None) -> int:
        """Drive every registered tick hook that is due — the ONE
        timed pass shared by SLO window refresh, history sampling and
        incident-detector evaluation (each hook rate-limits on its own
        ``interval_s``; until due it costs one compare).  Called from
        :meth:`maybe_export` so a serving loop pays a single
        ``time.monotonic()`` read per step for the whole control
        plane.  Hooks are individually guarded: a broken one logs and
        is disabled rather than taking down the serving loop."""
        if not self._tick_hooks:
            return 0
        if now is None:
            now = time.monotonic()
        ran = 0
        for hook in self._tick_hooks:
            # hook = [fn, interval_s, last_t, name, alive]
            if not hook[4] or (hook[2] is not None
                               and now - hook[2] < hook[1]):
                continue
            hook[2] = now
            try:
                hook[0](now)
                ran += 1
            except Exception:
                hook[4] = False
                from deepspeed_tpu.utils.logging import logger

                logger.exception(
                    "telemetry: tick hook %s raised — disabled",
                    hook[3])
        return ran

    def register_tick_hook(self, fn, interval_s: float = 1.0,
                           name: str = "") -> None:
        """Attach ``fn(now_monotonic)`` to the exporter's per-step
        timed pass (see :meth:`run_tick_hooks`).  ``interval_s``
        rate-limits the hook independently of the sink
        ``interval_s`` — history samples at 1 s while Prometheus
        writes at 10 s."""
        interval_s = float(interval_s)
        if interval_s < 0:
            raise ValueError(
                f"tick hook interval_s must be >= 0, got {interval_s}")
        self._tick_hooks.append(
            [fn, interval_s, None, name or getattr(fn, "__name__", "?"),
             True])

    def maybe_export(self, step: Optional[int] = None,
                     force: bool = False) -> bool:
        if not self.registry.enabled:
            return False
        now = time.monotonic()
        if not force:
            # hooks run only on the owner's per-step path: a forced
            # flush (watchdog postmortem, shutdown) arrives on ANOTHER
            # thread, and the hook consumers (IncidentManager, SLO
            # tracker state) are single-writer by contract — the
            # forced path wants the sinks, not the control plane
            self.run_tick_hooks(now)
        if not force and self._last is not None and \
                now - self._last < self.interval_s:
            return False
        self._last = now
        self._step = self._step + 1 if step is None else int(step)
        if self.monitor is not None and self.monitor.enabled:
            self.registry.publish_to_monitor(self.monitor, self._step)
            self.monitor.flush()
        if self.prometheus_path:
            self.registry.write_prometheus(self.prometheus_path)
        return True

    # ---------------------------------------------------- introspection
    def add_source(self, registry: MetricsRegistry) -> None:
        """Append another registry to the ``/metrics`` exposition
        (idempotent per registry).  Collision discipline is the
        caller's: give each source its own ``namespace`` — the fleet
        router uses ``dstpu_r0``, ``dstpu_r1``, … per replica."""
        if registry is not self.registry and \
                all(registry is not s for s in self._sources):
            self._sources.append(registry)

    def remove_source(self, registry: MetricsRegistry) -> None:
        """Drop a registry from the exposition (no-op if absent) —
        the fleet calls this when a replica RETIRES, so a long-lived
        elastic fleet's ``/metrics`` does not accumulate one dead
        replica's full metric set per scale cycle.  In-place mutation:
        the HTTP handler holds the live list."""
        for i, s in enumerate(self._sources):
            if s is registry:
                del self._sources[i]
                return

    def register_provider(self, name: str, fn) -> None:
        """Attach an introspection provider: ``statusz``/``healthz``/
        ``historyz`` take no args and return a JSON dict (healthz may
        include ``"ready": false`` to force a 503; historyz serves the
        metric-history rings + recent incident metadata); ``requestz``
        takes the request-id string; ``profilez`` takes the optional
        ``?capture_s=`` string (None for a plain devprof snapshot);
        ``tracez`` takes the ``?since=`` cursor string ("0" when
        absent) and returns an incremental flight-recorder segment.
        Re-registering a name replaces it (the engine owns its
        endpoints)."""
        if name not in ("statusz", "healthz", "requestz", "historyz",
                        "profilez", "tracez"):
            raise ValueError(
                f"unknown introspection provider {name!r} — expected "
                "statusz, healthz, historyz, profilez, tracez or "
                "requestz")
        self._providers[name] = fn

    # ------------------------------------------------------------- http
    def _start_http(self, port: int) -> None:
        import http.server

        registry = self.registry
        providers = self._providers
        sources = self._sources      # live list: add_source visible

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, obj, code: int = 200) -> None:
                import json

                self._send(code, (json.dumps(obj, indent=1,
                                             sort_keys=True)
                                  + "\n").encode())

            def do_GET(self):          # noqa: N802 (stdlib contract)
                from urllib.parse import parse_qs, urlparse

                u = urlparse(self.path)
                route = u.path.rstrip("/") or "/metrics"
                try:
                    if route == "/metrics":
                        text = "".join(
                            [registry.prometheus_text()]
                            + [s.prometheus_text() for s in sources
                               if s.enabled])
                        self._send(200, text.encode(),
                                   "text/plain; version=0.0.4")
                    elif route == "/statusz" and "statusz" in providers:
                        self._send_json(providers["statusz"]())
                    elif route == "/historyz" and \
                            "historyz" in providers:
                        self._send_json(providers["historyz"]())
                    elif route == "/healthz" and "healthz" in providers:
                        h = providers["healthz"]()
                        self._send_json(
                            h, 200 if h.get("ready", True) else 503)
                    elif route == "/profilez" and \
                            "profilez" in providers:
                        cs = parse_qs(u.query).get(
                            "capture_s", [None])[0]
                        self._send_json(providers["profilez"](cs))
                    elif route == "/tracez" and "tracez" in providers:
                        since = parse_qs(u.query).get(
                            "since", ["0"])[0]
                        self._send_json(providers["tracez"](since))
                    elif route == "/requestz" and \
                            "requestz" in providers:
                        rid = parse_qs(u.query).get("id", [None])[0]
                        if rid is None:
                            self._send_json(
                                {"error": "missing ?id= query"}, 400)
                        else:
                            d = providers["requestz"](rid)
                            self._send_json(
                                d, 200 if d.get("found") else 404)
                    else:
                        self.send_error(404)
                except Exception as e:   # a broken provider must not
                    try:                 # kill the serving thread
                        self._send_json({"error": repr(e)}, 500)
                    except Exception:
                        pass

            def log_message(self, *a):   # keep scrapes out of stderr
                pass

        class Server(http.server.ThreadingHTTPServer):
            # explicit (HTTPServer already sets it, but the lifecycle
            # contract — back-to-back engines on one fixed port — is
            # load-bearing enough to pin rather than inherit)
            allow_reuse_address = True
            daemon_threads = True

        self._httpd = Server(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="dstpu-telemetry-http", daemon=True)
        self._http_thread.start()

    def close(self) -> None:
        """Stop the HTTP server and join its thread.  Idempotent —
        engine teardown and explicit calls can both run it."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._http_thread = self._http_thread, None
        if t is not None:
            t.join(timeout=5.0)


# ----------------------------------------------------- exporter registry
_exporters: "weakref.WeakSet[TelemetryExporter]" = weakref.WeakSet()


def flush_all_exporters() -> int:
    """Force one export tick on every live :class:`TelemetryExporter`
    (Prometheus file + monitor bridge), each individually guarded —
    the watchdog calls this before ``os._exit(42)`` so a hang's final
    metric state lands on disk.  Returns the number flushed."""
    n = 0
    for e in list(_exporters):
        try:
            if e.maybe_export(force=True):
                n += 1
        except Exception:
            pass
    return n


# ------------------------------------------------------- default registry
_default_lock = threading.Lock()
_default: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """The process-wide registry.  Subsystems without a config handle
    (the aio pool, the comm backend) record here; engines wire their
    own registry from the ``telemetry`` config block.  ``DSTPU_TELEMETRY=0``
    disables it for the whole process."""
    global _default
    with _default_lock:
        if _default is None:
            enabled = os.environ.get("DSTPU_TELEMETRY", "1").lower() \
                not in ("0", "false", "off")
            _default = MetricsRegistry(enabled=enabled)
        return _default


def set_default_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-wide registry (tests; or to point the aio/comm
    instrumentation at an engine's registry).  Returns the previous one."""
    global _default
    with _default_lock:
        prev, _default = _default, reg
        return prev
