"""Elastic fleet: autoscaling on control-plane signals, zero-downtime
rolling weight updates, and streamed warm cold-start (ROADMAP open
item 3 — production fleets breathe).

PR 10's :class:`~deepspeed_tpu.fleet.FleetRouter` has the verbs —
``drain()`` with warm-digest handoff, ``rejoin()``, ``spawn()``/
``retire()``, health hysteresis — and PR 6's control plane emits the
signals (rolling goodput, multiwindow burn rates, shed rate, queue
depth).  This module closes the loop: a :class:`FleetAutoscaler` polls
the fleet's own signals every ``eval_interval_steps`` router steps and
drives

- **scale-down**: sustained low pressure → ``drain()`` the least
  useful routable replica (its warm prefix digest hands to the
  affinity successor, its queued work re-routes uncharged), then
  ``retire()`` once the in-flight work finished — a replica leaves the
  ring without dropping a request;
- **scale-up**: sustained queue/shed/burn pressure → spawn a replica
  from the registered ``engine_factory``.  With
  ``cold_start="streamed"`` the factory builds a ZeRO-Inference
  weight-streamed engine (ZeRO-Infinity tiering, arXiv:2104.07857;
  ZeRO-Offload host staging, arXiv:2101.06840): the replica serves its
  FIRST request while its weight image still lives on host/NVMe, and
  the autoscaler promotes layers into HBM between scheduler steps
  (:meth:`~deepspeed_tpu.inference.zero_inference.
  ZeroInferenceServingEngine.promote_resident_layers`) until the
  engine flips to fully resident — cold capacity in seconds, full
  speed shortly after;
- **hysteresis + cooldown**: pressure must persist ``up_after`` /
  ``down_after`` consecutive evaluations, and ``cooldown_s`` separates
  scale events, so a burn-rate blip never flaps the fleet; replica
  count stays inside ``[min_replicas, max_replicas]``, and a fleet
  that fell under the floor (failover deaths) heals back up to it.

On the same machinery, **rolling weight updates**
(:meth:`FleetAutoscaler.rollout`): the fleet walks one replica at a
time through drain → swap (:meth:`~deepspeed_tpu.inference.serving.
ServingEngine.swap_params`, which also invalidates the now
version-poisoned warm prefix pages) → rejoin, old and new versions
serving side by side with per-version SLO rollups
(:func:`~deepspeed_tpu.slo.fleet_rollup` ``versions=``).  Between
replicas the autoscaler soaks ``rollout_soak_steps`` ticks watching
the NEW version's burn rate; a trip past
``rollback_burn_threshold`` halts the rollout and walks the
already-updated replicas BACK (drain → swap old → rejoin) — an
upgrade never drops or double-generates a request, and a bad one
un-ships itself.

Chaos composes: the ``scale`` fault rules inject engine-factory
failures and slow cold-starts at the spawn path, and a ``replica``
kill rule with ``after=`` lands mid-rollout — the elastic soak
(``tools/chaos_soak.py --elastic``) drives a load sine wave through
all of it and asserts token identity, zero orphans/leaks, and an
exactly-once scale/rollout event trace.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu import faults as faults_mod
from deepspeed_tpu.config import AutoscaleConfig
from deepspeed_tpu.fleet import (DEAD, DEGRADED, DRAINING, HEALTHY,
                                 QUARANTINED)
from deepspeed_tpu.utils.logging import logger

# scale-down victim preference: retire the sickest routable-or-parked
# replica first (a QUARANTINED one serves nothing anyway)
_VICTIM_RANK = {QUARANTINED: 0, DEGRADED: 1, HEALTHY: 2}
_COLD_START_BUCKETS_S = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                         60.0, 120.0)


class FleetAutoscaler:
    """Drive a :class:`~deepspeed_tpu.fleet.FleetRouter` elastically.

    ``engine_factory(replica_id, streamed=False)`` builds one fleet-
    compatible replica engine (same model and page geometry as the
    existing replicas; pass ``replica_id=`` through to the engine and
    share the fleet's tracer/fault plan, exactly like
    :func:`~deepspeed_tpu.fleet.fleet_router` does at construction).
    ``streamed=True`` is only passed when ``cold_start="streamed"`` —
    the factory then builds the ZeRO-Inference engine whose weights
    page in from host/NVMe while it serves.

    Surface: :meth:`step` (router step + autoscaler tick — the drive
    loop's one call), :meth:`tick` (advance scaling/cold-start/rollout
    state without stepping the router), :meth:`rollout` (start a
    rolling weight update), :meth:`status` (the ``/statusz``
    ``elastic`` block).  The autoscaler is single-threaded with the
    router by design: everything happens between scheduler steps, so
    no engine is ever mutated mid-sweep.
    """

    def __init__(self, router, engine_factory: Callable[..., Any], *,
                 autoscale=None):
        self.cfg = AutoscaleConfig.coerce(autoscale)
        self.router = router
        self.factory = engine_factory
        live = sum(1 for rep in router.replicas.values()
                   if rep.state != DEAD)
        self.target = min(max(live, self.cfg.min_replicas),
                          self.cfg.max_replicas)
        self._tracer = router.tracer

        r = router.registry
        self._c_ups = r.counter(
            "autoscale_scale_ups", "replicas spawned by the autoscaler")
        self._c_downs = r.counter(
            "autoscale_scale_downs",
            "autoscaler drain→retire scale-downs completed")
        self._c_rollout_steps = r.counter(
            "autoscale_rollout_steps",
            "replicas walked through drain→swap→rejoin by a rollout "
            "(rollback steps count too — each is the same walk)")
        self._c_rollbacks = r.counter(
            "autoscale_rollbacks",
            "rollouts halted and rolled back by a new-version "
            "burn-rate trip")
        self._c_factory_failures = r.counter(
            "autoscale_factory_failures",
            "scale-ups aborted by an engine-factory failure (retried "
            "at a later evaluation)")
        self._c_flips = r.counter(
            "autoscale_cold_flips",
            "streamed cold-start replicas promoted to fully resident")
        self._g_replicas = r.gauge(
            "autoscale_replicas", "live (non-DEAD) replicas in the ring")
        self._g_target = r.gauge(
            "autoscale_target_replicas",
            "replica count the autoscaler is steering toward")
        self._h_cold = r.histogram(
            "autoscale_cold_start_seconds",
            "scale-up decision -> replica fully serving (streamed "
            "cold-starts: the resident flip; resident ones: the first "
            "completed request)", _COLD_START_BUCKETS_S)

        self._last_eval_step = router._steps
        self._last_scale_t: Optional[float] = None
        self._up_streak = 0
        self._down_streak = 0
        self._last_shed_seen = router._n_shed
        self._last_signals: Dict[str, Any] = {}
        # in-flight cold starts: rid -> {t0, streamed, first_token_s,
        # flip_s} — closed records move to cold_history (bounded: an
        # indefinitely breathing fleet must not grow host memory per
        # scale cycle)
        self._cold: Dict[str, Dict[str, Any]] = {}
        self.cold_history: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=256)
        self._retiring: set = set()
        self._rollout: Optional[Dict[str, Any]] = None
        self.last_rollout: Optional[Dict[str, Any]] = None
        # (swap_callable, version) once a rollout completed: replicas
        # spawned later swap onto the current version before serving
        self._current_weights = None
        # host-side ledger of every scale/rollout decision (the soak
        # reconciles it 1:1 against the trace ring; bounded like the
        # ring itself)
        self.events: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=4096)
        router.attach_autoscaler(self)
        self._update_gauges()

    # ------------------------------------------------------------ events
    def _event(self, kind: str, **attrs) -> None:
        self.events.append({"kind": kind,
                            "t": time.perf_counter(), **attrs})
        if self._tracer.enabled:
            self._tracer.event(kind, attrs=attrs)
        # scale/rollout decisions double as history annotations: the
        # /historyz timeline (and any incident bundle's pre-window)
        # shows WHEN the fleet breathed next to the series that made
        # it breathe
        h = getattr(self.router, "history", None)
        if h is not None:
            h.annotate(kind, attrs)

    # ------------------------------------------------------------- drive
    def step(self) -> List[Any]:
        """One elastic-fleet iteration: router step, then the
        autoscaler tick.  Returns the router's newly finished ids."""
        done = self.router.step()
        self.tick()
        return done

    def tick(self) -> None:
        """Advance autoscaler state WITHOUT stepping the router: cold
        starts promote toward residency, drained victims retire, an
        active rollout walks/soaks/rolls back, and — on the evaluation
        cadence — the control-plane signals are polled for scale
        pressure."""
        now = time.perf_counter()
        self._advance_cold(now)
        self._advance_retiring(now)
        due = self.cfg.enabled and (
            self.router._steps - self._last_eval_step
            >= self.cfg.eval_interval_steps)
        if due:
            self._last_eval_step = self.router._steps
        if self._rollout is not None:
            self._advance_rollout(now)
            # pressure-driven scaling pauses during a rollout (one
            # fleet mutation at a time) — but HEALING does not: a
            # mid-rollout replica death must not leave the fleet under
            # its floor for the rest of the walk (the spawn joins the
            # rollout plan and updates in turn)
            if due:
                self._evaluate(now, heal_only=True)
        elif due:
            self._evaluate(now)
        self._update_gauges()

    def _update_gauges(self) -> None:
        if not self.router.registry.enabled:
            return
        self._g_replicas.set(sum(
            1 for rep in self.router.replicas.values()
            if rep.state != DEAD))
        self._g_target.set(self.target)

    # ------------------------------------------------------------ signals
    def _max_burn(self, reps) -> float:
        worst = 0.0
        for rep in reps:
            snap = rep.engine.slo_tracker.snapshot()
            if not snap.get("enabled"):
                continue
            for t in snap.get("tiers", {}).values():
                for b in t.get("burn_rates", {}).values():
                    worst = max(worst, float(b))
        return worst

    def _evaluate(self, now: float, heal_only: bool = False) -> None:
        router = self.router
        live = [rep for rep in router.replicas.values()
                if rep.state != DEAD]
        if heal_only:
            effective = len(live) - len(self._retiring)
            if effective < self.cfg.min_replicas:
                self._scale_up(now, reason="heal")
                self._last_scale_t = now
            return
        pool = [rep for rep in live if rep.routable]
        # a saturation storm can quarantine EVERY replica (shed
        # activity reads as degraded until the shed window ages out):
        # that is maximal up-pressure, not a reason to stop looking —
        # a fresh replica is exactly what un-wedges the fleet
        wedged = not pool
        if wedged:
            pool = [rep for rep in live
                    if rep.state == QUARANTINED]
            if not pool:
                return      # only draining/dying: failover's problem
        qdepth = sum(len(rep.engine.queue)
                     for rep in pool) / len(pool)
        shed_now = router._n_shed
        sheds = shed_now - self._last_shed_seen
        self._last_shed_seen = shed_now
        burn = self._max_burn(pool)
        effective = len(live) - len(self._retiring)
        up = (wedged
              or qdepth >= self.cfg.scale_up_queue_depth
              or (self.cfg.scale_up_on_shed and sheds > 0)
              or burn > self.cfg.scale_up_burn)
        # under the floor (failover deaths): heal up regardless of load
        heal = effective < self.cfg.min_replicas
        down = (not up and qdepth <= self.cfg.scale_down_queue_depth
                and sheds == 0 and burn <= self.cfg.scale_up_burn)
        if up:
            self._up_streak += 1
            self._down_streak = 0
        elif down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        self._last_signals = {
            "queue_depth_per_replica": round(qdepth, 3),
            "sheds_since_last_eval": sheds,
            "max_burn": round(burn, 3),
            "effective_replicas": effective,
        }
        if getattr(router, "_roles_on", False):
            # disaggregated fleets scale per role: the pressured pool
            # gets the next spawn, and the per-role depths ride the
            # elastic statusz block
            self._last_signals["role_queue_depth"] = {
                ro: (round(v, 3) if v != float("inf") else "inf")
                for ro, v in router.role_pressure().items()}
        if not heal and self._last_scale_t is not None and \
                now - self._last_scale_t < self.cfg.cooldown_s:
            return          # cooling down: streaks keep accumulating
        if (heal or self._up_streak >= self.cfg.up_after) and \
                effective < self.cfg.max_replicas:
            self._scale_up(now, reason="heal" if heal else "pressure")
            self._up_streak = 0
            self._last_scale_t = now
        elif self._down_streak >= self.cfg.down_after and \
                effective > self.cfg.min_replicas and \
                not self._retiring:
            self._scale_down(now)
            self._down_streak = 0
            self._last_scale_t = now

    # ----------------------------------------------------------- scale up
    def _next_rid(self) -> str:
        router = self.router
        while f"r{router._spawn_seq}" in router.replicas:
            router._spawn_seq += 1
        return f"r{router._spawn_seq}"

    def _scale_up(self, now: float, reason: str = "pressure") -> None:
        rid = self._next_rid()
        streamed = self.cfg.cold_start == "streamed"
        t0 = time.perf_counter()
        try:
            # the `scale` fault hook: an error rule is a factory
            # failure, a latency rule a slow cold-start (the sleep
            # lands inside the cold_start_seconds histogram)
            faults_mod.inject("scale", key=rid)
            eng = (self.factory(rid, streamed=True) if streamed
                   else self.factory(rid))
        except Exception as e:
            self._c_factory_failures.inc()
            logger.warning(
                "autoscale: scale-up of %s aborted (factory: %s) — "
                "will retry at a later evaluation", rid, e)
            self._event("autoscale_up_failed", replica=rid,
                        error=repr(e)[:200])
            return
        cur = self._current_weights
        if cur is not None:
            swap, version = cur
            if str(eng.weights_version) != str(version):
                # the fleet already rolled to `version`: a replica the
                # factory built on the old image swaps before it ever
                # serves (it is drained by construction).  A failing
                # swap is a failed spawn — a wrong-version replica
                # must never enter rotation, and a crash here would
                # take down the whole serve loop
                try:
                    swap(eng)
                except Exception as e:
                    self._c_factory_failures.inc()
                    logger.warning(
                        "autoscale: scale-up of %s aborted (version "
                        "catch-up swap to %r: %s)", rid, version, e)
                    self._event("autoscale_up_failed", replica=rid,
                                error=repr(e)[:200])
                    try:
                        eng.shutdown()
                    except Exception:
                        pass
                    return
        role = None
        if getattr(self.router, "_roles_on", False):
            # spawn into the pressured pool (a role with no routable
            # member reads as infinite pressure and heals first)
            pressure = self.router.role_pressure()
            role = max(sorted(pressure), key=lambda ro: pressure[ro])
        self.router.spawn(eng, rid, role=role)
        if self._rollout is not None:
            # a replica added mid-rollout (heal after a rollout
            # casualty, or genuine pressure) comes up on the factory's
            # OLD image — appending it to the plan lets the normal
            # walk bring it to the target version, and keeps the
            # invariant that a completed rollout leaves every live
            # replica current (a rollback leaves it untouched: it was
            # never updated, so it is already on the old version)
            self._rollout["plan"].append(rid)
        self.target = max(self.target,
                          sum(1 for rep in self.router.replicas.values()
                              if rep.state != DEAD))
        self._c_ups.inc()
        streaming = streamed and \
            not getattr(eng, "fully_resident", True)
        if not streaming:
            # a resident engine is fully serving the moment the
            # factory returns — the histogram records build+spawn time
            self._h_cold.observe(time.perf_counter() - t0)
        self._cold[rid] = {
            "replica": rid, "t0": t0, "streamed": streaming,
            "first_token_s": None, "flip_s": None}
        self._event("autoscale_up", replica=rid, reason=reason,
                    streamed=streaming)

    def _pending_flip(self, rid: str) -> bool:
        """True while ``rid`` is a streamed cold-start whose resident
        flip has not landed — the only cold-start state that must
        block scale-down victim selection (an idle resident spawn is
        a perfectly good victim)."""
        rec = self._cold.get(rid)
        return rec is not None and rec["streamed"] \
            and rec["flip_s"] is None

    def _advance_cold(self, now: float) -> None:
        for rid in list(self._cold):
            rec = self._cold[rid]
            rep = self.router.replicas.get(rid)
            if rep is None or rep.state == DEAD:
                # died/retired before finishing its cold start
                self.cold_history.append(self._cold.pop(rid))
                continue
            if rep.state == DRAINING and not self._pending_flip(rid):
                # leaving rotation before its first token: record
                # what we have (a streamed spawn mid-flip keeps its
                # record — the promote loop below must finish even
                # through a rollout's drain)
                self.cold_history.append(self._cold.pop(rid))
                continue
            eng = rep.engine
            if rec["first_token_s"] is None and (
                    rep.completed > 0
                    or any(s is not None and len(s.generated)
                           for s in eng.slots)):
                rec["first_token_s"] = round(now - rec["t0"], 3)
            if rec["streamed"] and rec["flip_s"] is None:
                try:
                    eng.promote_resident_layers(
                        self.cfg.promote_layers_per_tick)
                except Exception:
                    logger.exception(
                        "autoscale: layer promotion on %s", rid)
                if eng.fully_resident:
                    rec["flip_s"] = round(now - rec["t0"], 3)
                    self._c_flips.inc()
                    self._h_cold.observe(now - rec["t0"])
                    self._event("autoscale_flip", replica=rid,
                                cold_start_s=rec["flip_s"])
                elif getattr(eng, "resident_flip_blocked", False):
                    # the HBM budget cannot hold another layer:
                    # streaming IS this replica's steady state (the
                    # >HBM operating point) — the cold start is done,
                    # there is no flip to wait for
                    rec["flip_s"] = round(now - rec["t0"], 3)
                    rec["budget_bound"] = True
                    self._h_cold.observe(now - rec["t0"])
                    self._event("autoscale_flip_budget_bound",
                                replica=rid,
                                cold_start_s=rec["flip_s"])
            # a record closes once the replica is fully serving AND
            # its first token was seen (the bench's scale_up-to-first-
            # token metric); only a pending streamed FLIP gates run()
            # — an idle resident spawn may simply never see traffic
            if rec["first_token_s"] is not None and (
                    not rec["streamed"] or rec["flip_s"] is not None):
                self.cold_history.append(self._cold.pop(rid))

    # --------------------------------------------------------- scale down
    def _scale_down(self, now: float) -> None:
        # (never reached while a rollout is active: tick() routes to
        # the heal-only evaluation then)
        cands = [rep for rep in self.router.replicas.values()
                 if rep.state in _VICTIM_RANK
                 and not self._pending_flip(rep.id)
                 # never scale a configured role's LAST replica away:
                 # routing would degrade to the other pool, silently
                 # un-disaggregating the fleet at every load trough
                 and not self.router.last_of_role(rep)]
        if not cands:
            return
        victim = min(cands, key=lambda rep: (_VICTIM_RANK[rep.state],
                                             rep.load()))
        self.router.drain(victim.id)
        self._retiring.add(victim.id)
        self.target = max(self.cfg.min_replicas, self.target - 1)
        self._event("autoscale_down", replica=victim.id,
                    state=victim.state)

    def _advance_retiring(self, now: float) -> None:
        for rid in list(self._retiring):
            rep = self.router.replicas.get(rid)
            if rep is None:
                self._retiring.discard(rid)
                continue
            if rep.state == DEAD or self.router.drained(rid):
                try:
                    # a victim that died mid-drain retires through
                    # the same verb: its work was already salvaged
                    self.router.retire(rid)
                except ValueError:
                    # the OTHER replicas died while this one drained:
                    # it is now the fleet's last live replica — the
                    # scale-down cancels and it goes back into
                    # rotation instead of crashing the loop
                    self._retiring.discard(rid)
                    self.router.rejoin(rid)
                    self._event("autoscale_down_cancelled",
                                replica=rid)
                    continue
                self._retiring.discard(rid)
                self._c_downs.inc()
                self._event("autoscale_down_done", replica=rid)

    # ------------------------------------------------------------ rollout
    def rollout(self, new_params=None, *, version,
                swap: Optional[Callable[[Any], None]] = None,
                rollback: Optional[Callable[[Any], None]] = None
                ) -> None:
        """Start a rolling weight update to ``version``.

        Default swap: ``engine.swap_params(new_params, version)`` (the
        resident engines).  For decomposed/streamed engines pass
        ``swap=`` (e.g. wrapping
        :meth:`~deepspeed_tpu.inference.zero_inference.
        ZeroInferenceServingEngine.swap_weights`) and ``rollback=`` —
        without a rollback callable the autoscaler captures each
        engine's served param tree before swapping and restores it via
        ``swap_params``, which only works when the engine serves a
        plain tree.

        The walk advances inside :meth:`tick`: drain the next replica
        (warm digest handed PAST the upcoming rollout target — the
        drain-successor guard), swap once drained, rejoin, then soak
        ``rollout_soak_steps`` ticks watching the new version's burn
        rate before the next replica.  A trip halts and rolls back.
        A replica that dies mid-rollout is skipped (failover already
        salvaged its work) and the walk continues on the survivors."""
        if self._rollout is not None:
            raise RuntimeError(
                f"rollout to {self._rollout['version']!r} is still in "
                "progress — one rollout at a time")
        if swap is None:
            if new_params is None:
                raise ValueError(
                    "rollout needs new_params (for the default "
                    "swap_params path) or an explicit swap= callable")
            swap = lambda eng: eng.swap_params(new_params, version)  # noqa: E731
        plan = [rid for rid, rep in self.router.replicas.items()
                if rep.state != DEAD]
        if not plan:
            raise RuntimeError("rollout on a fleet with no live replicas")
        if rollback is None:
            for rid in plan:
                if self.router.replicas[rid].engine.params is None:
                    raise ValueError(
                        f"replica {rid} serves a decomposed weight "
                        "image (params tree is None) — pass rollback= "
                        "alongside swap= so a halted rollout can "
                        "restore it")
        self._rollout = {
            "version": version,
            "plan": plan, "i": 0,
            "state": "next",
            "target": None,
            "updated": [],
            "skipped": [],
            "old": {},          # rid -> (params, version) for rollback
            "swap": swap, "rollback": rollback,
            "soak_left": 0,
            "rb_queue": [],
            "halted": False, "rolled_back": False,
            "halt_burn": None,
            "t0": time.perf_counter(),
        }
        self._event("rollout_start", version=str(version),
                    replicas=len(plan))

    @property
    def rollout_active(self) -> bool:
        return self._rollout is not None

    def _version_burn(self, version):
        """(max burn, classified-request count) across live replicas
        serving ``version`` — the halt-and-rollback trigger reads the
        NEW version's burn only, so a sick old replica cannot veto its
        own replacement."""
        worst, n = 0.0, 0
        for rep in self.router.replicas.values():
            if rep.state == DEAD or str(rep.version) != str(version):
                continue
            snap = rep.engine.slo_tracker.snapshot()
            if not snap.get("enabled"):
                continue
            for t in snap.get("tiers", {}).values():
                n += int(t.get("window_finished", 0))
                for b in t.get("burn_rates", {}).values():
                    worst = max(worst, float(b))
        return worst, n

    def _swap_and_rejoin(self, rid: str, swap) -> bool:
        """Swap a drained replica's weights and put it back in
        rotation; False = the swap failed (the replica rejoins on its
        OLD weights so capacity is never stranded — the event's
        ``version`` records what it actually serves)."""
        rep = self.router.replicas[rid]
        try:
            swap(rep.engine)
            ok = True
        except Exception:
            logger.exception("autoscale: weight swap on %s", rid)
            ok = False
        self.router.rejoin(rid)
        self._c_rollout_steps.inc()
        self._event("rollout_step", replica=rid,
                    version=str(rep.version), ok=ok)
        return ok

    def _advance_rollout(self, now: float) -> None:
        ro = self._rollout
        router = self.router
        state = ro["state"]

        if state == "next":
            while ro["i"] < len(ro["plan"]):
                rid = ro["plan"][ro["i"]]
                rep = router.replicas.get(rid)
                if rep is None or rep.state == DEAD:
                    # died before its turn: failover salvaged it,
                    # the walk continues on the survivors
                    ro["skipped"].append(rid)
                    self._event("rollout_target_died", replica=rid)
                    ro["i"] += 1
                    continue
                if rid in self._retiring or rep.state == DRAINING:
                    # already leaving the ring (scale-down or an
                    # operator drain): not ours to update
                    ro["skipped"].append(rid)
                    ro["i"] += 1
                    continue
                if str(rep.version) == str(ro["version"]):
                    ro["i"] += 1    # already current (spawned mid-roll)
                    continue
                # drain-successor guard: the warm digest must skip the
                # NEXT rollout target — it is about to drain too, and
                # the hint would die there
                upcoming = {r for r in ro["plan"][ro["i"] + 1:]
                            if r in router.replicas
                            and router.replicas[r].state != DEAD}
                ro["target"] = rid
                router.drain(rid, successor_exclude=upcoming)
                ro["state"] = "draining"
                return
            # walked the whole plan: done
            self._finish_rollout(completed=True)
            return

        if state == "draining":
            rid = ro["target"]
            rep = router.replicas.get(rid)
            if rep is None or rep.state == DEAD:
                ro["skipped"].append(rid)
                self._event("rollout_target_died", replica=rid)
                ro["i"] += 1
                ro["state"] = "next"
                return
            if not router.drained(rid):
                return
            eng = rep.engine
            ro["old"][rid] = (eng.params, eng.weights_version)
            if self._swap_and_rejoin(rid, ro["swap"]):
                ro["updated"].append(rid)
            ro["i"] += 1
            ro["soak_left"] = self.cfg.rollout_soak_steps
            ro["state"] = "soaking"
            return

        if state == "soaking":
            burn, n = self._version_burn(ro["version"])
            if n >= self.cfg.rollback_min_finished and \
                    burn > self.cfg.rollback_burn_threshold:
                ro["halted"] = True
                ro["halt_burn"] = round(burn, 3)
                ro["rb_queue"] = [r for r in reversed(ro["updated"])
                                  if r in router.replicas]
                ro["state"] = "rolling_back"
                self._c_rollbacks.inc()
                self._event("rollout_halt", version=str(ro["version"]),
                            burn=ro["halt_burn"],
                            updated=len(ro["updated"]))
                return
            ro["soak_left"] -= 1
            if ro["soak_left"] <= 0:
                ro["state"] = "next"
            return

        if state == "rolling_back":
            rid = ro["target"]
            if rid is not None and ro.get("rb_draining"):
                rep = router.replicas.get(rid)
                if rep is None or rep.state == DEAD:
                    ro["rb_draining"] = False
                    ro["target"] = None
                elif router.drained(rid):
                    old_params, old_version = ro["old"][rid]
                    rb = ro["rollback"]
                    if rb is None:
                        rb = (lambda eng, _p=old_params, _v=old_version:
                              eng.swap_params(_p, _v))
                    self._swap_and_rejoin(rid, rb)
                    ro["rb_draining"] = False
                    ro["target"] = None
                else:
                    return
            while ro["rb_queue"]:
                rid = ro["rb_queue"].pop(0)
                rep = router.replicas.get(rid)
                if rep is None or rep.state == DEAD:
                    continue
                ro["target"] = rid
                router.drain(rid)
                ro["rb_draining"] = True
                return
            ro["rolled_back"] = True
            self._finish_rollout(completed=False)
            return

    def _finish_rollout(self, completed: bool) -> None:
        ro = self._rollout
        summary = {
            "version": str(ro["version"]),
            "completed": completed,
            "halted": ro["halted"],
            "rolled_back": ro["rolled_back"],
            "halt_burn": ro["halt_burn"],
            "updated": len(ro["updated"]),
            "skipped": list(ro["skipped"]),
            "total": len(ro["plan"]),
            "duration_s": round(time.perf_counter() - ro["t0"], 3),
        }
        if completed:
            # future scale-ups must serve the new version: remember how
            # to bring a factory-fresh engine onto it
            self._current_weights = (ro["swap"], ro["version"])
            self._event("rollout_done", version=str(ro["version"]),
                        updated=len(ro["updated"]))
        else:
            self._event("rollout_rolled_back",
                        version=str(ro["version"]),
                        restored=len(ro["updated"]))
        self.last_rollout = summary
        self._rollout = None

    # ------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        """The fleet ``/statusz`` ``elastic`` block (host-side
        bookkeeping only — safe to poll; ``dstpu_top`` renders it)."""
        now = time.perf_counter()
        cooldown = 0.0
        if self._last_scale_t is not None:
            cooldown = max(
                0.0, self.cfg.cooldown_s - (now - self._last_scale_t))
        ro = self._rollout
        rollout: Dict[str, Any] = {"active": ro is not None}
        if ro is not None:
            rollout.update({
                "version": str(ro["version"]),
                "state": ro["state"],
                "updated": len(ro["updated"]),
                "total": len(ro["plan"]),
                "halted": ro["halted"],
            })
        elif self.last_rollout is not None:
            rollout.update(self.last_rollout)
        return {
            "enabled": self.cfg.enabled,
            "target_replicas": self.target,
            "min_replicas": self.cfg.min_replicas,
            "max_replicas": self.cfg.max_replicas,
            "live_replicas": sum(
                1 for rep in self.router.replicas.values()
                if rep.state != DEAD),
            "scale_ups": int(self._c_ups.value),
            "scale_downs": int(self._c_downs.value),
            "factory_failures": int(self._c_factory_failures.value),
            "cold_flips": int(self._c_flips.value),
            "rollout_steps": int(self._c_rollout_steps.value),
            "rollbacks": int(self._c_rollbacks.value),
            "cold_starts_in_flight": len(self._cold),
            "retiring": sorted(self._retiring),
            "cooldown_remaining_s": round(cooldown, 3),
            "pressure": {
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                **self._last_signals,
            },
            "rollout": rollout,
            "events": [
                {k: v for k, v in e.items() if k != "t"}
                for e in list(self.events)[-16:]],
        }

    # ------------------------------------------------------------- drive
    def run(self, max_steps: int = 10_000) -> Dict[Any, Any]:
        """Drive router + autoscaler until the fleet is idle AND no
        elastic operation (cold start, retirement, rollout) is in
        flight."""
        steps = 0

        def flip_pending():
            return any(rec["streamed"] and rec["flip_s"] is None
                       for rec in self._cold.values())

        while self.router.has_work or self._rollout is not None \
                or self._retiring or flip_pending():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError("elastic loop did not converge")
        return dict(self.router.finished)
