"""Out-of-process fleet: child replica processes behind the router.

The crossing of the process boundary ROADMAP open item 1 calls "the
refactor that unlocks genuine scale" — and deliberately NOT a second
router.  :class:`ProcEngine` is a proxy that speaks the exact
engine surface :class:`~deepspeed_tpu.fleet.FleetRouter` drives
(submit / step / take_queued / abandon_inflight / export_pages /
admit_fabric / healthz / check_leaks / warm_digest / shutdown), so
every fleet semantic — affinity routing, harvest-first failover, the
typed never-double-generate partition, drain, roles, statusz,
incidents — runs UNCHANGED over replicas that live in their own OS
processes.  The bytes move over :mod:`deepspeed_tpu.transport`
(shared-memory ring same-host, length-prefixed TCP generally),
selected by the ``transport`` config block.

Correctness never depends on the wire:

- Results are ack-retained in the child's outbox — a lost or corrupt
  poll reply re-delivers on the next poll; a frame that fails crc is
  dropped and the RPC retried.
- Migrated pages hop child → router fabric → child carrying their
  demote-time per-buffer crc32s verbatim; a corruption that survives
  the frame crc still dies at the importer's promotion-time checksum
  and re-prefills (``_promotion_fallback`` stays the last line).
- A SIGKILLed child needs no cooperation to fail over: the proxy
  mirrors the child's queued/in-flight state from every poll reply,
  so the router's salvage (``take_queued`` / ``abandon_inflight``)
  synthesizes the partition from last-reported knowledge — zero
  reported tokens re-places on a survivor, any reported tokens fails
  typed, and tokens that never surfaced through a harvest were never
  delivered to anyone, so at-most-once delivery holds.
"""

from __future__ import annotations

import json
import os
import select
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu import faults as faults_mod
from deepspeed_tpu import transport as tx
from deepspeed_tpu.config import (FaultsConfig, ProcFleetConfig,
                                  TracingConfig, TransportConfig)
from deepspeed_tpu.faults import FaultPlan
from deepspeed_tpu.fleet import FleetRouter
from deepspeed_tpu.history import NULL_HISTORY
from deepspeed_tpu.inference.serving import (EngineClosed, RequestFailed,
                                             RequestShed)
from deepspeed_tpu.request_trace import RequestTracer
from deepspeed_tpu.telemetry import MetricsRegistry
from deepspeed_tpu.utils.logging import logger

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHILD = os.path.join(_REPO, "tools", "replica_child.py")

# the default child: a tiny deterministic gpt2 — (config, seed) is the
# whole weight image, so every process on this host rebuilds identical
# params and cross-process token identity is checkable
DEFAULT_CHILD_SPEC: Dict[str, Any] = {
    "model": {"family": "gpt2", "dim": 32, "n_layers": 2,
              "n_heads": 2, "max_seq_len": 64},
    "engine": {"max_batch": 2, "page_size": 8, "num_pages": 24,
               "max_seq": 32, "prefill_bucket": 8},
    "seed": 0,
}


class _ReqRef:
    """The shape the router's salvage verbs actually read: an object
    with a ``req_id`` (the fleet ledger carries everything else)."""

    __slots__ = ("req_id",)

    def __init__(self, req_id):
        self.req_id = req_id


class _ProxySlo:
    """Last-known child SLO snapshot behind the tracker surface the
    router reads (``snapshot``/``forget``)."""

    def __init__(self):
        self._snap: Dict[str, Any] = {"enabled": False}

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        return dict(self._snap)

    def forget(self, req_id) -> None:
        pass


class _ProxyPool:
    """Digest-backed stand-in for the child's spill-pool index: the
    router's migration planner asks ``has``/``location`` for LOCAL
    coverage, and the freshest truth this process holds is the
    digest mirrored off poll replies and admit acknowledgements.
    Staleness only costs an extra (idempotent, already-warm-is-free)
    page shipment — never correctness."""

    def __init__(self, owner: "ProcEngine"):
        self._owner = owner

    def has(self, key: bytes) -> bool:
        return key in self._owner._digest

    def location(self, key: bytes) -> Optional[str]:
        return self._owner._digest.get(key)


class _ProxyAllocator:
    __slots__ = ("index",)

    def __init__(self):
        self.index: Dict[bytes, int] = {}


class ProcEngine:
    """One child replica process behind the ServingEngine duck-surface
    the router drives.  ``step()`` is a poll RPC (the child's own
    serve loop does the actual engine stepping between replies);
    everything the router later needs after a SIGKILL — queued ids,
    per-request progress, health, digest — is mirrored out of every
    reply, because a dead child answers nothing."""

    def __init__(self, proc: subprocess.Popen, chan: tx.Channel,
                 caps: Dict[str, Any], *, rid: str,
                 pf: ProcFleetConfig, tracer=None,
                 registry: Optional[MetricsRegistry] = None,
                 http_port: Optional[int] = None,
                 ring_paths: Tuple[str, ...] = ()):
        self.proc = proc
        self.chan = chan
        self.cfg = pf
        self.replica_id = rid
        self.http_port = http_port
        self._ring_paths = ring_paths
        self.page_size = int(caps["page_size"])
        self.max_seq = int(caps["max_seq"])
        self.eos = caps.get("eos")
        self.weights_version = caps.get("weights_version")
        self._pc_on = bool(caps.get("pc_on", False))
        self._kvt_on = bool(caps.get("kvt_on", False))
        self.registry = registry if registry is not None \
            else MetricsRegistry(namespace=f"dstpu_{rid}")
        if tracer is not None and getattr(tracer, "enabled", False) \
                and hasattr(tracer, "bind"):
            self.tracer = tracer.bind(replica=rid)
        else:
            from deepspeed_tpu.request_trace import NULL_TRACER
            self.tracer = tracer if tracer is not None else NULL_TRACER
        self.history = NULL_HISTORY
        self.slo_tracker = _ProxySlo()
        self.allocator = _ProxyAllocator()
        self._kv_pool = _ProxyPool(self)
        self._fabric = None
        self.finished: Dict[Any, Any] = {}
        # ---- the SIGKILL mirror: last-reported child state
        self.queue: List[Any] = []           # queued req_ids
        self._active: Dict[Any, int] = {}    # req_id -> generated
        self._digest: Dict[bytes, str] = {}
        self._digest_v = -1
        self._child_has_work = False
        self._health: Optional[Dict[str, Any]] = None
        self._health_t = -1e18
        self._counters = {"n_shed": 0, "n_failed": 0, "n_submitted": 0}
        self._ack = -1
        self._closed = False

    # --------------------------------------------------------- plumbing
    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def slots(self) -> List[Any]:
        # the router only counts non-None entries
        return list(self._active.keys())

    @property
    def has_work(self) -> bool:
        return bool(self.queue or self._active or self._child_has_work)

    @property
    def _n_shed(self) -> int:
        return self._counters["n_shed"]

    @property
    def _n_failed(self) -> int:
        return self._counters["n_failed"]

    @property
    def _n_submitted(self) -> int:
        return self._counters["n_submitted"]

    def child_alive(self) -> bool:
        return not self._closed and self.proc.poll() is None

    def _rpc(self, msg: Dict[str, Any], blobs=(), *,
             timeout_s: Optional[float] = None,
             retries: int = 1) -> Tuple[Dict[str, Any], List[Any]]:
        """One RPC to the child; every op in the protocol is
        idempotent under retry, so a corrupt/lost frame costs one
        resend.  Any unrecoverable failure (dead process, exhausted
        retries) surfaces as :class:`EngineClosed` — the exact typed
        signal the router's placement and health paths already treat
        as 'this replica cannot serve'."""
        if self._closed:
            raise EngineClosed(
                f"proxy for replica {self.replica_id} is shut down")
        last: Optional[BaseException] = None
        for _ in range(retries + 1):
            rc = self.proc.poll()
            if rc is not None:
                raise EngineClosed(
                    f"replica {self.replica_id} child process died "
                    f"(rc={rc})")
            try:
                rep, rblobs = self.chan.request(
                    msg, blobs,
                    timeout_s=timeout_s or self.cfg.poll_timeout_s)
            except tx.TransportError as e:
                last = e
                continue
            if rep.get("closed"):
                raise EngineClosed(
                    f"replica {self.replica_id} engine is closed")
            return rep, rblobs
        raise EngineClosed(
            f"replica {self.replica_id} transport failed: {last}")

    # ------------------------------------------------------- submission
    def submit(self, req_id, tokens, max_new_tokens: int = 32,
               temperature: float = 0.0, tier: Optional[str] = None,
               arrival: Optional[float] = None):
        age = 0.0 if arrival is None \
            else max(0.0, time.perf_counter() - arrival)
        rep, _ = self._rpc({
            "op": "submit", "req_id": req_id,
            "tokens": [int(t) for t in tokens],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "tier": tier, "age_s": age,
        })
        if rep.get("error"):
            raise ValueError(rep["error"])
        if "shed" in rep:
            shed = RequestShed(req_id, rep["shed"]["reason"],
                               rep["shed"].get("tier"))
            # mirror the in-process contract: a shed is recorded in
            # finished AND returned (the router pops it on retry)
            self.finished[req_id] = shed
            self._counters["n_shed"] += 1
            self._counters["n_submitted"] += 1
            return shed
        self._counters["n_submitted"] += 1
        self.queue = list(self.queue) + [req_id]
        return None

    # ------------------------------------------------------------ step
    def step(self) -> List[Any]:
        rep, _ = self._rpc({"op": "poll", "ack": self._ack})
        self._absorb_poll(rep)
        return []

    def _absorb_poll(self, rep: Dict[str, Any]) -> None:
        for idx, enc in rep.get("results", []):
            self._ack = max(self._ack, int(idx))
            rid = enc["rid"]
            kind = enc.get("kind")
            if kind == "ok":
                self.finished[rid] = [int(t) for t in enc["tokens"]]
            elif kind == "shed":
                self.finished[rid] = RequestShed(
                    rid, enc["reason"], enc.get("tier"))
            else:
                self.finished[rid] = RequestFailed(
                    rid, enc["reason"], enc.get("error", ""),
                    enc.get("tier"),
                    generated=int(enc.get("generated", 0)))
            self._active.pop(rid, None)
        prog = rep.get("progress")
        if prog is not None:
            self.queue = list(prog.get("queued", []))
            self._active = {rid: int(g)
                            for rid, g in prog.get("active", [])}
        self._child_has_work = bool(rep.get("has_work", False))
        h = rep.get("healthz")
        if h is not None:
            self._health, self._health_t = h, time.monotonic()
        slo = rep.get("slo")
        if slo is not None:
            self.slo_tracker._snap = slo
        c = rep.get("counters")
        if c is not None:
            self._counters.update(c)
        d = rep.get("digest")
        if d is not None and rep.get("digest_v", 0) > self._digest_v:
            self._digest = {bytes.fromhex(k): v for k, v in d.items()}
            self._digest_v = int(rep.get("digest_v", 0))

    # ----------------------------------------------------------- health
    def healthz(self) -> Dict[str, Any]:
        rc = self.proc.poll()
        if rc is not None:
            # the SIGKILL detection path: the router's health poll
            # turns this into _fail_replica on the next step
            raise EngineClosed(
                f"replica {self.replica_id} child process died "
                f"(rc={rc})")
        now = time.monotonic()
        if self._health is not None and \
                now - self._health_t < self.cfg.health_cache_s:
            return self._health
        rep, _ = self._rpc({"op": "healthz"})
        self._health, self._health_t = rep, time.monotonic()
        return rep

    # -------------------------------------------------- fleet handoffs
    def take_queued(self) -> List[_ReqRef]:
        """Queue salvage.  Live child: a real RPC pops its queue.
        Dead child: synthesize from the mirror — these requests never
        reported progress, so re-placing them cannot double-generate."""
        rids = list(self.queue)
        if self.child_alive():
            try:
                rep, _ = self._rpc({"op": "take_queued"})
                rids = list(rep.get("queued", []))
            except EngineClosed:
                pass            # fall back to the mirror
        self.queue = []
        return [_ReqRef(r) for r in rids]

    def abandon_inflight(self) -> List[Tuple[_ReqRef, int]]:
        """Slot salvage.  Dead child: last-REPORTED token counts
        drive the router's partition — any harvested progress fails
        typed (re-running would double-generate), zero-progress work
        re-places.  Tokens generated after the last poll never
        surfaced to any caller, so at-most-once delivery holds."""
        pairs = [[rid, g] for rid, g in self._active.items()]
        if self.child_alive():
            try:
                rep, _ = self._rpc({"op": "abandon"})
                pairs = rep.get("inflight", pairs)
            except EngineClosed:
                pass
        self._active = {}
        return [(_ReqRef(r), int(g)) for r, g in pairs]

    # -------------------------------------------------------- fabric
    def attach_fabric(self, fabric) -> None:
        if fabric is not None and not self._kvt_on:
            raise ValueError(
                "attach_fabric needs the kv_tier block — the child's "
                "spill pool is the admission side of the transport")
        self._fabric = fabric

    def warm_digest(self) -> Dict[bytes, str]:
        return dict(self._digest)

    # dstpu: hot-path — page trains cross the process boundary here
    def export_pages(self, keys: List[bytes], fabric=None) -> int:
        """Owner-side migration leg: the child exports into its
        transit fabric and ships the serialized entries (crc32s
        riding verbatim); this proxy republishes them into the
        ROUTER's fabric, where the usual publish-side fault rules and
        kv_fabric_* metrics apply."""
        fab = fabric if fabric is not None else self._fabric
        if fab is None or not self._kvt_on:
            raise ValueError(
                "export_pages needs an attached fabric and the "
                "kv_tier block")
        rep, blobs = self._rpc(
            {"op": "export", "keys": [k.hex() for k in keys]},
            timeout_s=self.cfg.poll_timeout_s)
        if rep.get("error"):
            raise IOError(
                f"replica {self.replica_id} export failed: "
                f"{rep['error']}")
        for e in tx.entries_from_frame(rep, blobs):
            try:
                fab.publish(e.key, e)
            except Exception:
                break           # chain-prefix discipline: stop here
        return fab.covers(keys)

    # dstpu: hot-path — page trains cross the process boundary here
    def admit_fabric(self, keys: List[bytes],
                     deadline: Optional[float] = None) -> int:
        """Target-side migration leg: fetch the chain out of the
        ROUTER's fabric (its fetch-side fault rules and metrics fire
        here, same as in-process) and ship it to the child, whose own
        ``admit_fabric`` runs the checksum-verified promotion path."""
        fab = self._fabric
        if fab is None or not self._kvt_on:
            raise ValueError(
                "admit_fabric needs an attached fabric and the "
                "kv_tier block")
        entries = []
        for k in keys:
            if k in self._digest:
                continue        # child-warm already: nothing to ship
            if not fab.has(k):
                break
            try:
                entries.append(fab.fetch(k))
            except (KeyError, IOError, OSError):
                break
        budget = 5.0 if deadline is None \
            else max(0.05, deadline - time.perf_counter())
        msg, blobs = tx.entries_to_frame(entries, {
            "op": "admit", "keys": [k.hex() for k in keys],
            "budget_s": budget})
        rep, _ = self._rpc(
            msg, blobs, timeout_s=budget + self.cfg.poll_timeout_s)
        for kh, loc in rep.get("locations", []):
            self._digest[bytes.fromhex(kh)] = loc
        return int(rep.get("admitted", 0))

    # ------------------------------------------------------- accounting
    def check_leaks(self) -> List[str]:
        if not self.child_alive():
            # a SIGKILLed child's pages died with its address space —
            # there is nothing left to leak in THIS process tree
            return []
        try:
            rep, _ = self._rpc({"op": "check_leaks"})
        except EngineClosed:
            return []
        return list(rep.get("leaks", []))

    # -------------------------------------------------------- lifecycle
    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        p = self.proc
        if p.poll() is None:
            try:
                self.chan.request({"op": "shutdown"}, timeout_s=1.0)
            except Exception:
                pass
            try:
                p.terminate()
                p.wait(timeout=self.cfg.shutdown_grace_s)
            except subprocess.TimeoutExpired:
                logger.warning(
                    "proc_fleet: replica %s ignored SIGTERM for %.1fs "
                    "— SIGKILL", self.replica_id,
                    self.cfg.shutdown_grace_s)
                p.kill()
                try:
                    p.wait(timeout=self.cfg.shutdown_grace_s)
                except subprocess.TimeoutExpired:
                    pass
            except Exception:
                pass
        else:
            try:
                p.wait(timeout=1.0)
            except Exception:
                pass
        self.chan.close()


# --------------------------------------------------------------------
# spawn + builder
# --------------------------------------------------------------------

def _read_handshake(p: subprocess.Popen,
                    timeout_s: float) -> Dict[str, Any]:
    deadline = time.monotonic() + timeout_s
    while True:
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise RuntimeError(
                f"replica child pid {p.pid} produced no handshake "
                f"within {timeout_s}s")
        r, _, _ = select.select([p.stdout], [], [], min(rem, 1.0))
        if r:
            line = p.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"replica child died before the handshake "
                    f"(rc={p.poll()})")
            return json.loads(line)
        if p.poll() is not None:
            raise RuntimeError(
                f"replica child died before the handshake "
                f"(rc={p.poll()})")


def spawn_replica(rid: str, spec: Dict[str, Any], *,
                  transport: Optional[TransportConfig] = None,
                  proc_fleet: Optional[ProcFleetConfig] = None,
                  workdir: Optional[str] = None,
                  tracer=None) -> ProcEngine:
    """Spawn one child replica process and connect its transport.
    ``transport.kind`` ``"auto"`` resolves to shm — the children this
    builder spawns are same-host by construction; pin ``"tcp"`` to
    exercise the general path."""
    tc = TransportConfig.coerce(transport)
    pf = ProcFleetConfig.coerce(proc_fleet)
    kind = "shm" if tc.kind == "auto" else tc.kind
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # children build 1-device CPU
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, _CHILD, "--replica", rid,
           "--requests", "0",
           "--engine-json", json.dumps(spec),
           "--transport", kind,
           "--accept-timeout-s", str(pf.spawn_timeout_s)]
    rings: Tuple[str, ...] = ()
    if kind == "shm":
        wd = workdir or tempfile.mkdtemp(prefix="dstpu-shm-")
        c2s, s2c = tx.create_shm_pair(
            wd, rid, slot_bytes=tc.slot_bytes, n_slots=tc.ring_slots)
        rings = (c2s, s2c)
        cmd += ["--shm-c2s", c2s, "--shm-s2c", s2c]
    p = subprocess.Popen(cmd, cwd=_REPO, env=env, text=True,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL)
    try:
        hs = _read_handshake(p, pf.spawn_timeout_s)
        registry = MetricsRegistry(namespace=f"dstpu_{rid}")
        if kind == "shm":
            endpoint = tx.attach_shm_pair(rings[0], rings[1], "client")
            reconnect = None
        else:
            port = int(hs["tcp_port"])
            endpoint = tx.connect_tcp(
                "127.0.0.1", port, attempts=tc.connect_attempts,
                backoff_s=tc.backoff_s, timeout_s=tc.io_timeout_s)
            reconnect = lambda: tx.connect_tcp(        # noqa: E731
                "127.0.0.1", port, attempts=tc.connect_attempts,
                backoff_s=tc.backoff_s, timeout_s=tc.io_timeout_s)
        chan = tx.Channel(endpoint, peer=rid, registry=registry,
                          reconnect=reconnect,
                          io_timeout_s=tc.io_timeout_s)
        return ProcEngine(p, chan, hs["caps"], rid=rid, pf=pf,
                          tracer=tracer, registry=registry,
                          http_port=hs.get("port"),
                          ring_paths=rings)
    except Exception:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=10)
        raise


class ProcFleetRouter(FleetRouter):
    """A FleetRouter whose replicas are :class:`ProcEngine` proxies;
    teardown additionally reaps the children's shm ring files."""

    _proc_workdir: Optional[str] = None

    def kill_child(self, rid: str, sig: int = signal.SIGKILL) -> float:
        """Deliver a REAL signal to a child replica process (the
        chaos soak's mid-generation SIGKILL).  Returns the kill time
        (perf_counter) so recovery_s is measured from the actual
        signal, not from detection."""
        eng = self.replicas[rid].engine
        t = time.perf_counter()
        os.kill(eng.pid, sig)
        return t

    def shutdown(self) -> None:
        super().shutdown()
        wd = self._proc_workdir
        if wd is not None:
            shutil.rmtree(wd, ignore_errors=True)
            self._proc_workdir = None


def proc_fleet_router(spec: Optional[Dict[str, Any]] = None, *,
                      proc_fleet=None, transport=None, fleet=None,
                      telemetry=None, tracing=None, faults=None,
                      fabric=None, history=None,
                      incidents=None) -> ProcFleetRouter:
    """Build a fleet of OUT-OF-PROCESS replicas over one child spec.

    The shape mirrors :func:`~deepspeed_tpu.fleet.fleet_router`: one
    shared tracer, one fault plan installed by the router (transport
    and fabric rules fire in THIS process, where the channels and the
    router fabric live), per-replica ``dstpu_r{i}`` metric namespaces
    (here carrying the proxy's ``transport_*`` channel family).  The
    children rebuild identical params from ``(spec.model,
    spec.seed)``; the router speaks the wire through
    :class:`ProcEngine` proxies and every FleetRouter behavior —
    routing, migration, failover, drain, statusz — applies verbatim.
    With ``proc_fleet.attach_scrape`` the children's HTTP wire
    surfaces additionally ride the PR 19 scrape plane as
    ``RemoteReplica`` rows."""
    pf = ProcFleetConfig.coerce(proc_fleet)
    tc = TransportConfig.coerce(transport)
    spec = spec if spec is not None else DEFAULT_CHILD_SPEC
    tracer = RequestTracer.from_config(TracingConfig.coerce(tracing))
    if isinstance(faults, FaultPlan):
        plan: Optional[FaultPlan] = faults
    else:
        fcfg = FaultsConfig.coerce(faults)
        plan = FaultPlan.from_config(fcfg) if fcfg.enabled else None
    # install BEFORE any channel exists: ownership lands on the router
    installed_here = faults_mod.ensure_installed(plan)
    workdir = tempfile.mkdtemp(prefix="dstpu-procfleet-")
    engines: List[ProcEngine] = []
    try:
        for i in range(pf.replicas):
            engines.append(spawn_replica(
                f"r{i}", spec, transport=tc, proc_fleet=pf,
                workdir=workdir, tracer=tracer))
        router = ProcFleetRouter(
            engines, fleet=fleet, telemetry=telemetry, faults=plan,
            tracer=tracer, fabric=fabric, history=history,
            incidents=incidents)
    except Exception:
        for e in engines:
            try:
                e.shutdown()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)
        if installed_here:
            faults_mod.clear_fault_plan(plan)
        raise
    if installed_here:
        router._owns_fault_plan = True
    router._proc_workdir = workdir
    if pf.attach_scrape:
        from deepspeed_tpu.config import ObsWireConfig
        scfg = ObsWireConfig(enabled=True, poll_interval_s=0.2,
                             timeout_s=2.0, stale_after_s=2.0,
                             lost_after_s=6.0)
        for e in engines:
            if e.http_port:
                router.attach_remote(
                    url=f"http://127.0.0.1:{e.http_port}",
                    rid=f"scrape-{e.replica_id}", cfg=scfg)
    return router
