"""LoRA adapter training (ref: deepspeed/linear/optimized_linear.py
LoRAOptimizedLinear + deepspeed/linear/config.py LoRAConfig — the
reference wraps Linear modules so only the low-rank A/B factors train,
with the frozen base weight optionally sharded).

TPU design: models here are pure pytrees, so LoRA is a TREE transform,
not a module wrapper.  The ENGINE's params are just the adapter tree —
optimizer state, ZeRO sharding, and checkpoints are all adapter-sized
(the entire point of LoRA: a 0.1% state footprint) — while the frozen
base weights are closed over by the loss and baked into the jitted step
as device constants.  Each step traces ``W_eff = W + (alpha/r)·A@B`` per
target leaf; XLA fuses the rank-r matmul + add into the consumer region,
so no persistent merged copy exists and gradients flow only to A/B by
construction (the base is not an argument).

Example::

    lcfg = LoRAConfig(lora_r=8, lora_alpha=16,
                      target_modules=("wq", "wv"))
    adapters = init_lora(jax.random.PRNGKey(0), base_params, lcfg)
    engine, _, _, _ = dstpu.initialize(
        loss_fn=lora_loss_fn(llama.loss_fn(cfg), base_params, lcfg),
        params=adapters, config={...})
    ...
    merged = merge_lora(base_params, engine.module_params(), lcfg)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.trees import leaf_path as _leaf_path


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """ref: deepspeed/linear/config.py LoRAConfig (lora_r, lora_alpha,
    base_weight_sharding — the last is moot here: GSPMD shards the frozen
    base like any other constant)."""

    lora_r: int = 8
    lora_alpha: int = 32
    target_modules: Sequence[str] = ("wq", "wk", "wv", "wo")

    @property
    def scale(self) -> float:
        return self.lora_alpha / self.lora_r

    def matches(self, path: str) -> bool:
        leaf = path.split(".")[-1]
        return any(t == leaf or t == path for t in self.target_modules)


def _target_leaves(params: Any, cfg: LoRAConfig):
    out = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        path = _leaf_path(kp)
        if cfg.matches(path) and getattr(leaf, "ndim", 0) >= 2:
            out.append((path, leaf))
    if not out:
        raise ValueError(
            f"no parameter matched target_modules={cfg.target_modules!r} "
            "— check the leaf names against your params tree")
    return out


def init_lora(rng: jax.Array, base_params: Any, cfg: LoRAConfig,
              dtype=jnp.float32) -> Any:
    """Adapter tree {path: {"A": [..., in, r], "B": [..., r, out]}}.

    A is gaussian (1/r std), B zeros — so training starts exactly at the
    base model (reference init).  Stacked-layer leaves ([L, in, out])
    get stacked adapters ([L, in, r] / [L, r, out]).
    """
    adapters = {}
    for path, leaf in _target_leaves(base_params, cfg):
        rng, k = jax.random.split(rng)
        *lead, din, dout = leaf.shape
        adapters[path] = {
            "A": (jax.random.normal(k, (*lead, din, cfg.lora_r))
                  / cfg.lora_r).astype(dtype),
            "B": jnp.zeros((*lead, cfg.lora_r, dout), dtype),
        }
    return adapters


def _delta(ad, scale, dtype):
    return (scale * ad["A"].astype(jnp.float32)
            @ ad["B"].astype(jnp.float32)).astype(dtype)


def apply_lora(base_params: Any, adapters: Any, cfg: LoRAConfig) -> Any:
    """Effective params: base + scale·A@B on target leaves (traced —
    call inside the loss/forward)."""
    flat = dict(adapters)

    def leaf(kp, w):
        ad = flat.get(_leaf_path(kp))
        if ad is None:
            return w
        return w + _delta(ad, cfg.scale, w.dtype)

    return jax.tree_util.tree_map_with_path(leaf, base_params)


def lora_loss_fn(base_loss_fn: Callable, base_params: Any,
                 cfg: LoRAConfig, compute_dtype=jnp.bfloat16) -> Callable:
    """``(adapters, batch) -> loss`` for ``initialize(params=adapters)``.

    The frozen base is captured in compute precision (no f32 master is
    ever built for it — it does not train)."""
    frozen = jax.tree.map(
        lambda x: jax.lax.stop_gradient(x.astype(compute_dtype))
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
        base_params)

    def f(adapters, batch):
        return base_loss_fn(apply_lora(frozen, adapters, cfg), batch)

    return f


def merge_lora(base_params: Any, adapters: Any, cfg: LoRAConfig) -> Any:
    """Fold trained adapters into a standalone checkpoint-ready tree
    (ref: peft merge_and_unload / the reference's full-weight export)."""
    return apply_lora(base_params, jax.tree.map(jnp.asarray, adapters), cfg)


def count_trainable(adapters: Any) -> Tuple[int, int]:
    """(n_adapter_params, bytes) — the LoRA footprint."""
    n = sum(l.size for l in jax.tree.leaves(adapters))
    b = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(adapters))
    return n, b
