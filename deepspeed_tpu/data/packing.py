"""Sequence packing for the packed-attention path (ref: the data
efficiency suite's variable-length batching; packing is the standard
TPU-side answer — static [B, T] shapes keep XLA happy while segment ids
keep documents isolated in attention and loss).

Produces batches in the llama ``loss_fn`` contract: ``tokens`` [B, T]
int32 and token-aligned ``segment_ids`` [B, T] int32 where id 0 is
padding and each document gets 1, 2, ... per row.  Downstream,
``models/llama.py`` (and Mixtral) isolate attention per id and mask
cross-document / padding targets out of the CE
(`llama.packed_doc_mask`).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

import numpy as np


def pack_documents(docs: Iterable[Sequence[int]], seq_len: int,
                   pad_id: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Greedy first-fit packing → (tokens [B, T], segment_ids [B, T]).

    Deterministic for a given doc order.  Documents longer than
    ``seq_len`` are truncated (the reference's seqlen truncation
    behavior); empty documents are skipped.
    """
    rows: List[List[int]] = []
    segs: List[List[int]] = []
    for doc in docs:
        doc = list(doc[:seq_len])
        if not doc:
            continue
        for r in range(len(rows)):
            if len(rows[r]) + len(doc) <= seq_len:
                segs[r] += [segs[r][-1] + 1] * len(doc)
                rows[r] += doc
                break
        else:
            rows.append(doc)
            segs.append([1] * len(doc))
    B = len(rows)
    tokens = np.full((B, seq_len), pad_id, np.int32)
    segments = np.zeros((B, seq_len), np.int32)
    for r in range(B):
        tokens[r, :len(rows[r])] = rows[r]
        segments[r, :len(segs[r])] = segs[r]
    return tokens, segments


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """Fraction of slots holding real tokens (1.0 = zero padding)."""
    seg = np.asarray(segment_ids)
    return float((seg > 0).mean()) if seg.size else 0.0


class PackedDataLoader:
    """Wraps an iterable of token-id documents into packed train batches
    ``{"tokens", "segment_ids"}`` of static shape [batch_rows, seq_len]
    (+1 column so the loss's next-token shift stays inside the row —
    the llama/Mixtral ``loss_fn`` contract).

    Greedy packing runs over a window of ``batch_rows * fill_factor``
    documents at a time; rows left over when a window can't fill a whole
    batch CARRY OVER into the pending pool and mix with the next
    window's rows (no row is emitted early), and the final short batch
    is padded up to ``batch_rows`` with empty (all-padding) rows so
    every batch has the same static shape.
    """

    def __init__(self, documents: Sequence[Sequence[int]],
                 batch_rows: int, seq_len: int, pad_id: int = 0,
                 fill_factor: int = 4):
        if batch_rows < 1 or seq_len < 2:
            raise ValueError("batch_rows >= 1 and seq_len >= 2 required")
        self.docs = documents
        self.batch_rows = batch_rows
        self.seq_len = seq_len
        self.pad_id = pad_id
        self.window = max(batch_rows * fill_factor, batch_rows)

    def __iter__(self):
        pending_t: List[np.ndarray] = []
        pending_s: List[np.ndarray] = []

        def emit():
            t = np.stack(pending_t[:self.batch_rows])
            s = np.stack(pending_s[:self.batch_rows])
            del pending_t[:self.batch_rows], pending_s[:self.batch_rows]
            return {"tokens": t, "segment_ids": s}

        for w0 in range(0, len(self.docs), self.window):
            toks, segs = pack_documents(
                self.docs[w0:w0 + self.window], self.seq_len + 1,
                self.pad_id)
            pending_t.extend(toks)
            pending_s.extend(segs)
            while len(pending_t) >= self.batch_rows:
                yield emit()
        if pending_t:
            pad_rows = self.batch_rows - len(pending_t)
            pending_t.extend(
                [np.full(self.seq_len + 1, self.pad_id, np.int32)]
                * pad_rows)
            pending_s.extend(
                [np.zeros(self.seq_len + 1, np.int32)] * pad_rows)
            yield emit()
