"""Host-side data loader (ref: deepspeed/runtime/dataloader.py
DeepSpeedDataLoader).

The reference wraps a torch DataLoader with a DistributedSampler per DP
rank.  Here the loader yields GLOBAL batches (dict/tuple of numpy arrays);
sharding onto the mesh happens when the jitted step consumes them (GSPMD
splits the batch dim across data axes).  A background prefetch thread
overlaps host batch assembly with device compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np


class DataLoader:
    def __init__(self, dataset: Sequence, batch_size: int,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None, prefetch: int = 2):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.prefetch = prefetch
        self.epoch = 0
        self._idx_svc = None  # lazy native shuffle service (csrc/hostruntime)

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _indices(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.dataset))
        # Epoch shuffle via the C++ index service (csrc/hostruntime.cpp),
        # off the GIL; falls back to numpy inside the service.
        if self._idx_svc is None or self._idx_svc.n != len(self.dataset):
            from deepspeed_tpu.io.native import ShuffleIndexService

            self._idx_svc = ShuffleIndexService(
                len(self.dataset), seed=self.seed, shuffle=True)
        return self._idx_svc.epoch_order(self.epoch)

    def __iter__(self) -> Iterator[Any]:
        idx = self._indices()
        nb = len(self)
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that re-checks stop so an abandoned iterator
            # doesn't leave this thread parked on a full queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            for b in range(nb):
                if stop.is_set():
                    return
                sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
                if not put(self.collate_fn([self.dataset[int(i)] for i in sel])):
                    return
            put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                yield item
        finally:
            stop.set()


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([it[i] for it in items])
                           for i in range(len(first)))
    return np.stack(items)


class RepeatingLoader:
    """ref: deepspeed/runtime/dataloader.py RepeatingLoader — endless iter."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self._it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self._it = iter(self.loader)
            return next(self._it)
