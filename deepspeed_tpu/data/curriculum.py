"""Curriculum learning (ref: deepspeed/runtime/data_pipeline/curriculum_scheduler.py
+ deepspeed/runtime/data_pipeline/config.py).

The reference schedules a "difficulty" (canonically sequence length) from
``min_difficulty`` to ``max_difficulty`` with fixed_linear / fixed_root /
fixed_discrete / custom schedules; the training loop truncates or re-packs
each batch to the current difficulty.

TPU-native notes: seqlen is a static shape, so each distinct difficulty is
one XLA compile.  ``difficulty_step`` (the reference's quantization knob,
default 8 there for sentence packing) doubles here as the recompile
limiter — difficulties only move in multiples of it, so a full curriculum
costs (max-min)/step compiles, each cached.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp


@dataclasses.dataclass
class CurriculumConfig:
    """ref: data_pipeline/config.py curriculum_learning block keys."""

    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"   # fixed_linear|fixed_root|fixed_discrete
    # schedule_config sub-keys (flattened, same names as reference):
    total_curriculum_step: int = 1000
    difficulty_step: int = 8
    root_degree: int = 2
    difficulty: Tuple[int, ...] = ()       # fixed_discrete: difficulty list
    max_step: Tuple[int, ...] = ()         # fixed_discrete: step boundaries

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CurriculumConfig":
        flat = dict(d)
        sched = flat.pop("schedule_config", {})
        flat.update(sched)
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in flat.items() if k in known}
        for tup in ("difficulty", "max_step"):
            if tup in kw:
                kw[tup] = tuple(kw[tup])
        return cls(**kw)


class CurriculumScheduler:
    """ref: curriculum_scheduler.py CurriculumScheduler — maps global step
    → difficulty."""

    def __init__(self, cfg: CurriculumConfig):
        self.cfg = cfg
        if cfg.schedule_type == "fixed_discrete":
            if not cfg.difficulty or len(cfg.max_step) != len(cfg.difficulty) - 1:
                raise ValueError(
                    "fixed_discrete needs difficulty list and max_step with "
                    "len(difficulty)-1 boundaries")
        elif cfg.schedule_type not in ("fixed_linear", "fixed_root"):
            raise ValueError(f"unknown schedule_type {cfg.schedule_type}")

    def _quantize(self, diff: float) -> int:
        c = self.cfg
        q = max(1, c.difficulty_step)
        d = int(diff // q) * q
        return int(min(max(d, c.min_difficulty), c.max_difficulty))

    def get_difficulty(self, global_step: int) -> int:
        c = self.cfg
        if not c.enabled:
            return c.max_difficulty
        if c.schedule_type == "fixed_discrete":
            for bound, diff in zip(c.max_step, c.difficulty):
                if global_step <= bound:
                    return int(diff)
            return int(c.difficulty[-1])
        frac = min(1.0, global_step / max(1, c.total_curriculum_step))
        if c.schedule_type == "fixed_root":
            frac = frac ** (1.0 / c.root_degree)
        diff = c.min_difficulty + (c.max_difficulty - c.min_difficulty) * frac
        return self._quantize(diff)


def truncate_to_difficulty(batch: Dict[str, jnp.ndarray] | jnp.ndarray,
                           seqlen: int,
                           seq_keys: Sequence[str] = ("input_ids", "labels",
                                                      "attention_mask",
                                                      "position_ids")):
    """Truncate the sequence axis (axis 1) to ``seqlen`` — the reference's
    batch post-processing for seqlen curriculum (megatron utils
    curriculum truncation)."""
    if isinstance(batch, dict):
        return {k: (v[:, :seqlen] if k in seq_keys and v.ndim >= 2 else v)
                for k, v in batch.items()}
    return batch[:, :seqlen]


# the one list of batch keys that carry a sequence axis — shared by
# every engine's curriculum hook so the engines cannot drift
ENGINE_SEQ_KEYS = ("tokens", "input_ids", "labels", "attention_mask",
                   "position_ids", "loss_mask", "segment_ids")


def apply_seqlen_curriculum(batch, scheduler, global_step: int):
    """One engine-facing entrypoint (TrainingEngine and
    ParamStreamEngine both call this): truncate the batch to the
    scheduler's current difficulty when the curriculum is seqlen-typed,
    pass the batch through untouched otherwise.  The untouched case is
    deliberate, not a silent gap: non-seqlen curriculum types are
    DATA-SAMPLING curricula — the loader/:class:`DifficultyIndexer`
    restricts which samples are drawn, and there is nothing for the
    engine's batch hook to do (same division of labor as the
    reference's data_efficiency pipeline vs megatron truncation)."""
    if scheduler is None or scheduler.cfg.curriculum_type != "seqlen":
        return batch
    return truncate_to_difficulty(
        batch, scheduler.get_difficulty(global_step),
        seq_keys=ENGINE_SEQ_KEYS)


# ------------------------------------------------- difficulty-ordered sampling
class DifficultyIndexer:
    """Data-analysis half of curriculum (ref: data_pipeline/data_sampling/
    data_analyzer.py, simplified): pre-computes a difficulty value per
    sample and serves index batches restricted to the current difficulty
    ceiling."""

    def __init__(self, difficulties: Sequence[float], seed: int = 0):
        self.diff = np.asarray(difficulties, np.float64)
        self.order = np.argsort(self.diff, kind="stable")
        self.sorted_diff = self.diff[self.order]
        self.rng = np.random.RandomState(seed)

    def eligible(self, max_difficulty: float) -> np.ndarray:
        hi = np.searchsorted(self.sorted_diff, max_difficulty, side="right")
        return self.order[:hi]

    def sample(self, batch_size: int, max_difficulty: float) -> np.ndarray:
        pool = self.eligible(max_difficulty)
        if len(pool) == 0:
            pool = self.order[:1]
        return self.rng.choice(pool, size=batch_size,
                               replace=len(pool) < batch_size)
