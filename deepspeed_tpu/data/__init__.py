"""Data pipeline (ref: deepspeed/runtime/dataloader.py, data_pipeline/)."""

from deepspeed_tpu.data.loader import DataLoader
