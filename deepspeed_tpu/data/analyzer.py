"""Offline dataset analysis for curriculum learning (ref:
deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py
DataAnalyzer / DistributedDataAnalyzer).

The reference maps metric functions over the training set ahead of time
(sharded across workers, merged into index files) so the curriculum
sampler can order samples by measured difficulty instead of a schedule
proxy.  Same here: host-side numpy over dataset shards — this is IO/CPU
work with no accelerator involvement — with per-worker shard files and
an explicit merge, feeding
:class:`~deepspeed_tpu.data.curriculum.DifficultyIndexer`.

Built-in metrics (the reference's two standard ones):

- ``seqlen``: non-pad token count per sample.
- ``vocab_rarity``: mean −log p(token) under the corpus unigram
  distribution (two passes: corpus counts, then per-sample score).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

from deepspeed_tpu.data.curriculum import DifficultyIndexer


def _tokens_of(sample) -> np.ndarray:
    if isinstance(sample, dict):
        for key in ("tokens", "input_ids", "text_ids"):
            if key in sample:
                return np.asarray(sample[key]).reshape(-1)
        raise KeyError(
            f"sample dict has none of tokens/input_ids/text_ids: "
            f"{list(sample)}")
    return np.asarray(sample).reshape(-1)


def seqlen_metric(pad_token_id: int = 0) -> Callable[[Any], float]:
    def f(sample):
        toks = _tokens_of(sample)
        return float(np.sum(toks != pad_token_id))

    return f


class VocabRarity:
    """Two-pass metric: ``fit`` accumulates corpus token counts, the call
    scores a sample by mean −log p(token)."""

    def __init__(self, vocab_size: int, pad_token_id: Optional[int] = None):
        self.counts = np.zeros(vocab_size, np.int64)
        self.pad = pad_token_id
        self._logp: Optional[np.ndarray] = None

    def fit(self, dataset: Sequence) -> "VocabRarity":
        V = len(self.counts)
        for sample in dataset:
            toks = _tokens_of(sample)
            if toks.size and (toks.min() < 0 or toks.max() >= V):
                raise ValueError(
                    f"token id {int(toks.min())}..{int(toks.max())} outside "
                    f"vocab_size {V} — did added special tokens grow the "
                    "vocab past the size passed to VocabRarity?")
            self.counts += np.bincount(toks, minlength=V)
        if self.pad is not None:
            self.counts[self.pad] = 0
        total = max(self.counts.sum(), 1)
        p = self.counts / total
        # unseen tokens are the HARDEST, not the easiest: floor p at 1e-12
        # so −log p is large for out-of-corpus ids instead of zero
        self._logp = np.log(np.maximum(p, 1e-12))
        return self

    def __call__(self, sample) -> float:
        if self._logp is None:
            raise RuntimeError("VocabRarity.fit(dataset) must run first")
        toks = _tokens_of(sample)
        if self.pad is not None:
            toks = toks[toks != self.pad]
        if toks.size == 0:
            return 0.0
        return float(-np.mean(self._logp[toks]))


class DataAnalyzer:
    """Map ``metric_fns`` over (a shard of) the dataset and persist the
    results (ref: DataAnalyzer.run_map / run_reduce).

    ``worker_id``/``num_workers`` shard by stride so each launcher process
    analyzes only its slice; :meth:`merge` runs once afterwards to
    combine shard files into one ``{metric}.npy`` per metric.
    """

    def __init__(self, metric_fns: Dict[str, Callable[[Any], float]],
                 save_path: str, worker_id: int = 0, num_workers: int = 1):
        if not metric_fns:
            raise ValueError("DataAnalyzer needs at least one metric fn")
        if not (0 <= worker_id < num_workers):
            raise ValueError(f"worker_id {worker_id} outside "
                             f"num_workers {num_workers}")
        self.metric_fns = dict(metric_fns)
        self.save_path = save_path
        self.worker_id = worker_id
        self.num_workers = num_workers
        os.makedirs(save_path, exist_ok=True)

    # ------------------------------------------------------------- map
    def _shard_file(self, metric: str, worker: int) -> str:
        return os.path.join(self.save_path,
                            f"{metric}.worker{worker}.npz")

    def run_map(self, dataset: Sequence) -> Dict[str, np.ndarray]:
        """Score this worker's stride-shard; writes one shard file per
        metric holding (indices, values)."""
        idx = np.arange(self.worker_id, len(dataset), self.num_workers)
        out = {}
        for name, fn in self.metric_fns.items():
            vals = np.asarray([fn(dataset[int(i)]) for i in idx], np.float64)
            np.savez(self._shard_file(name, self.worker_id),
                     indices=idx, values=vals)
            out[name] = vals
        return out

    # ---------------------------------------------------------- reduce
    def merge(self, dataset_len: int) -> Dict[str, np.ndarray]:
        """Combine every worker's shard files → ``{metric}.npy`` of
        length ``dataset_len`` (ref: run_reduce index merge)."""
        merged = {}
        for name in self.metric_fns:
            full = np.full(dataset_len, np.nan)
            for w in range(self.num_workers):
                path = self._shard_file(name, w)
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"missing shard {path} — worker {w} has not run "
                        "run_map yet")
                z = np.load(path)
                full[z["indices"]] = z["values"]
            if np.isnan(full).any():
                raise ValueError(
                    f"metric {name}: merged index has holes — worker "
                    "shards do not cover the dataset")
            np.save(os.path.join(self.save_path, f"{name}.npy"), full)
            merged[name] = full
        return merged

    # ------------------------------------------------------------ load
    @staticmethod
    def load(save_path: str, metric: str) -> np.ndarray:
        return np.load(os.path.join(save_path, f"{metric}.npy"))

    @staticmethod
    def indexer(save_path: str, metric: str,
                seed: int = 0) -> DifficultyIndexer:
        """The analysis→sampling handoff: measured difficulties into the
        curriculum sampler."""
        return DifficultyIndexer(DataAnalyzer.load(save_path, metric),
                                 seed=seed)
