"""Metric monitoring backends (ref: deepspeed/monitor/*).

The reference ships tensorboard/wandb/csv writers behind a common
``Monitor`` interface driven by the config's ``tensorboard`` /
``wandb`` / ``csv_monitor`` blocks (ref: deepspeed/monitor/config.py,
monitor.py).  Same shape here: each backend implements
``write_events([(tag, value, step), ...])``; :class:`MonitorMaster`
fans out to every enabled backend, on host rank 0 only.
"""

from __future__ import annotations

import csv
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

Event = Tuple[str, float, int]  # (tag, scalar, global_step)


class Monitor:
    enabled = True

    def write_events(self, events: Sequence[Event]) -> None:  # pragma: no cover
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CsvMonitor(Monitor):
    """ref: deepspeed/monitor/csv_monitor.py — one csv file per tag."""

    def __init__(self, output_path: str = "ds_logs", job_name: str = "run"):
        self.dir = os.path.join(output_path, job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files: Dict[str, Any] = {}

    def _writer(self, tag: str):
        if tag not in self._files:
            safe = tag.replace("/", "_")
            f = open(os.path.join(self.dir, f"{safe}.csv"), "a", newline="")
            w = csv.writer(f)
            if f.tell() == 0:
                w.writerow(["step", tag])
            self._files[tag] = (f, w)
        return self._files[tag]

    def write_events(self, events: Sequence[Event]) -> None:
        for tag, value, step in events:
            f, w = self._writer(tag)
            w.writerow([step, float(value)])

    def flush(self) -> None:
        for f, _ in self._files.values():
            f.flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            f.close()
        self._files.clear()


class TensorBoardMonitor(Monitor):
    """ref: deepspeed/monitor/tensorboard.py.  Gated on tensorboardX /

    torch.utils.tensorboard being importable; otherwise disabled."""

    def __init__(self, output_path: str = "ds_logs", job_name: str = "run"):
        self.enabled = False
        self._sw = None
        try:  # torch (cpu) is baked in; its tensorboard needs tensorboard pkg
            from torch.utils.tensorboard import SummaryWriter  # type: ignore

            self._sw = SummaryWriter(log_dir=os.path.join(output_path, job_name))
            self.enabled = True
        except Exception:
            pass

    def write_events(self, events: Sequence[Event]) -> None:
        if self._sw is None:
            return
        for tag, value, step in events:
            self._sw.add_scalar(tag, float(value), step)

    def flush(self) -> None:
        if self._sw is not None:
            self._sw.flush()

    def close(self) -> None:
        if self._sw is not None:
            self._sw.close()


class WandbMonitor(Monitor):
    """ref: deepspeed/monitor/wandb.py.  Gated on wandb being importable."""

    def __init__(self, project: Optional[str] = None, group: Optional[str] = None,
                 team: Optional[str] = None):
        self.enabled = False
        self._wandb = None
        try:
            import wandb  # type: ignore

            wandb.init(project=project, group=group, entity=team)
            self._wandb = wandb
            self.enabled = True
        except Exception:
            pass

    def write_events(self, events: Sequence[Event]) -> None:
        if self._wandb is None:
            return
        for tag, value, step in events:
            self._wandb.log({tag: float(value)}, step=step)

    def close(self) -> None:
        if self._wandb is not None:
            self._wandb.finish()


class CometMonitor(Monitor):
    """ref: deepspeed/monitor/comet.py.  Gated on comet_ml being
    importable (it is not baked into this image, so the backend is a
    no-op unless the user's environment provides it — same
    import-gating as wandb/tensorboard)."""

    def __init__(self, project: Optional[str] = None,
                 workspace: Optional[str] = None,
                 api_key: Optional[str] = None,
                 experiment_name: Optional[str] = None,
                 experiment_key: Optional[str] = None,
                 online: Optional[bool] = None,
                 mode: Optional[str] = None):
        self.enabled = False
        self._exp = None
        try:
            import comet_ml  # type: ignore

            exp = comet_ml.start(
                api_key=api_key, project=project, workspace=workspace,
                experiment_key=experiment_key,
                online=online,
                mode=mode or "get_or_create")
            try:
                if experiment_name:
                    exp.set_name(experiment_name)
            except Exception:
                # a started experiment must not leak its upload threads
                # when the backend ends up disabled
                exp.end()
                raise
            self._exp = exp
            self.enabled = True
        except Exception:
            pass

    def write_events(self, events: Sequence[Event]) -> None:
        if self._exp is None:
            return
        for tag, value, step in events:
            self._exp.log_metric(tag, float(value), step=step)

    def flush(self) -> None:
        if self._exp is not None:
            self._exp.flush()

    def close(self) -> None:
        if self._exp is not None:
            self._exp.end()


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends, rank-0 only (ref: monitor/monitor.py

    ``MonitorMaster``).  Config keys match the reference:
    ``{"tensorboard": {"enabled": ..., "output_path": ..., "job_name": ...},
       "wandb": {...}, "csv_monitor": {...}}``.
    """

    def __init__(self, monitor_config: Optional[Dict[str, Any]] = None):
        import jax

        self.rank0 = jax.process_index() == 0
        self.backends: List[Monitor] = []
        cfg = monitor_config or {}
        if not self.rank0:
            return
        tb = cfg.get("tensorboard", {})
        if tb.get("enabled"):
            m = TensorBoardMonitor(tb.get("output_path", "ds_logs"),
                                   tb.get("job_name", "run"))
            if m.enabled:
                self.backends.append(m)
        wb = cfg.get("wandb", {})
        if wb.get("enabled"):
            m = WandbMonitor(wb.get("project"), wb.get("group"), wb.get("team"))
            if m.enabled:
                self.backends.append(m)
        cm = cfg.get("csv_monitor", {})
        if cm.get("enabled"):
            self.backends.append(CsvMonitor(cm.get("output_path", "ds_logs"),
                                            cm.get("job_name", "run")))
        co = cfg.get("comet", {})
        if co.get("enabled"):
            m = CometMonitor(
                project=co.get("project"), workspace=co.get("workspace"),
                api_key=co.get("api_key"),
                experiment_name=co.get("experiment_name"),
                experiment_key=co.get("experiment_key"),
                online=co.get("online"), mode=co.get("mode"))
            if m.enabled:
                self.backends.append(m)

    @property
    def enabled(self) -> bool:  # type: ignore[override]
        return bool(self.backends)

    def write_events(self, events: Sequence[Event]) -> None:
        for b in self.backends:
            b.write_events(events)

    def write_scalars(self, scalars: Dict[str, float], step: int) -> None:
        self.write_events([(k, v, step) for k, v in scalars.items()])

    def flush(self) -> None:
        for b in self.backends:
            b.flush()

    def close(self) -> None:
        for b in self.backends:
            b.close()
