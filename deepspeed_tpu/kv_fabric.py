"""Cross-replica KV fabric: a shared, content-addressed exchange of
serialized KV pages (ROADMAP open item 2's second half — the tier
becomes a fabric, not just a spill).

ZeRO-Infinity (arXiv:2104.07857) proved the host/NVMe transport for
serialized, checksummed tensor pages, and ZeRO-Offload
(arXiv:2101.06840) the host-staging discipline; PRs 7/9 applied both to
KV pages inside ONE engine (demote → spill → checksum-verified
promotion → re-prefill fallback).  This module lifts the exact same
payloads one level up: fleet replicas PUBLISH page chains into the
fabric and FETCH chains another replica computed, so

- an **affinity miss** where another replica's digest covers the
  prompt becomes a migration (the router asks the owner to export the
  matching chain, the target admits it into its own spill pool and
  re-admits through the existing ``begin_promotion``/``TierPageReader``
  path) instead of a full re-prefill, and
- a **disaggregated fleet** (``fleet.roles``) hands prompts from
  prefill-specialized replicas to decode-specialized ones as migrated
  admissions — the architecture serving systems converge on at scale.

The entries are the spill tier's own :class:`~deepspeed_tpu.inference.
prefix_cache.TierEntry` records: serialized buffers with the per-buffer
crc32 recorded at encode time, int8-quantized cold pages riding as-is.
Nothing downstream trusts the transport — the ADMITTING replica's
promotion decodes against the original checksums, so corruption
anywhere between export and scatter falls back to re-prefill exactly
like a failed tier promotion (PR 9's ``_promotion_fallback``).

Chaos surface: the ``faults`` plan's ``fabric`` rules fire at
:meth:`KVFabric.publish` (key ``export:<hex>``; error = failed export),
:meth:`KVFabric.fetch` (key ``fetch:<hex>``; latency pushes a migration
toward its timeout, error fails it) and after the publish checksum
passthrough (key ``corrupt:<hex>``; error flips a payload byte in the
fabric's copy — never the owner's — so only importers see it).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu import faults as _faults
from deepspeed_tpu.config import FabricConfig
from deepspeed_tpu.inference.prefix_cache import TierEntry, key_hex
from deepspeed_tpu.utils.logging import logger


class FabricExportError(IOError):
    """An export into the fabric failed (injected or real): the
    migration falls back to re-prefill — correctness preserved, the
    DMA saving lost."""


class KVFabric:
    """Content-addressed KV-page exchange shared by fleet replicas.

    One fabric per fleet (built by :func:`~deepspeed_tpu.fleet.
    fleet_router`, or directly for tests); replicas reach it through
    :meth:`~deepspeed_tpu.inference.serving.ServingEngine.export_pages`
    / ``admit_fabric``.  Entries are host-resident serialized payloads
    capped at ``capacity_bytes`` with oldest-first eviction — the
    fabric is a TRANSIT BUFFER, not a third storage tier: an evicted
    chain just means the next migration re-exports from its owner (or
    the target re-prefills).

    Single-router threading model: the fleet's submit/step loop is the
    only caller, so no internal locking — same contract as the router
    itself.
    """

    def __init__(self, fabric=None, registry=None):
        self.cfg = FabricConfig.coerce(fabric)
        self.entries: "collections.OrderedDict[bytes, TierEntry]" = \
            collections.OrderedDict()
        self.bytes = 0
        # host-side lifetime accounting (works with telemetry disabled;
        # the soak reconciles these against the registry family)
        self.exports = 0            # pages published (dedups excluded)
        self.fetches = 0            # pages fetched
        self.bytes_in = 0           # serialized bytes exported in
        self.bytes_out = 0          # serialized bytes fetched out
        self.export_failures = 0
        self.fetch_failures = 0
        self.evicted = 0
        self.corrupted = 0          # injected in-fabric corruptions
        if registry is None or not getattr(registry, "enabled", False):
            from deepspeed_tpu.telemetry import NULL_METRIC

            self._c_exports = self._c_fetches = NULL_METRIC
            self._c_bytes_in = self._c_bytes_out = NULL_METRIC
            self._c_exp_fail = self._c_fetch_fail = NULL_METRIC
            self._c_evicted = NULL_METRIC
            self._g_entries = self._g_bytes = NULL_METRIC
            self.h_migrate = NULL_METRIC
        else:
            r = registry
            self._c_exports = r.counter(
                "kv_fabric_exports",
                "pages published into the fabric (dedup hits excluded)")
            self._c_fetches = r.counter(
                "kv_fabric_fetches",
                "pages fetched out of the fabric for a migrated "
                "admission")
            self._c_bytes_in = r.counter(
                "kv_fabric_bytes_in",
                "serialized payload bytes exported into the fabric")
            self._c_bytes_out = r.counter(
                "kv_fabric_bytes_out",
                "serialized payload bytes fetched out of the fabric")
            self._c_exp_fail = r.counter(
                "kv_fabric_export_failures",
                "page exports that failed (the migration falls back "
                "to re-prefill for the uncovered span)")
            self._c_fetch_fail = r.counter(
                "kv_fabric_fetch_failures",
                "page fetches that failed (the admitting replica "
                "re-prefills the uncovered span)")
            self._c_evicted = r.counter(
                "kv_fabric_evicted_entries",
                "entries evicted oldest-first under capacity_bytes")
            self._g_entries = r.gauge(
                "kv_fabric_entries", "pages resident in the fabric")
            self._g_bytes = r.gauge(
                "kv_fabric_bytes", "serialized bytes resident")
            # observed by the router around one whole migration
            # (export leg + fetch/admit leg)
            self.h_migrate = r.histogram(
                "kv_fabric_migrate_seconds",
                "one cross-replica migration, export-start -> "
                "admitted (timeouts counted as fallbacks instead)")

    # ------------------------------------------------------------ index
    def has(self, key: bytes) -> bool:
        return key in self.entries

    def covers(self, keys: Sequence[bytes]) -> int:
        """Longest CONTIGUOUS prefix of ``keys`` resident in the
        fabric — chain semantics, same as the allocator's tier walk."""
        n = 0
        for k in keys:
            if k not in self.entries:
                break
            n += 1
        return n

    def _refresh_gauges(self) -> None:
        self._g_entries.set(len(self.entries))
        self._g_bytes.set(self.bytes)

    # ---------------------------------------------------------- publish
    def publish(self, key: bytes, entry: TierEntry) -> bool:
        """Export one serialized page into the fabric.  The payload
        arrays are COPIED — the fabric's lifetime (and its injected
        corruptions) must never alias the owner's live spill entries.
        Dedup: a key already resident just refreshes its age.  Raises
        :class:`FabricExportError` on an injected/real export failure
        (the caller counts it and the migration degrades)."""
        hexk = key_hex(key)
        delay, err = _faults.poll("fabric", f"export:{hexk}")
        if delay:
            time.sleep(delay)
        if err is not None:
            self.export_failures += 1
            self._c_exp_fail.inc()
            raise FabricExportError(
                f"injected fabric export failure ({hexk[:12]})")
        if key in self.entries:
            self.entries.move_to_end(key)
            return False
        data = tuple(np.array(b, copy=True) for b in entry.data)
        e = dataclasses.replace(entry, location="host", data=data)
        _delay, corrupt = _faults.poll("fabric", f"corrupt:{hexk}")
        if corrupt is not None:
            # AFTER the checksum passthrough: the importer's decode
            # must catch exactly this and re-prefill
            _faults.corrupt_array(e.data[0])
            self.corrupted += 1
        if e.nbytes > self.cfg.capacity_bytes:
            # BEFORE the eviction loop: an unpublishable oversized
            # entry must not flush every other replica's in-flight
            # chains first
            logger.warning(
                "kv_fabric: entry %s (%d B) exceeds capacity_bytes %d "
                "— not published", hexk[:12], e.nbytes,
                self.cfg.capacity_bytes)
            return False
        while self.bytes + e.nbytes > self.cfg.capacity_bytes \
                and self.entries:
            old_key, old = self.entries.popitem(last=False)
            self.bytes -= old.nbytes
            self.evicted += 1
            self._c_evicted.inc()
        self.entries[key] = e
        self.bytes += e.nbytes
        self.exports += 1
        self.bytes_in += e.nbytes
        self._c_exports.inc()
        self._c_bytes_in.inc(e.nbytes)
        self._refresh_gauges()
        return True

    # ------------------------------------------------------------ fetch
    def fetch(self, key: bytes) -> TierEntry:
        """One page out of the fabric for a migrated admission.
        Latency rules sleep here (pushing the migration toward its
        ``migrate_timeout_s`` — the router abandons the remainder);
        error rules raise (the caller counts a fetch failure and the
        uncovered span re-prefills).  KeyError when the entry evicted
        since ``covers()``."""
        hexk = key_hex(key)
        delay, err = _faults.poll("fabric", f"fetch:{hexk}")
        if delay:
            time.sleep(delay)
        if err is not None:
            self.fetch_failures += 1
            self._c_fetch_fail.inc()
            raise IOError(
                f"injected fabric fetch failure ({hexk[:12]})")
        e = self.entries[key]
        self.entries.move_to_end(key)
        self.fetches += 1
        self.bytes_out += e.nbytes
        self._c_fetches.inc()
        self._c_bytes_out.inc(e.nbytes)
        return e

    # ------------------------------------------------------ introspection
    def occupancy(self) -> Dict[str, Any]:
        return {
            "entries": len(self.entries),
            "bytes": int(self.bytes),
            "capacity_bytes": int(self.cfg.capacity_bytes),
            "exports": int(self.exports),
            "fetches": int(self.fetches),
            "bytes_moved": int(self.bytes_in + self.bytes_out),
            "export_failures": int(self.export_failures),
            "fetch_failures": int(self.fetch_failures),
            "evicted": int(self.evicted),
            "corrupted": int(self.corrupted),
        }
