"""Time-series metric history: multi-resolution ring buffers over the
telemetry registry — the retained-trajectory half of the black-box
flight recorder (PR 15).

Every observability surface before this one answers "what is happening
NOW": the registry (PR 2) holds cumulative counters and last-value
gauges, ``/statusz`` (PR 6) is a point-in-time snapshot, and the flight
recorder (PR 4) keeps events but not metric values.  The elastic,
disaggregated fleet fails as *trajectories* — a burn trip is preceded
by 30 s of rising queue depth, a kv-tier breaker trip by a climbing
checksum-failure rate — and ZeRO-Infinity-style tiered streaming
(arXiv:2104.07857) makes stall/bandwidth pathologies develop over
seconds, invisible to any point-in-time gauge.

:class:`MetricHistory` samples the registry on the
:class:`~deepspeed_tpu.telemetry.TelemetryExporter` tick (via
``register_tick_hook`` — never the decode hot path) into fixed-memory
rings, one per configured resolution (default 1 s × 120 and
10 s × 360):

- **counters → rates**: per-tick delta / elapsed; a counter RESET
  (value below the last observation — a swapped registry, a restarted
  subsystem) contributes the post-reset value rather than a huge
  negative spike;
- **gauges → last value**;
- **histograms → p50/p95** of the samples landed since the previous
  tick (``<name>:p50`` / ``<name>:p95`` series), estimated from the
  Prometheus bucket-count deltas; a tick with no new observations
  records a gap, not a zero.

Coarser rings aggregate the fine samples per bucket — mean for
rate/gauge series, max for percentile series (the conservative reading
for an alarm surface).  :meth:`MetricHistory.annotate` drops labeled
marks (autoscaler scale/rollout events) onto the same timeline, and
:func:`history_rollup` merges per-replica snapshots into one fleet
view the way :func:`~deepspeed_tpu.slo.fleet_rollup` does for SLO
state: rate and gauge series SUM per aligned bucket, percentile series
take the MAX across replicas.

Surfaces: ``/historyz`` on the telemetry HTTP server, ``dstpu_top``
sparklines, and the pre-trip windows captured into incident bundles by
:mod:`deepspeed_tpu.incidents`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.config import HistoryConfig

# series-kind tags: how samples aggregate into coarser buckets and how
# the fleet rollup merges them across replicas
RATE = "rate"          # counter delta/dt   (mean per bucket, sum fleet)
GAUGE = "gauge"        # last value         (mean per bucket, sum fleet)
PCT = "pct"            # histogram p50/p95  (max per bucket, max fleet)


class _Ring:
    """One fixed-capacity resolution ring for one series.

    Slot ``i`` holds the aggregate of every sample whose bucket index
    (``int(t / period)``) maps to ``i = bucket % capacity``; stale
    slots (lapped by the ring) are detected by their stored bucket
    index, so an idle series never replays ancient values."""

    __slots__ = ("period", "capacity", "buckets", "values",
                 "_acc_bucket", "_acc_sum", "_acc_n", "_acc_max")

    def __init__(self, period: float, capacity: int):
        self.period = float(period)
        self.capacity = int(capacity)
        # None = never-written slot: an int sentinel would collide
        # with a genuine bucket index when a window reaches past t=0
        self.buckets: List[Optional[int]] = [None] * self.capacity
        self.values: List[float] = [0.0] * self.capacity
        self._acc_bucket: Optional[int] = None
        self._acc_sum = 0.0
        self._acc_n = 0
        self._acc_max = 0.0

    def record(self, now: float, value: float, kind: str) -> None:
        b = int(now / self.period)
        if b != self._acc_bucket:
            self._flush()
            self._acc_bucket = b
        self._acc_sum += value
        self._acc_n += 1
        if self._acc_n == 1 or value > self._acc_max:
            self._acc_max = value
        # publish the in-progress aggregate immediately: a reader never
        # waits a full coarse period to see the current bucket
        i = b % self.capacity
        self.buckets[i] = b
        self.values[i] = (self._acc_max if kind == PCT
                          else self._acc_sum / self._acc_n)

    def _flush(self) -> None:
        self._acc_sum = 0.0
        self._acc_n = 0
        self._acc_max = 0.0

    def window(self, now: float, seconds: float
               ) -> List[Tuple[float, float]]:
        """(bucket start time, value) pairs inside the trailing
        window, oldest first."""
        lo = int((now - seconds) / self.period)
        hi = int(now / self.period)
        out: List[Tuple[float, float]] = []
        for b in range(max(lo, hi - self.capacity + 1), hi + 1):
            i = b % self.capacity
            if self.buckets[i] == b:
                out.append((b * self.period, self.values[i]))
        return out

    def snapshot(self, now: float) -> Dict[str, Any]:
        return {
            "period_s": self.period,
            "capacity": self.capacity,
            "points": [[round(t, 3), _round(v)] for t, v in
                       self.window(now, self.period * self.capacity)],
        }


def _round(v: float) -> float:
    return round(float(v), 6)


def _percentile_from_buckets(deltas: List[Tuple[float, int]],
                             q: float) -> Optional[float]:
    """Estimate the q-quantile from cumulative ``(le, count)`` DELTAS
    (already de-cumulated to per-bucket counts by the caller).  Returns
    the bucket upper bound holding the quantile — the standard
    Prometheus histogram_quantile reading, biased at most one bucket
    high."""
    total = sum(c for _, c in deltas)
    if total <= 0:
        return None
    target = q * total
    acc = 0
    finite = [b for b, _ in deltas if b != float("inf")]
    top = finite[-1] if finite else None
    for le, c in deltas:
        acc += c
        if acc >= target:
            # a quantile landing in the +Inf overflow bucket clamps to
            # the highest FINITE bound: an inf sample would poison the
            # EWMA detector baseline and break strict-JSON consumers
            # of /historyz and the incident bundles
            return le if le != float("inf") else top
    return top


class MetricHistory:
    """Fixed-memory multi-resolution history over a
    :class:`~deepspeed_tpu.telemetry.MetricsRegistry`.

    ``maybe_sample`` is the tick entry point (rate-limited internally
    to ``sample_interval_s``, so exporter hooks and manual drivers can
    both call it freely); ``snapshot`` renders the ``/historyz``
    document; ``window``/``latest`` serve the incident engine's
    pre-trip capture and EWMA detectors; ``annotate`` drops labeled
    marks (scale/rollout events) onto the timeline.  All public
    methods are thread-safe — the HTTP thread snapshots while the
    engine thread samples."""

    def __init__(self, cfg: HistoryConfig, registry, *,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.enabled = bool(cfg.enabled) and registry.enabled
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._last_t: Optional[float] = None
        # per-series ring sets + per-metric last raw observations
        self._series: "Dict[str, Dict[str, Any]]" = {}   # name -> rec
        self._last_counters: Dict[str, float] = {}
        self._last_hist: Dict[str, Dict[float, int]] = {}
        self.annotations: List[Dict[str, Any]] = []
        self._filter = (set(cfg.metrics) if cfg.metrics is not None
                        else None)
        r = registry
        self._c_samples = r.counter(
            "history_samples_total",
            "history sampling ticks taken (exporter-tick cadence — "
            "never the decode hot path)")
        self._c_annotations = r.counter(
            "history_annotations_total",
            "labeled marks (scale/rollout events) dropped onto the "
            "history timeline")
        self._g_series = r.gauge(
            "history_series_tracked",
            "distinct series with live rings (bounded by "
            "history.max_series)")

    # ------------------------------------------------------------ series
    def _rec(self, name: str, kind: str) -> Optional[Dict[str, Any]]:
        rec = self._series.get(name)
        if rec is None:
            if len(self._series) >= self.cfg.max_series:
                return None              # bounded memory: drop, never grow
            # "t" = the series' last RECORD time (not bucket time):
            # the incident detectors gate on it to judge once per new
            # sample even when several samples land in one fine bucket
            rec = {"kind": kind, "t": None,
                   "rings": [_Ring(p, n) for p, n in self.cfg.rings]}
            self._series[name] = rec
            self._g_series.set(len(self._series))
        return rec

    def _record(self, name: str, kind: str, now: float,
                value: float) -> None:
        rec = self._rec(name, kind)
        if rec is None:
            return
        rec["t"] = now
        for ring in rec["rings"]:
            ring.record(now, value, kind)

    def _tracked(self, name: str) -> bool:
        return self._filter is None or name in self._filter

    # ------------------------------------------------------------ sample
    # dstpu: hot-path
    def maybe_sample(self, now: Optional[float] = None) -> bool:
        """One history tick if ``sample_interval_s`` elapsed; safe to
        call every scheduler step (one clock compare until due)."""
        if not self.enabled:
            return False
        now = self._clock() if now is None else now
        if self._last_t is not None and \
                now - self._last_t < self.cfg.sample_interval_s:
            return False
        self.sample(now)
        return True

    def sample(self, now: Optional[float] = None) -> None:
        """Unconditional sampling pass: counters as rates, gauges as
        last value, histograms as p50/p95 of the tick's delta."""
        if not self.enabled:
            return
        now = self._clock() if now is None else now
        snap = self.registry.snapshot()
        with self._lock:
            dt = (now - self._last_t) if self._last_t is not None \
                else None
            self._last_t = now
            for name, v in snap.get("counters", {}).items():
                if not self._tracked(name):
                    continue
                last = self._last_counters.get(name)
                self._last_counters[name] = v
                if last is None or dt is None or dt <= 0:
                    continue
                # reset tolerance: a counter that went BACKWARDS was
                # restarted — the post-reset value is the true delta
                delta = v - last if v >= last else v
                self._record(f"{name}:rate", RATE, now, delta / dt)
            for name, v in snap.get("gauges", {}).items():
                if not self._tracked(name):
                    continue
                self._record(name, GAUGE, now, float(v))
            for name, h in snap.get("histograms", {}).items():
                if not self._tracked(name):
                    continue
                cum = {float(le) if le != "+Inf" else float("inf"): c
                       for le, c in h.get("buckets", {}).items()}
                last = self._last_hist.get(name, {})
                self._last_hist[name] = cum
                if not last and dt is None:
                    # first observation: no delta window yet
                    continue
                # de-cumulate, then delta against the previous tick
                # (cumulative "le" buckets subtract cleanly)
                deltas = []
                prev_new = prev_old = 0
                for le in sorted(cum):
                    d_new = cum[le] - prev_new
                    d_old = last.get(le, 0) - prev_old
                    prev_new, prev_old = cum[le], last.get(le, 0)
                    deltas.append((le, max(d_new - d_old, 0)))
                p50 = _percentile_from_buckets(deltas, 0.50)
                p95 = _percentile_from_buckets(deltas, 0.95)
                if p50 is not None:
                    self._record(f"{name}:p50", PCT, now, p50)
                if p95 is not None:
                    self._record(f"{name}:p95", PCT, now, p95)
        self._c_samples.inc()

    # -------------------------------------------------------- annotate
    def annotate(self, label: str,
                 attrs: Optional[Dict[str, Any]] = None,
                 now: Optional[float] = None) -> None:
        """Drop a labeled mark (scale event, rollout step, operator
        action) onto the history timeline; bounded ring."""
        if not self.enabled:
            return
        now = self._clock() if now is None else now
        with self._lock:
            self.annotations.append(
                {"t": round(now, 3), "label": str(label),
                 **({"attrs": dict(attrs)} if attrs else {})})
            if len(self.annotations) > self.cfg.max_annotations:
                del self.annotations[:len(self.annotations)
                                     - self.cfg.max_annotations]
        self._c_annotations.inc()

    # ------------------------------------------------------------- read
    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def latest(self, name: str) -> Optional[float]:
        """Most recent fine-ring value of one series (detector food)."""
        pt = self.latest_point(name)
        return pt[1] if pt is not None else None

    def latest_point(self, name: str) -> Optional[Tuple[float, float]]:
        """Most recent ``(sample_time, value)`` of one series — the
        SAMPLE time (not the bucket time: several samples can land in
        one fine bucket) lets the incident detectors advance once per
        NEW sample instead of once per evaluation tick, judging the
        bucket's current aggregate each time."""
        with self._lock:
            rec = self._series.get(name)
            if rec is None or rec["t"] is None:
                return None
            ring = rec["rings"][0]
            pts = ring.window(rec["t"], ring.period)
            return (rec["t"], pts[-1][1]) if pts else None

    def window(self, name: str, seconds: float,
               now: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Trailing ``seconds`` of one series from the finest ring
        whose span covers the window (falling back to the coarsest)."""
        now = self._clock() if now is None else now
        with self._lock:
            rec = self._series.get(name)
            if rec is None:
                return []
            for ring in rec["rings"]:
                if ring.period * ring.capacity >= seconds:
                    return ring.window(now, seconds)
            return rec["rings"][-1].window(now, seconds)

    def snapshot(self, now: Optional[float] = None,
                 series: Optional[List[str]] = None,
                 window_s: Optional[float] = None) -> Dict[str, Any]:
        """The ``/historyz`` document: every ring of every (selected)
        series plus annotations.  ``window_s`` trims each ring's points
        to a trailing window (the incident bundle's pre-trip capture)."""
        if not self.enabled:
            return {"enabled": False}
        now = self._clock() if now is None else now
        out_series: Dict[str, Any] = {}
        with self._lock:
            names = series if series is not None else sorted(self._series)
            for name in names:
                rec = self._series.get(name)
                if rec is None:
                    continue
                rings = []
                for ring in rec["rings"]:
                    snap = ring.snapshot(now)
                    if window_s is not None:
                        snap["points"] = [
                            [t, v] for t, v in snap["points"]
                            if t >= now - window_s]
                    rings.append(snap)
                out_series[name] = {"kind": rec["kind"], "rings": rings}
            anns = list(self.annotations)
        if window_s is not None:
            anns = [a for a in anns if a["t"] >= now - window_s]
        return {
            "enabled": True,
            "t_monotonic": round(now, 3),
            "sample_interval_s": self.cfg.sample_interval_s,
            "rings": [{"period_s": p, "capacity": n}
                      for p, n in self.cfg.rings],
            "samples": int(self._c_samples.value),
            "series": out_series,
            "annotations": anns,
        }


class _NullHistory:
    """Shared no-op stand-in when the block is off: every hook is one
    early return, mirroring telemetry's null metrics."""

    enabled = False

    def maybe_sample(self, now=None):
        return False

    def sample(self, now=None):
        pass

    def annotate(self, label, attrs=None, now=None):
        pass

    def series_names(self):
        return []

    def latest(self, name):
        return None

    def window(self, name, seconds, now=None):
        return []

    def snapshot(self, now=None, series=None, window_s=None):
        return {"enabled": False}


NULL_HISTORY = _NullHistory()


# ------------------------------------------------------------- rollup
def history_rollup(snapshots) -> Dict[str, Any]:
    """Aggregate per-replica :meth:`MetricHistory.snapshot` documents
    into one fleet view, the way :func:`~deepspeed_tpu.slo.
    fleet_rollup` merges SLO snapshots: per series and ring, values SUM
    per aligned bucket for rate/gauge series (fleet queue depth is the
    sum of replica queue depths) and take the MAX for percentile
    series (the alert question is "how bad is the worst replica").
    Disabled snapshots pass through; annotations concatenate in time
    order.

    Snapshots need not come from in-process objects: the scrape plane
    (:mod:`deepspeed_tpu.obs_wire`) feeds this the ``history`` block
    of a remote replica's ``/historyz`` document — same shape over the
    wire, and a never-scraped remote's ``None`` filters out here like
    a disabled ring set."""
    snaps = [s for s in snapshots if s and s.get("enabled")]
    if not snaps:
        return {"enabled": False}
    series: Dict[str, Any] = {}
    for s in snaps:
        for name, rec in s.get("series", {}).items():
            agg = series.get(name)
            if agg is None:
                agg = series[name] = {
                    "kind": rec["kind"],
                    "rings": [{"period_s": r["period_s"],
                               "capacity": r["capacity"],
                               "points": {}}
                              for r in rec["rings"]],
                }
            for ri, r in enumerate(rec["rings"]):
                if ri >= len(agg["rings"]):
                    continue
                pts = agg["rings"][ri]["points"]
                for t, v in r["points"]:
                    if rec["kind"] == PCT:
                        pts[t] = max(pts.get(t, v), v)
                    else:
                        pts[t] = pts.get(t, 0.0) + v
    for rec in series.values():
        for r in rec["rings"]:
            r["points"] = [[t, _round(v)]
                           for t, v in sorted(r["points"].items())]
    anns = sorted((a for s in snaps
                   for a in s.get("annotations", [])),
                  key=lambda a: a.get("t", 0.0))
    return {
        "enabled": True,
        "replicas": len(snaps),
        "rings": snaps[0].get("rings", []),
        "series": series,
        "annotations": anns,
    }
