"""Observability over the wire (no reference analogue; the scrape
plane under ROADMAP open item 1's out-of-process fleet).

Every observability surface in this repo — ``fleet_rollup``,
``history_rollup``, :meth:`TelemetryExporter.add_source`, the shared
:class:`~deepspeed_tpu.request_trace.FlightRecorder`, the
:class:`~deepspeed_tpu.incidents.IncidentManager` — historically held a
Python reference to the replica it observed.  A process split severs
every one of those references at once, so this module rebuilds the
spine over the ``/statusz``-shaped HTTP surface each engine already
exposes:

- **Versioned wire schema** — :func:`wire_stamp` adds
  ``wire_schema`` + wall/monotonic timestamps to every route document,
  :func:`check_wire_schema` rejects a major mismatch loudly
  (:class:`WireSchemaError`), and :func:`tracez_provider` serves the
  new ``/tracez?since=`` route: an incremental flight-recorder drain
  built on :meth:`FlightRecorder.events_since`, so a remote poller
  re-reads nothing it has already fetched.
- **:class:`RemoteReplica`** — a per-replica scrape client with
  timeout/retry/backoff (:func:`~deepspeed_tpu.faults.retry_with_backoff`
  around every fetch, a ``scrape`` fault-injection point keyed by
  replica id), a FRESH→STALE→LOST staleness state machine with
  hysteresis (``fresh_after`` consecutive good scrapes to recover),
  and last-known-snapshot retention so a SIGKILLed child still renders
  in the fleet statusz — flagged LOST, never silently absent.
- **Cross-process trace correlation** — :meth:`RemoteReplica.
  estimate_clock_offset` runs an RTT-based min-RTT probe against the
  remote's monotonic clock (offset error bounded by min-RTT/2, the
  bound recorded into the merged trace meta), and
  :func:`merge_trace_segments` applies per-segment offsets when
  folding ``/tracez`` drains from many processes into one Chrome
  trace with request spans stitched across replica tags.
- **ReplicaSource contract** — the duck-typed surface
  (``statusz_row`` / ``slo_snapshot`` / ``history_snapshot`` /
  ``poll_health``) implemented by both the in-process
  :class:`~deepspeed_tpu.fleet.Replica` and :class:`RemoteReplica`,
  so the router's rollups aggregate either transparently.

Nothing here imports JAX: the wire plane is pure stdlib
(``urllib`` + ``json``) and must keep working when the model side of
a replica is wedged.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu import faults
from deepspeed_tpu.config import ObsWireConfig
from deepspeed_tpu.request_trace import (Event, event_to_dict,
                                         events_from_dicts,
                                         events_to_chrome)

# Major bumps on breaking shape changes (field removed/renamed, route
# semantics changed); minor bumps on additive fields.  A scraper built
# against major N must refuse documents from major M != N — silently
# mis-parsing a foreign schema is how fleets go dark politely.
OBS_WIRE_SCHEMA = (1, 0)
OBS_WIRE_SCHEMA_STR = ".".join(str(x) for x in OBS_WIRE_SCHEMA)

# Staleness states (strings on purpose: they travel through JSON).
FRESH = "FRESH"
STALE = "STALE"
LOST = "LOST"


class WireSchemaError(RuntimeError):
    """A scraped document's ``wire_schema`` major does not match this
    process (or the stamp is missing entirely).  Deliberately NOT an
    OSError: retry/backoff must not paper over a contract break."""


# ---------------------------------------------------------------- schema
def wire_stamp() -> Dict[str, Any]:
    """The fields every wire-served route document carries: schema
    version plus paired wall/monotonic timestamps (wall for humans and
    cross-host joins, monotonic for offset estimation and staleness
    arithmetic — never mix the two)."""
    return {"wire_schema": OBS_WIRE_SCHEMA_STR,
            "t_wall": time.time(),
            "t_mono_ns": time.monotonic_ns()}


def check_wire_schema(doc: Any, route: str = "?") -> Tuple[int, int]:
    """Validate a scraped document's stamp; returns ``(major, minor)``.

    Raises :class:`WireSchemaError` on a missing stamp or a major
    mismatch.  A minor ahead of ours is fine (additive fields); a
    minor behind is fine too (we tolerate absent additions).
    """
    if not isinstance(doc, dict) or "wire_schema" not in doc:
        raise WireSchemaError(
            f"{route}: document carries no wire_schema stamp — remote "
            "predates the wire plane or is not a deepspeed_tpu replica")
    raw = str(doc["wire_schema"])
    try:
        major, minor = (int(x) for x in raw.split(".", 1))
    except ValueError:
        raise WireSchemaError(
            f"{route}: malformed wire_schema {raw!r}") from None
    if major != OBS_WIRE_SCHEMA[0]:
        raise WireSchemaError(
            f"{route}: wire_schema major mismatch — remote speaks "
            f"{raw}, this process speaks {OBS_WIRE_SCHEMA_STR}; "
            "refusing to mis-parse a foreign schema")
    return major, minor


def tracez_provider(recorder, replica: Optional[str] = None):
    """Build the ``tracez`` introspection provider for an exporter.

    The returned callable takes the raw ``?since=`` query value and
    serves one incremental segment: events with sequence index >=
    ``since`` (via :meth:`FlightRecorder.events_since` — the lock is
    held only for the returned slots) plus the new cursor, so a
    steady-state poll ships only the delta.
    """
    def provider(since: Optional[str]) -> Dict[str, Any]:
        try:
            cursor = int(since) if since else 0
        except ValueError:
            cursor = 0
        total, events = recorder.events_since(max(cursor, 0))
        doc = wire_stamp()
        doc.update({
            "since": max(cursor, 0),
            "total": total,                      # the next ?since=
            "dropped": recorder.dropped,
            "events": [event_to_dict(e) for e in events],
        })
        if replica is not None:
            doc["replica"] = replica
        return doc
    return provider


# ---------------------------------------------------------------- client
def http_get_json(url: str, timeout_s: float) -> Dict[str, Any]:
    """One JSON GET with a hard timeout.  Raises OSError-family on
    transport trouble (what retry_with_backoff retries) and ValueError
    on non-JSON bodies."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        body = resp.read()
    return json.loads(body.decode("utf-8"))


def http_get_text(url: str, timeout_s: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8")


class RemoteReplica:
    """Scrape client for one out-of-process replica.

    Implements the ReplicaSource contract from last-known snapshots:
    ``statusz_row``/``slo_snapshot``/``history_snapshot`` read the most
    recent successful scrape, so a dead child keeps rendering (flagged
    by ``scrape_state``) instead of vanishing from the rollups.

    The staleness machine is age-based with recovery hysteresis:

    - success: ``ok_streak`` grows; entering FRESH (from attach or
      after an outage) requires ``fresh_after`` consecutive good
      scrapes; once FRESH, one recent ok keeps it.
    - failure / silence: once ``now - last_ok`` passes
      ``stale_after_s`` the state reads STALE, past ``lost_after_s``
      it reads LOST.  Transitions into LOST emit a ``remote_lost``
      trace event (an incident trigger) on the tracer, once per
      outage.

    Thread-safety: ``poll``/``fetch_trace`` are expected from one
    poller thread; the read-side accessors snapshot under a lock so
    HTTP statusz threads see consistent state.
    """

    def __init__(self, url: str, rid: str,
                 cfg: Optional[ObsWireConfig] = None,
                 registry=None, tracer=None,
                 clock=time.monotonic) -> None:
        self.url = url.rstrip("/")
        self.id = rid
        self.cfg = ObsWireConfig.coerce(cfg if cfg is not None else True)
        self.tracer = tracer
        self._clock = clock
        self._lock = threading.Lock()
        self.state = STALE            # nothing known yet: not FRESH,
        self.ok_streak = 0            # not LOST either
        self.last_ok: Optional[float] = None
        self.last_error: Optional[str] = None
        self.scrapes = 0
        self.scrape_errors = 0
        self.last_latency_s = 0.0
        self.last_statusz: Optional[Dict[str, Any]] = None
        self.last_healthz: Optional[Dict[str, Any]] = None
        self.last_historyz: Optional[Dict[str, Any]] = None
        self.trace_cursor = 0
        self._last_attempt: Optional[float] = None
        self.clock_offset_ns: Optional[int] = None
        self.clock_offset_err_ns: Optional[int] = None
        self.closed = False
        if registry is not None:
            self._m_scrapes = registry.counter(
                "obswire_scrapes",
                "remote statusz scrapes attempted")
            self._m_errors = registry.counter(
                "obswire_scrape_errors",
                "remote scrapes that failed after retries")
            self._m_latency = registry.histogram(
                "obswire_scrape_latency_seconds",
                "wall time of one successful scrape cycle")
            self._m_lost = registry.counter(
                "obswire_remote_lost_transitions",
                "transitions into scrape state LOST (one per outage)")
        else:
            from deepspeed_tpu.telemetry import NULL_METRIC
            self._m_scrapes = NULL_METRIC
            self._m_errors = NULL_METRIC
            self._m_latency = NULL_METRIC
            self._m_lost = NULL_METRIC

    # -------------------------------------------------------- transport
    def _get(self, route: str, query: str = "") -> Dict[str, Any]:
        """One schema-checked JSON fetch with the scrape fault hook,
        retry/backoff, and the hard per-request timeout."""
        url = f"{self.url}{route}" + (f"?{query}" if query else "")
        cfg = self.cfg

        def fetch() -> Dict[str, Any]:
            # injected latency is capped at the request budget so a
            # fault rule can slow the loop but never wedge it
            delay, err = faults.poll("scrape", self.id)
            if delay:
                time.sleep(min(delay, cfg.timeout_s))
            if err is not None:
                raise faults.InjectedFault(
                    f"injected scrape fault (key={self.id!r})")
            doc = http_get_json(url, cfg.timeout_s)
            check_wire_schema(doc, route)
            return doc

        return faults.retry_with_backoff(
            fetch, attempts=max(cfg.retries - 1, 0),
            backoff_s=cfg.backoff_s)

    # ------------------------------------------------------------- poll
    def maybe_poll(self, now: Optional[float] = None
                   ) -> Optional[bool]:
        """Scrape if ``poll_interval_s`` has elapsed since the last
        attempt (the router calls this every step; pacing lives here so
        callers need no timers).  Between due polls the staleness state
        still advances.  Returns poll()'s result, or None if not due."""
        now = self._clock() if now is None else now
        if self._last_attempt is not None and \
                now - self._last_attempt < self.cfg.poll_interval_s:
            self.refresh_state(now)
            return None
        return self.poll(now)

    def poll(self, now: Optional[float] = None) -> bool:
        """One scrape cycle: statusz + healthz + historyz.  Returns
        True on success.  Transport failures (timeouts, refused
        connections, injected ``scrape`` faults) are absorbed into the
        staleness machine — the poll loop never raises for a dead
        remote.  :class:`WireSchemaError` DOES propagate: a schema
        break is a deployment bug, not an outage."""
        now = self._clock() if now is None else now
        self._last_attempt = now
        t0 = time.monotonic()
        self._m_scrapes.inc()
        self.scrapes += 1
        try:
            statusz = self._get("/statusz")
            healthz = self._get("/healthz")
            historyz = None
            try:
                historyz = self._get("/historyz")
            except (OSError, ValueError):
                pass        # route optional: history may be disabled
        except WireSchemaError:
            self._m_errors.inc()
            self.scrape_errors += 1
            raise
        except (OSError, ValueError) as e:
            self._m_errors.inc()
            with self._lock:
                self.scrape_errors += 1
                self.last_error = repr(e)
                self.ok_streak = 0
            self.refresh_state(now)
            return False
        self.last_latency_s = time.monotonic() - t0
        self._m_latency.observe(self.last_latency_s)
        with self._lock:
            self.last_statusz = statusz
            self.last_healthz = healthz
            if historyz is not None:
                self.last_historyz = historyz
            self.last_ok = now
            self.last_error = None
            self.ok_streak += 1
        self.refresh_state(now)
        return True

    def refresh_state(self, now: Optional[float] = None) -> str:
        """Age-based state transitions (also called WITHOUT a scrape,
        so statusz readers see staleness advance between polls)."""
        now = self._clock() if now is None else now
        with self._lock:
            age = (now - self.last_ok) if self.last_ok is not None \
                else float("inf")
            prev = self.state
            if age >= self.cfg.lost_after_s:
                nxt = LOST
            elif age >= self.cfg.stale_after_s:
                nxt = STALE
            elif self.ok_streak >= self.cfg.fresh_after or \
                    (prev == FRESH and self.ok_streak > 0):
                nxt = FRESH
            else:
                # LOST (and a just-attached STALE) exits only through
                # the ok_streak gate above — the re-entry hysteresis
                nxt = prev
            self.state = nxt
        if nxt == LOST and prev != LOST:
            self._m_lost.inc()
            if self.tracer is not None and self.tracer.enabled:
                self.tracer.event(
                    "remote_lost", req=None,
                    attrs={"replica": self.id, "url": self.url,
                           "age_s": round(age, 3)})
        return nxt

    def force_lost(self, reason: str) -> None:
        """Pin the state LOST out-of-band (the router uses this for a
        schema-incompatible remote: not an outage, but no data we can
        trust either).  Last-known snapshots are retained."""
        with self._lock:
            prev = self.state
            self.state = LOST
            self.last_error = reason
            self.ok_streak = 0
        if prev != LOST:
            self._m_lost.inc()
            if self.tracer is not None and self.tracer.enabled:
                self.tracer.event(
                    "remote_lost", req=None,
                    attrs={"replica": self.id, "url": self.url,
                           "reason": reason})

    def age_s(self, now: Optional[float] = None) -> Optional[float]:
        now = self._clock() if now is None else now
        with self._lock:
            return None if self.last_ok is None else now - self.last_ok

    def fetch_metrics(self) -> Dict[str, Any]:
        """One ``/metrics`` scrape, parsed back through
        :func:`~deepspeed_tpu.telemetry.parse_prometheus_text`.  The
        text exposition carries no JSON stamp (Prometheus grammar has
        nowhere to put one) — schema enforcement rides the JSON routes
        polled by the same client against the same server.  On-demand
        only: :meth:`poll` deliberately skips it (the statusz document
        already embeds the registry snapshot)."""
        from deepspeed_tpu.telemetry import parse_prometheus_text
        cfg = self.cfg

        def fetch() -> str:
            delay, err = faults.poll("scrape", self.id)
            if delay:
                time.sleep(min(delay, cfg.timeout_s))
            if err is not None:
                raise faults.InjectedFault(
                    f"injected scrape fault (key={self.id!r})")
            return http_get_text(f"{self.url}/metrics", cfg.timeout_s)

        text = faults.retry_with_backoff(
            fetch, attempts=max(cfg.retries - 1, 0),
            backoff_s=cfg.backoff_s)
        return parse_prometheus_text(text)

    # ----------------------------------------------------- trace drain
    def fetch_trace(self, since: Optional[int] = None
                    ) -> Tuple[List[Event], Dict[str, Any]]:
        """Drain one incremental ``/tracez`` segment.  Advances the
        stored cursor (pass ``since`` to override, e.g. 0 for a full
        re-read) and returns ``(events, meta)`` where meta carries the
        remote's stamp + cursor/drop accounting."""
        cursor = self.trace_cursor if since is None else since
        doc = self._get("/tracez", f"since={cursor}")
        events = events_from_dicts(doc.get("events", []))
        self.trace_cursor = int(doc.get("total", cursor))
        meta = {k: doc.get(k) for k in
                ("wire_schema", "t_wall", "t_mono_ns", "since",
                 "total", "dropped", "replica")}
        return events, meta

    # ------------------------------------------------- clock correlation
    def estimate_clock_offset(self, probes: Optional[int] = None
                              ) -> Tuple[int, int]:
        """Min-RTT estimate of ``remote_monotonic - local_monotonic``.

        Each probe brackets one ``/healthz`` fetch with local
        ``monotonic_ns`` reads; the remote's ``t_mono_ns`` stamp is
        assumed taken at the bracket midpoint, so the sample error is
        bounded by RTT/2.  Keeping the minimum-RTT sample minimises
        that bound (NTP's core trick).  Returns and stores
        ``(offset_ns, err_bound_ns)``.
        """
        n = self.cfg.offset_probes if probes is None else int(probes)
        best_rtt = None
        best_offset = None
        for _ in range(max(n, 1)):
            t0 = time.monotonic_ns()
            doc = self._get("/healthz")
            t1 = time.monotonic_ns()
            rtt = t1 - t0
            remote = int(doc["t_mono_ns"])
            offset = remote - (t0 + t1) // 2
            if best_rtt is None or rtt < best_rtt:
                best_rtt, best_offset = rtt, offset
        self.clock_offset_ns = int(best_offset)
        self.clock_offset_err_ns = int(best_rtt // 2)
        return self.clock_offset_ns, self.clock_offset_err_ns

    # --------------------------------------------------- ReplicaSource
    def statusz_row(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Per-replica fleet statusz row, remote flavour: last-known
        engine fields plus the scrape-plane truth (state/age/errors).
        Shape-compatible with the in-process rows where the data
        exists; remote-only fields are additive."""
        now = self._clock() if now is None else now
        self.refresh_state(now)
        with self._lock:
            s = self.last_statusz or {}
            age = None if self.last_ok is None else now - self.last_ok
            row = {
                "replica": self.id,
                "remote": True,
                "url": self.url,
                "scrape_state": self.state,
                "scrape_age_s": round(age, 3) if age is not None
                else None,
                "scrape_errors": self.scrape_errors,
                "scrapes": self.scrapes,
                "scrape_latency_s": round(self.last_latency_s, 6),
                # matches the in-process fleet state vocabulary
                # (lowercase); "unknown" until the first scrape lands
                "state": "degraded"
                if (self.last_healthz or {}).get("degraded")
                else ("healthy" if self.last_healthz else "unknown"),
                "queue_depth": s.get("queue", {}).get("depth", 0),
                "active_slots": s.get("active_slots", 0),
                "uptime_s": s.get("uptime_s"),
                "role": None,
                "version": str(s.get("weights_version")),
                "mesh": s.get("mesh") or {
                    "sharded": False, "devices": 1, "axes": {},
                    "tp": 1, "ep": 1},
                "reasons": list(
                    (self.last_healthz or {}).get("reasons", [])),
            }
            if self.last_error is not None:
                row["scrape_error"] = self.last_error
            if self.clock_offset_ns is not None:
                row["clock_offset_ns"] = self.clock_offset_ns
                row["clock_offset_err_ns"] = self.clock_offset_err_ns
            return row

    def slo_snapshot(self, now: Optional[float] = None
                     ) -> Optional[Dict[str, Any]]:
        """Last-known ``statusz["slo"]`` — exactly the
        ``SLOTracker.snapshot()`` shape ``fleet_rollup`` consumes, so
        remote replicas fold into the fleet SLO with zero adaptation."""
        with self._lock:
            s = self.last_statusz
            return s.get("slo") if s else None

    def history_snapshot(self) -> Optional[Dict[str, Any]]:
        """Last-known ``historyz["history"]`` for ``history_rollup``
        (which already tolerates None/disabled snapshots)."""
        with self._lock:
            h = self.last_historyz
            return h.get("history") if h else None

    def healthz(self) -> Dict[str, Any]:
        with self._lock:
            h = dict(self.last_healthz or {})
        h["scrape_state"] = self.state
        h.setdefault("ready", self.state != LOST and bool(h))
        return h

    def close(self) -> None:
        self.closed = True


# ------------------------------------------------------------ trace merge
def merge_trace_segments(segments: List[Dict[str, Any]]
                         ) -> Dict[str, Any]:
    """Fold per-process trace segments into one Chrome trace.

    Each segment: ``{"events": [Event...], "offset_ns": int,
    "err_ns": int, "replica": str}``.  Events are shifted onto the
    LOCAL monotonic axis (``t_ns - offset_ns``), tagged with their
    replica in attrs (request spans from the same req id stitch
    naturally once they share an axis), merge-sorted, and rendered via
    :func:`events_to_chrome`.  The per-segment offsets and error
    bounds land in ``otherData.clock_offsets`` so a reader knows how
    much cross-process skew to trust.
    """
    merged: List[Event] = []
    offsets: Dict[str, Dict[str, Any]] = {}
    for seg in segments:
        off = int(seg.get("offset_ns") or 0)
        tag = str(seg.get("replica", f"r{len(offsets)}"))
        offsets[tag] = {"offset_ns": off,
                        "err_ns": int(seg.get("err_ns") or 0),
                        "events": len(seg.get("events", []))}
        for (t, req, slot, phase, attrs) in seg.get("events", []):
            a = dict(attrs) if attrs else {}
            a.setdefault("replica", tag)
            merged.append((t - off, req, slot, phase, a))
    merged.sort(key=lambda e: e[0])
    chrome = events_to_chrome(merged)
    chrome["otherData"]["clock_offsets"] = offsets
    chrome["otherData"]["merged_segments"] = len(segments)
    return chrome
