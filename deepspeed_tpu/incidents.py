"""Incident engine: automatic capture of the fleet's failure moments —
the trip logic of the black-box flight recorder (PR 15).

The stack already *emits* everything a postmortem needs: structured
flight-recorder events (``slo_burn_alert``, ``kv_promote_failed``,
``replica_dead``, ``rollout_halt``/``rollout_rolled_back``,
``request_shed`` storms), registry metrics, and ``/statusz``
snapshots.  What it lacked was the production answer to "were you
watching at the right moment": operators debug a 3 a.m. burn trip from
whatever ``dstpu_top`` happened to show.  :class:`IncidentManager`
closes that gap — it polls the flight-recorder ring incrementally on
the shared :class:`~deepspeed_tpu.telemetry.TelemetryExporter` tick
(never the decode hot path), classifies trigger events into incident
classes, runs lightweight EWMA z-score detectors over
:class:`~deepspeed_tpu.history.MetricHistory` series (TTFT p95, stall
rate, goodput collapse — the trajectory pathologies ZeRO-Infinity-
style tiered streaming develops over seconds, arXiv:2104.07857), and
on a trip captures an **incident bundle**: one atomic JSON document
(``utils/evidence.atomic_write_json``) holding

- the triggering event (or detector verdict) at t0,
- ``pre_window_s`` of metric history for the tracked series,
- the last ``ring_events`` flight-recorder events around t0,
- the ``/statusz`` + SLO snapshot at capture time,
- the history annotations (scale/rollout marks) inside the window.

Dedup discipline: trips of one incident class inside
``dedup_window_s`` are SUPPRESSED (counted, never written) — a burn
storm yields one bundle, not hundreds — and ``max_bundles`` caps a
process's total.  ``tools/incident_report.py`` renders a bundle into a
human timeline; ``dstpu_top`` shows recent incidents as a ticker row.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.config import IncidentsConfig
from deepspeed_tpu.request_trace import event_to_dict
from deepspeed_tpu.utils.evidence import atomic_write_json

# trigger event phase -> incident class.  These are the structured
# events the stack already emits; anything else in the ring is context,
# not a trip.
# a detector excursion must hold this many consecutive evaluations
# before it trips: percentile series are bucket-quantized, so a single
# one-bucket jump is jitter; a sustained excursion is a regime change
_DETECTOR_CONSECUTIVE = 3

TRIGGER_PHASES: Dict[str, str] = {
    "slo_burn_alert": "slo_burn",
    "kv_promote_failed": "kv_tier_fault",
    "replica_dead": "replica_failover",
    "rollout_halt": "rollback",
    "rollout_rolled_back": "rollback",
    "autoscale_up_failed": "scale_failure",
    "watchdog_fired": "watchdog",
    # a scraped out-of-process replica aged past lost_after_s (or
    # spoke an incompatible wire schema) — emitted once per outage by
    # obs_wire.RemoteReplica on the router's tracer
    "remote_lost": "remote_lost",
}


class IncidentManager:
    """Subscribe to the structured event stream + run online anomaly
    detectors; capture deduped incident bundles on trips.

    Single-writer contract: :meth:`maybe_evaluate` runs on the engine/
    router thread (exporter tick hook).  Read surfaces
    (:meth:`snapshot`) are safe from the HTTP thread — bundle metadata
    lives in an append-only list.
    """

    def __init__(self, cfg: IncidentsConfig, *, registry, tracer=None,
                 history=None,
                 statusz_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 source: str = "engine",
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.enabled = bool(cfg.enabled)
        self.tracer = tracer
        self.history = history
        self.statusz_fn = statusz_fn
        self.source = str(source)
        self._clock = clock
        self._last_eval: Optional[float] = None
        self._ring_cursor = 0
        self._last_trip: Dict[str, float] = {}     # class -> t (dedup)
        self._seq = 0
        self.bundles: List[Dict[str, Any]] = []    # meta, append-only
        # plain-int twins of the registry counters: snapshot() must
        # report true suppression/trip totals even when the manager runs
        # on a DISABLED registry (incidents needs tracing, not
        # telemetry — null metrics would read 0 forever)
        self._n_suppressed = 0
        self._n_detector = 0
        # EWMA detector state: series ->
        # [mean, var, n, streak, last_bucket_t] — last_bucket_t gates
        # the update to once per NEW history sample, whatever the
        # evaluation cadence (an explicit empty cfg.detect disables;
        # None defers to the consumer's defaults via watch_series)
        self._detect: Dict[str, List[Any]] = {
            name: [0.0, 0.0, 0, 0, None]
            for name in (cfg.detect or ())}
        # extra trip probes: zero-arg callables returning
        # (class, attrs) on a trip, None otherwise (the watchdog feed)
        self._probes: List[Callable[[], Optional[Tuple[str, Dict]]]] = []
        # named bundle attachments: zero-arg callables whose return
        # value is embedded in every bundle under its name (devprof
        # registers its compile ledger + capture references here)
        self._attachments: Dict[str, Callable[[], Any]] = {}
        r = registry
        self._c_bundles = r.counter(
            "incident_bundles_total",
            "incident bundles captured (atomic JSON, deduped per "
            "class inside incidents.dedup_window_s)")
        self._c_suppressed = r.counter(
            "incident_suppressed_total",
            "trips suppressed by per-class dedup / the bundle cap — "
            "a burn storm yields one bundle, not hundreds")
        self._c_detector = r.counter(
            "incident_detector_trips",
            "EWMA z-score anomaly-detector trips (before dedup)")

    # ------------------------------------------------------------ wiring
    def watch_series(self, name: str) -> None:
        """Add a history series to the EWMA anomaly detectors (the
        ZeRO-Inference engine registers its stream-stall p95 here)."""
        self._detect.setdefault(name, [0.0, 0.0, 0, 0, None])

    def add_probe(self, fn: Callable[[], Optional[Tuple[str, Dict]]]
                  ) -> None:
        """Register an extra trip probe, polled each evaluation:
        return ``(incident_class, attrs)`` to trip, None otherwise.
        Probes are individually guarded — a broken probe never takes
        down the tick."""
        self._probes.append(fn)

    def add_attachment(self, name: str, fn: Callable[[], Any]) -> None:
        """Register a named bundle attachment: ``fn()`` is evaluated at
        capture time and embedded in the bundle under ``name``.  Each
        attachment is individually guarded — a broken one degrades to
        an ``{"error": ...}`` stanza, never loses the bundle."""
        self._attachments[str(name)] = fn

    # ---------------------------------------------------------- evaluate
    # dstpu: hot-path
    def maybe_evaluate(self, now: Optional[float] = None) -> bool:
        """One evaluation if ``eval_interval_s`` elapsed; safe to call
        every scheduler step (one clock compare until due)."""
        if not self.enabled:
            return False
        now = self._clock() if now is None else now
        if self._last_eval is not None and \
                now - self._last_eval < self.cfg.eval_interval_s:
            return False
        self.evaluate(now)
        return True

    def evaluate(self, now: Optional[float] = None) -> List[str]:
        """Unconditional evaluation pass: drain new ring events,
        classify triggers, run detectors and probes; capture bundles
        for surviving trips.  Returns the classes captured."""
        if not self.enabled:
            return []
        now = self._clock() if now is None else now
        self._last_eval = now
        trips: List[Tuple[str, Dict[str, Any]]] = []
        shed_seen = 0
        recorder = (self.tracer.recorder
                    if self.tracer is not None and self.tracer.enabled
                    else None)
        if recorder is not None:
            self._ring_cursor, fresh = recorder.events_since(
                self._ring_cursor)
            for e in fresh:
                cls = TRIGGER_PHASES.get(e[3])
                if cls is not None:
                    trips.append((cls, {"trigger": event_to_dict(e)}))
                elif e[3] == "request_shed":
                    shed_seen += 1
        if self.cfg.shed_storm_threshold and \
                shed_seen >= self.cfg.shed_storm_threshold:
            trips.append(("shed_storm", {"trigger": {
                "phase": "request_shed",
                "sheds_in_window": shed_seen}}))
        trips.extend(self._run_detectors())
        for probe in self._probes:
            try:
                got = probe()
            except Exception:
                got = None          # a broken probe never kills the tick
            if got is not None:
                cls, attrs = got
                trips.append((str(cls), {"trigger": dict(attrs)}))
        captured: List[str] = []
        for cls, info in trips:
            if self._capture(cls, info, now):
                captured.append(cls)
        return captured

    def _run_detectors(self) -> List[Tuple[str, Dict[str, Any]]]:
        """EWMA z-score over the configured history series: trip when
        the latest sample sits past ``z_threshold`` standard deviations
        from the running mean (two-sided — a goodput COLLAPSE is a
        negative excursion) after ``min_samples`` of warmup, AND the
        excursion sustains :data:`_DETECTOR_CONSECUTIVE` consecutive
        evaluations — a single bucket-quantized percentile jump is
        jitter, a held excursion is a regime change.  The std carries a
        relative floor so a near-constant warmup cannot make any
        ordinary fluctuation read as infinite z."""
        out: List[Tuple[str, Dict[str, Any]]] = []
        h = self.history
        if h is None or not getattr(h, "enabled", False):
            return out
        a = self.cfg.ewma_alpha
        for name, st in self._detect.items():
            pt = h.latest_point(name)
            if pt is None:
                continue
            t, x = pt
            if st[4] is not None and t <= st[4]:
                continue       # no NEW sample since the last judgment
            st[4] = t
            mean, var, n, streak = st[:4]
            if n >= self.cfg.min_samples:
                std = max(math.sqrt(max(var, 0.0)),
                          0.02 * abs(mean), 1e-9)
                z = (x - mean) / std
                if abs(z) >= self.cfg.z_threshold:
                    st[3] = streak + 1
                    if st[3] >= _DETECTOR_CONSECUTIVE:
                        st[3] = 0
                        self._c_detector.inc()
                        self._n_detector += 1
                        out.append((f"anomaly_{_slug(name)}",
                                    {"trigger": {
                                        "detector": name,
                                        "value": round(x, 6),
                                        "z": round(z, 3),
                                        "mean": round(mean, 6),
                                        "std": round(std, 6)}}))
                    # the excursion must not poison the baseline the
                    # next samples are judged against
                    continue
                st[3] = 0
            d = x - mean
            st[0] = mean + a * d
            st[1] = (1.0 - a) * (var + a * d * d)
            st[2] = n + 1
        return out

    # ------------------------------------------------------------ capture
    def _capture(self, cls: str, info: Dict[str, Any],
                 now: float) -> bool:
        last = self._last_trip.get(cls)
        if last is not None and now - last < self.cfg.dedup_window_s:
            self._c_suppressed.inc()
            self._n_suppressed += 1
            return False
        if len(self.bundles) >= self.cfg.max_bundles:
            self._c_suppressed.inc()
            self._n_suppressed += 1
            return False
        self._last_trip[cls] = now
        self._seq += 1
        bundle: Dict[str, Any] = {
            "schema_version": 1,
            "incident": cls,
            "source": self.source,
            "seq": self._seq,
            "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "t0_monotonic": round(now, 3),
            "pre_window_s": self.cfg.pre_window_s,
            **info,
        }
        h = self.history
        if h is not None and getattr(h, "enabled", False):
            bundle["history"] = h.snapshot(
                now=now, window_s=self.cfg.pre_window_s)
        recorder = (self.tracer.recorder
                    if self.tracer is not None and self.tracer.enabled
                    else None)
        if recorder is not None:
            bundle["ring"] = [event_to_dict(e) for e in
                              recorder.tail(self.cfg.ring_events)]
        if self.statusz_fn is not None:
            try:
                bundle["statusz"] = self.statusz_fn()
            except Exception as e:     # a broken snapshot must not
                bundle["statusz"] = {"error": repr(e)}  # lose the bundle
        for aname, afn in self._attachments.items():
            try:
                bundle[aname] = afn()
            except Exception as e:     # same contract as statusz_fn
                bundle[aname] = {"error": repr(e)}
        # source is part of the name: _seq is per-MANAGER, and a fleet-
        # level manager plus replica engine-level managers can share
        # one dir — without it their same-class bundles would collide
        # on (class, pid, seq) and atomic_write_json would overwrite
        path = os.path.join(
            self.cfg.dir,
            f"incident_{_slug(self.source)}_{_slug(cls)}_"
            f"{os.getpid()}_{self._seq}.json")
        try:
            os.makedirs(self.cfg.dir, exist_ok=True)
            atomic_write_json(bundle, path)
        except OSError:
            from deepspeed_tpu.utils.logging import logger

            logger.exception("incidents: bundle write to %s", path)
            path = None
        self.bundles.append({
            "incident": cls, "seq": self._seq, "t": bundle["t"],
            "t0_monotonic": bundle["t0_monotonic"], "path": path,
        })
        self._c_bundles.inc()
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.event("incident_bundle", attrs={
                "incident": cls, "seq": self._seq, "path": path})
        return True

    # ------------------------------------------------------------- read
    def snapshot(self) -> Dict[str, Any]:
        """The ``/statusz``/``/historyz`` ``incidents`` block + the
        dstpu_top ticker feed: bundle/suppression totals and recent
        bundle metadata (never bundle contents — those live on disk)."""
        if not self.enabled:
            return {"enabled": False}
        return {
            "enabled": True,
            "dir": self.cfg.dir,
            "bundles": len(self.bundles),
            "suppressed": self._n_suppressed,
            "detector_trips": self._n_detector,
            "detect_series": sorted(self._detect),
            "recent": list(self.bundles)[-8:],
        }


def _slug(name: str) -> str:
    return "".join(c if (c.isalnum() or c == "_") else "_"
                   for c in name)


class _NullIncidentManager:
    """Shared no-op stand-in when the block is off."""

    enabled = False
    bundles: List[Dict[str, Any]] = []

    def watch_series(self, name):
        pass

    def add_probe(self, fn):
        pass

    def add_attachment(self, name, fn):
        pass

    def maybe_evaluate(self, now=None):
        return False

    def evaluate(self, now=None):
        return []

    def snapshot(self):
        return {"enabled": False}


NULL_INCIDENTS = _NullIncidentManager()
