"""HuggingFace interop (SURVEY.md §2 #38; ref: the reference's HF Trainer
integration + module_inject checkpoint loading,
deepspeed/module_inject/load_checkpoint.py).

Loads HF checkpoints (safetensors or torch .bin shards) into plain numpy
state dicts, then converts to our pytrees via inference/injection.py
policies.  Tokenizers pass through untouched (they are host-side).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def load_state_dict(model_dir: str) -> Dict[str, np.ndarray]:
    """Load all weight shards under ``model_dir`` → {name: np.ndarray}."""
    sd: Dict[str, np.ndarray] = {}
    entries = sorted(os.listdir(model_dir))
    safes = [e for e in entries if e.endswith(".safetensors")]
    bins = [e for e in entries if e.endswith(".bin") and "pytorch_model" in e]
    if safes:
        from safetensors import safe_open

        for fname in safes:
            with safe_open(os.path.join(model_dir, fname), framework="np") as f:
                for key in f.keys():
                    sd[key] = f.get_tensor(key)
    elif bins:
        import torch

        for fname in bins:
            shard = torch.load(os.path.join(model_dir, fname),
                               map_location="cpu", weights_only=True)
            for key, val in shard.items():
                sd[key] = val.float().numpy()
    else:
        raise FileNotFoundError(
            f"no .safetensors or pytorch_model*.bin under {model_dir}")
    return sd


def load_config(model_dir: str) -> Dict[str, Any]:
    with open(os.path.join(model_dir, "config.json")) as f:
        return json.load(f)


def from_pretrained(model_dir: str, attn_impl: str = "auto",
                    dtype=None, arch: Optional[str] = None):
    """Load an HF checkpoint directory into (apply_fn, params, cfg, specs).

    The architecture is taken from config.json ``architectures[0]`` unless
    overridden.  Equivalent of the reference's
    ``deepspeed.init_inference(AutoModel.from_pretrained(...))`` flow
    without materializing a torch module.
    """
    import jax.numpy as jnp

    from deepspeed_tpu.inference.injection import inject

    hf_cfg = load_config(model_dir)
    arch = arch or (hf_cfg.get("architectures") or ["llama"])[0]
    sd = load_state_dict(model_dir)
    return inject(arch, hf_cfg, sd, attn_impl=attn_impl,
                  dtype=dtype or jnp.bfloat16)


def save_pretrained(params, cfg, save_dir: str) -> None:
    """Export our llama pytree back to an HF-layout safetensors checkpoint
    (inverse of injection's weight converter) so trained weights flow back
    into the HF ecosystem."""
    import jax

    os.makedirs(save_dir, exist_ok=True)
    params = jax.tree.map(lambda x: np.asarray(x, np.float32), params)
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": params["embed"],
        "model.norm.weight": params["final_norm"],
    }
    blocks = params["blocks"]
    L = blocks["wq"].shape[0]
    names = {
        "attn_norm": ("model.layers.{}.input_layernorm.weight", False),
        "wq": ("model.layers.{}.self_attn.q_proj.weight", True),
        "wk": ("model.layers.{}.self_attn.k_proj.weight", True),
        "wv": ("model.layers.{}.self_attn.v_proj.weight", True),
        "wo": ("model.layers.{}.self_attn.o_proj.weight", True),
        "mlp_norm": ("model.layers.{}.post_attention_layernorm.weight", False),
        "w1": ("model.layers.{}.mlp.gate_proj.weight", True),
        "w3": ("model.layers.{}.mlp.up_proj.weight", True),
        "w2": ("model.layers.{}.mlp.down_proj.weight", True),
    }
    for i in range(L):
        for ours, (fmt, transpose) in names.items():
            w = blocks[ours][i]
            sd[fmt.format(i)] = w.T if transpose else w
    if "lm_head" in params:
        sd["lm_head.weight"] = params["lm_head"].T
    from safetensors.numpy import save_file

    # safetensors serializes the raw buffer — transposed views must be
    # materialized or the strides are silently dropped
    sd = {k: np.ascontiguousarray(v) for k, v in sd.items()}
    save_file(sd, os.path.join(save_dir, "model.safetensors"))
    hf_cfg = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": int(cfg.vocab_size),
        "hidden_size": int(cfg.dim),
        "num_hidden_layers": int(cfg.n_layers),
        "num_attention_heads": int(cfg.n_heads),
        "num_key_value_heads": int(cfg.n_kv_heads),
        "intermediate_size": int(cfg.ffn_dim),
        "max_position_embeddings": int(cfg.max_seq_len),
        "rope_theta": float(cfg.rope_theta),
        "rms_norm_eps": float(cfg.norm_eps),
        "tie_word_embeddings": bool(cfg.tie_embeddings),
        "model_type": "llama",
    }
    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)
