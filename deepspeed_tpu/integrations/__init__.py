"""Framework integrations (ref: DeepSpeed's HF Trainer / accelerate glue)."""
