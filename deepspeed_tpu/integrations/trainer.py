"""HF-Trainer-style bridge (SURVEY.md §2 #38; ref: the reference's
HuggingFace integration — ``TrainingArguments(deepspeed=<config>)`` +
``Trainer.train()`` driving ``deepspeed.initialize`` under the hood, and
transformers' ``HfTrainerDeepSpeedConfig.trainer_config_process`` which
fills the config's ``"auto"`` values from the TrainingArguments).

The shim keeps the same three-object shape users know::

    args = TrainingArguments(output_dir=..., deepspeed={...}, ...)
    trainer = Trainer(model_dir="path/to/hf-llama", args=args,
                      train_dataset=[{"input_ids": [...]}, ...])
    trainer.train()
    trainer.save_model()          # HF-layout safetensors + config.json

``model_dir`` is an HF checkpoint directory (safetensors / torch bins);
the weights round-trip through :mod:`deepspeed_tpu.integrations.hf` and
the architecture policies in :mod:`deepspeed_tpu.inference.injection`,
so the trained model loads back with ``AutoModel.from_pretrained``.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class TrainingArguments:
    """The TrainingArguments fields the reference's HF integration reads
    when resolving a DeepSpeed config (everything else in HF's class is
    torch-runtime plumbing with no TPU analogue)."""

    output_dir: str = "output"
    deepspeed: Any = None                  # dict | path to a DS json
    per_device_train_batch_size: int = 8
    gradient_accumulation_steps: int = 1
    learning_rate: float = 5e-5
    weight_decay: float = 0.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    max_grad_norm: float = 1.0
    num_train_epochs: float = 1.0
    max_steps: int = -1                    # >0 overrides epochs
    warmup_steps: int = 0
    logging_steps: int = 10
    seed: int = 42


def _resolve_auto(ds: Dict[str, Any], args: TrainingArguments,
                  num_update_steps: int) -> Dict[str, Any]:
    """Fill ``"auto"`` leaves from TrainingArguments (ref: transformers
    HfTrainerDeepSpeedConfig.trainer_config_process /
    trainer_config_finalize — same key → argument mapping)."""
    ds = json.loads(json.dumps(ds))  # deep copy, keeps it JSON-clean
    fills = {
        "train_micro_batch_size_per_gpu": args.per_device_train_batch_size,
        "gradient_accumulation_steps": args.gradient_accumulation_steps,
        "gradient_clipping": args.max_grad_norm,
    }
    for key, val in fills.items():
        if ds.get(key) == "auto":
            ds[key] = val
    opt = ds.get("optimizer", {})
    op = opt.get("params", {})
    for key, val in (("lr", args.learning_rate),
                     ("betas", [args.adam_beta1, args.adam_beta2]),
                     ("eps", args.adam_epsilon),
                     ("weight_decay", args.weight_decay)):
        if op.get(key) == "auto":
            op[key] = val
    sched = ds.get("scheduler", {})
    sp = sched.get("params", {})
    for key, val in (("warmup_max_lr", args.learning_rate),
                     ("warmup_min_lr", 0.0),
                     ("warmup_num_steps", args.warmup_steps),
                     ("total_num_steps", num_update_steps)):
        if sp.get(key) == "auto":
            sp[key] = val
    leftovers = [k for k, v in {**ds, **op, **sp}.items() if v == "auto"]
    if leftovers:
        raise ValueError(
            f"unresolved 'auto' config values {leftovers} — no "
            f"TrainingArguments counterpart (the reference raises here too)")
    return ds


def _pad_batch(rows: Sequence[List[int]], pad_id: int,
               length: int) -> Dict[str, np.ndarray]:
    toks = np.full((len(rows), length), pad_id, np.int32)
    mask = np.zeros((len(rows), length), np.float32)
    for i, r in enumerate(rows):
        toks[i, :len(r)] = r[:length]
        mask[i, :min(len(r), length)] = 1.0
    return {"tokens": toks, "loss_mask": mask}


class Trainer:
    """Minimal HF-Trainer facade over :func:`deepspeed_tpu.initialize`.

    Parameters
    ----------
    model_dir: HF checkpoint directory to fine-tune (loaded via
        :func:`integrations.hf.from_pretrained`), or pass ``model`` as the
        ``(apply_fn, params, cfg, specs)`` tuple directly.
    args: :class:`TrainingArguments`; ``args.deepspeed`` is REQUIRED —
        this bridge exists to honor that config contract.
    train_dataset: sequence/iterable of ``{"input_ids": [...]}`` rows
        (HF datasets convention).
    """

    def __init__(self, model: Any = None, args: TrainingArguments = None,
                 train_dataset: Iterable = None, *,
                 model_dir: Optional[str] = None,
                 arch: Optional[str] = None,
                 max_seq_len: Optional[int] = None):
        if args is None or args.deepspeed is None:
            raise ValueError(
                "Trainer requires TrainingArguments with a `deepspeed` "
                "config (dict or json path) — that contract is the point "
                "of this bridge")
        if (model is None) == (model_dir is None):
            raise ValueError("pass exactly one of model / model_dir")
        from deepspeed_tpu.integrations import hf as hf_io

        if model_dir is not None:
            model = hf_io.from_pretrained(model_dir, arch=arch)
        self.apply_fn, params, self.model_cfg, self.param_specs = model
        if params is None:
            raise ValueError("checkpoint had no weights to fine-tune")
        self.args = args
        self.train_dataset = list(train_dataset or [])
        if not self.train_dataset:
            raise ValueError("train_dataset is empty")
        ds = args.deepspeed
        if isinstance(ds, str):
            with open(ds) as f:
                ds = json.load(f)

        self._rows = [list(map(int, r["input_ids"]))
                      for r in self.train_dataset]
        self.max_seq_len = max_seq_len or min(
            self.model_cfg.max_seq_len, max(len(r) for r in self._rows))
        steps_per_epoch = self._steps_per_epoch(ds, args)
        num_update_steps = (args.max_steps if args.max_steps > 0 else
                            math.ceil(args.num_train_epochs
                                      * steps_per_epoch))
        ds = _resolve_auto(ds, args, num_update_steps)
        self.num_update_steps = num_update_steps

        # honor the JSON activation_checkpointing block (ref: the HF
        # trainer's gradient_checkpointing flows through the ds config):
        # apply_fn closes over the MUTABLE model cfg — same pattern
        # injection.inject uses for attn_impl — so setting remat here
        # reaches the already-built forward
        from deepspeed_tpu.config import Config as _DsConfig
        from deepspeed_tpu.remat import resolve_policy

        ac_policy = _DsConfig.from_dict(ds).activation_checkpointing.policy
        if ac_policy != "none" and hasattr(self.model_cfg, "remat"):
            self.model_cfg.remat = resolve_policy(ac_policy)

        import deepspeed_tpu as dstpu

        # causal-LM loss over the policy's apply_fn
        import jax
        import jax.numpy as jnp

        def loss_fn(p, batch):
            logits = self.apply_fn(p, batch["tokens"][:, :-1])
            targets = batch["tokens"][:, 1:]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            mask = batch["loss_mask"][:, 1:]
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        self.engine, self.optimizer, _, self.lr_scheduler = dstpu.initialize(
            loss_fn=loss_fn, params=params, config=ds,
            param_specs=self.param_specs)
        self._pad_id = 0

    def _steps_per_epoch(self, ds: Dict[str, Any],
                         args: TrainingArguments) -> int:
        # global batch isn't final until the engine resolves it; estimate
        # with the same arithmetic for scheduler total_num_steps
        micro = ds.get("train_micro_batch_size_per_gpu")
        if micro in (None, "auto"):
            micro = args.per_device_train_batch_size
        accum = ds.get("gradient_accumulation_steps")
        if accum in (None, "auto"):
            accum = args.gradient_accumulation_steps
        import jax

        world = jax.device_count()
        return max(1, len(self.train_dataset) // (micro * accum * world))

    # -------------------------------------------------------------- training
    def get_train_dataloader(self, epoch: int = 0):
        """Shuffled epoch iterator of padded {tokens, loss_mask} batches
        (fresh permutation per epoch, like the HF Trainer's sampler)."""
        B = self.engine.train_batch_size
        if len(self._rows) < B:
            raise ValueError(
                f"train_dataset has {len(self._rows)} rows but the global "
                f"batch is {B} (micro*accum*world) — not even one batch")
        rng = np.random.default_rng(self.args.seed + epoch)
        order = rng.permutation(len(self._rows))
        for i in range(0, len(order) - B + 1, B):
            rows = [self._rows[j] for j in order[i:i + B]]
            yield _pad_batch(rows, self._pad_id, self.max_seq_len)

    def train(self) -> Dict[str, float]:
        """Run the configured steps/epochs; returns final metrics (the
        reference returns a TrainOutput — we keep a plain dict)."""
        args = self.args
        target = self.num_update_steps
        step = 0
        epoch = 0
        losses: List[float] = []
        while step < target:
            for batch in self.get_train_dataloader(epoch):
                loss = float(self.engine.train_batch(batch))
                losses.append(loss)
                step += 1
                if args.logging_steps and step % args.logging_steps == 0:
                    logger.info("trainer step %d/%d loss=%.4f lr=%.2e",
                                step, target, loss,
                                self.engine.get_lr()[0])
                if step >= target:
                    break
            epoch += 1
        return {"train_loss": float(np.mean(losses)) if losses else 0.0,
                "train_steps": step, "final_loss": losses[-1]}

    # ------------------------------------------------------------ save/export
    def save_model(self, output_dir: Optional[str] = None) -> str:
        """Export HF-layout safetensors (ref: Trainer.save_model, which
        consolidates ZeRO shards first — module_params does that here)."""
        from deepspeed_tpu.integrations import hf as hf_io

        out = output_dir or self.args.output_dir
        params = self.engine.module_params()
        hf_io.save_pretrained(params, self.model_cfg, out)
        return out

    def save_state(self, output_dir: Optional[str] = None) -> str:
        """Engine checkpoint (optimizer state included) for resumption."""
        out = os.path.join(output_dir or self.args.output_dir, "ds_ckpt")
        return self.engine.save_checkpoint(out)
