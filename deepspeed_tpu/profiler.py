"""FLOPs profiler (ref: deepspeed/profiling/flops_profiler/profiler.py).

The reference hooks every torch module to count MACs/params and prints a
per-module table plus aggregate FLOPS/latency.  The TPU-native design
has two complementary sources of truth:

- **XLA cost analysis**: ``jit(fn).lower(...).compile().cost_analysis()``
  returns the compiler's own flops / bytes-accessed estimate for the real
  fused program — more honest than module hooks, since it sees what
  actually runs after fusion.
- **Analytic formulas** for transformer train/inference FLOPs (the
  standard 6*N*T + attention terms), used for MFU targets and for
  per-component tables where compilation is too coarse.

``get_model_profile`` mirrors the reference's entrypoint name.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.timers import device_peak_flops


# ----------------------------------------------------------------- analytic
def transformer_train_flops(n_params: float, tokens: float,
                            n_layers: int = 0, hidden: int = 0,
                            seq_len: int = 0,
                            checkpoint_activations: bool = False) -> float:
    """FLOPs for one train step over ``tokens`` tokens.

    Standard decomposition (Kaplan/Chinchilla accounting): 6*N per token
    for fwd+bwd matmuls (8*N with full activation rematerialisation), plus
    the seq-quadratic attention term 12*L*H*T^2 per sequence-token batch.
    """
    mult = 8.0 if checkpoint_activations else 6.0
    flops = mult * n_params * tokens
    if n_layers and hidden and seq_len:
        attn_mult = 4.0 if checkpoint_activations else 3.0
        flops += attn_mult * 4.0 * n_layers * hidden * seq_len * tokens
    return flops


def transformer_decode_flops(n_params: float, n_layers: int, hidden: int,
                             kv_len: int) -> float:
    """FLOPs for decoding ONE token with a ``kv_len`` KV cache."""
    return 2.0 * n_params + 4.0 * n_layers * hidden * kv_len


def params_count(params: Any) -> int:
    """Total leaf elements of a pytree (ref: profiler's params column)."""
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params)
               if hasattr(x, "shape"))


# ------------------------------------------------------------- XLA-measured
def xla_cost_analysis_lowered(lowered) -> Dict[str, float]:
    """Compiler-reported flops / bytes for an already-lowered program
    (``jit(fn).lower(...)`` — concrete args or ShapeDtypeStructs both
    work).  The entry point :mod:`deepspeed_tpu.devprof` reuses for its
    roofline denominators: the engine lowers its OWN jitted sweep
    programs once at build instead of re-jitting through
    :func:`xla_cost_analysis`."""
    ca = lowered.compile().cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns a per-computation list
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


def xla_cost_analysis(fn: Callable, *args,
                      static_argnums=()) -> Dict[str, float]:
    """Compiler-reported flops / bytes for the fused program."""
    return xla_cost_analysis_lowered(
        jax.jit(fn, static_argnums=static_argnums).lower(*args))


class FlopsProfiler:
    """Measure a jitted step: XLA flops, wall latency, achieved TFLOPS, MFU.

    ref: deepspeed/profiling/flops_profiler — ``start_profile`` /
    ``stop_profile`` / ``print_model_profile`` flow, minus torch hooks.
    """

    def __init__(self, fn: Callable, static_argnums=()):
        self.fn = fn
        self.static_argnums = static_argnums
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.latency = 0.0

    def profile(self, *args, iters: int = 5, warmup: int = 2) -> Dict[str, float]:
        cost = xla_cost_analysis(self.fn, *args, static_argnums=self.static_argnums)
        self.flops = cost["flops"]
        self.bytes_accessed = cost["bytes_accessed"]
        jfn = jax.jit(self.fn, static_argnums=self.static_argnums)
        for _ in range(warmup):
            jax.block_until_ready(jfn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jfn(*args)
        jax.block_until_ready(out)
        self.latency = (time.perf_counter() - t0) / iters
        return self.summary()

    def summary(self) -> Dict[str, float]:
        tflops = self.flops / self.latency / 1e12 if self.latency else 0.0
        peak = device_peak_flops()
        return {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "latency_s": self.latency,
            "tflops": tflops,
            "mfu": tflops * 1e12 / peak if peak else 0.0,
            "arithmetic_intensity": (self.flops / self.bytes_accessed
                                     if self.bytes_accessed else 0.0),
        }


def get_model_profile(fn: Callable, args: Tuple, params: Optional[Any] = None,
                      iters: int = 5, print_profile: bool = True,
                      static_argnums=()) -> Dict[str, float]:
    """One-call profile (ref: flops_profiler.get_model_profile)."""
    prof = FlopsProfiler(fn, static_argnums=static_argnums)
    out = prof.profile(*args, iters=iters)
    if params is not None:
        out["params"] = float(params_count(params))
    if print_profile:
        from deepspeed_tpu.utils.logging import log_dist

        rows = [f"  {k:>22}: {v:.4g}" for k, v in out.items()]
        log_dist("flops profile:\n" + "\n".join(rows))
    return out
