"""Process-boundary transport: the real wire under the KV fabric.

PR 12 pinned the fabric/router contracts inside one interpreter and
deferred the transport; this module is that deferred piece.  Two
interchangeable byte movers live behind one framed-message surface:

- :class:`ShmRing` — a file-backed mmap ring for same-host peers.
  Fixed-size slots, single-producer/single-consumer per direction,
  per-fragment crc32, and a sequence-number publication order that
  makes torn writes *detectable*: the producer writes payload, then
  length/crc, then the slot's sequence word LAST, and only then
  advances the shared head — a consumer never trusts a slot whose
  sequence doesn't match its own consume cursor.  A full ring is
  backpressure (bounded poll-sleep), never silent drop.
- :class:`TcpEndpoint` — a length-prefixed TCP stream, the general
  path.  Reconnect rides :func:`deepspeed_tpu.faults.
  retry_with_backoff` at the :class:`Channel` layer.

Above both sits :class:`Channel`: JSON header + raw array blobs in one
crc-framed message, ``transport`` fault-rule hooks (``send:<peer>``,
``recv:<peer>``, ``corrupt:<peer>``), ``transport_*`` metrics, and a
sequence-matched ``request()`` RPC.  :func:`entry_to_wire` /
:func:`entry_from_wire` carry :class:`~deepspeed_tpu.inference.
prefix_cache.TierEntry` pages — including int8-quantized cold pages —
with their demote-time per-buffer crc32s travelling verbatim, so the
importer's promotion-time checksum stays the correctness last line no
matter what the wire did.  A corrupt frame raises
:class:`TransportCorrupt` here and degrades to recompute upstream;
it never becomes wrong tokens.
"""

from __future__ import annotations

import json
import mmap
import os
import socket
import struct
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu import faults as _faults
from deepspeed_tpu.telemetry import MetricsRegistry
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "TransportError", "TransportCorrupt", "TransportClosed",
    "encode_frame", "decode_frame", "entry_to_wire", "entry_from_wire",
    "entries_to_frame", "entries_from_frame",
    "ShmRing", "ShmEndpoint", "TcpEndpoint", "TcpListener",
    "connect_tcp", "Channel",
]


class TransportError(IOError):
    """The wire failed (timeout, broken pipe, injected fault).  An
    IOError so :func:`~deepspeed_tpu.faults.retry_with_backoff`'s
    default ``retry_on`` covers it."""


class TransportCorrupt(TransportError):
    """A frame arrived but its checksum/sequence bookkeeping does not
    add up (bit rot, torn write, injected corruption).  The payload
    must be discarded — upstream degrades to recompute."""


class TransportClosed(TransportError):
    """The peer is gone (EOF / closed endpoint) — distinct from a
    transient error so a router can fail over instead of retrying."""


# --------------------------------------------------------------------
# frame codec: one JSON header + N raw array blobs, crc32 over all of it
# --------------------------------------------------------------------

_FRAME_MAGIC = 0x44535457          # "DSTW"
_FRAME_HDR = struct.Struct("<III")  # magic, crc32, json_len


def _np_dtype(name: str) -> np.dtype:
    """``np.dtype`` lookup that understands the accelerator dtypes
    (bfloat16 et al.) even when only ml_dtypes registers them."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


# every message (and every migrated page) funnels through here; one
# json dump + flat byte concat, no per-element work
# dstpu: hot-path
def encode_frame(msg: Dict[str, Any],
                 blobs: Sequence[np.ndarray] = ()) -> bytes:
    parts: List[bytes] = []
    meta = []
    for b in blobs:
        a = np.ascontiguousarray(b)
        parts.append(a.tobytes())
        meta.append([list(a.shape), str(a.dtype), a.nbytes])
    head = dict(msg)
    if meta:
        head["_blobs"] = meta
    jb = json.dumps(head, separators=(",", ":")).encode("utf-8")
    payload = b"".join([jb] + parts)
    crc = zlib.crc32(struct.pack("<I", len(jb)) + payload) & 0xFFFFFFFF
    return _FRAME_HDR.pack(_FRAME_MAGIC, crc, len(jb)) + payload


# dstpu: hot-path — the receive side of every message
def decode_frame(buf: bytes) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    if len(buf) < _FRAME_HDR.size:
        raise TransportCorrupt(f"frame truncated: {len(buf)} bytes")
    magic, crc, jlen = _FRAME_HDR.unpack_from(buf, 0)
    if magic != _FRAME_MAGIC:
        raise TransportCorrupt(f"bad frame magic {magic:#x}")
    payload = buf[_FRAME_HDR.size:]
    want = zlib.crc32(struct.pack("<I", jlen) + payload) & 0xFFFFFFFF
    if want != crc:
        raise TransportCorrupt(
            f"frame crc mismatch ({want:#x} != {crc:#x})")
    if jlen > len(payload):
        raise TransportCorrupt("frame header overruns payload")
    try:
        head = json.loads(payload[:jlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise TransportCorrupt(f"frame header undecodable: {e}")
    blobs: List[np.ndarray] = []
    off = jlen
    for shape, dtype, nbytes in head.pop("_blobs", []):
        raw = payload[off:off + nbytes]
        if len(raw) != nbytes:
            raise TransportCorrupt("frame blob overruns payload")
        blobs.append(np.frombuffer(raw, dtype=_np_dtype(dtype))
                     .reshape(shape).copy())
        off += nbytes
    return head, blobs


# --------------------------------------------------------------------
# TierEntry <-> wire: quantized or bit-exact pages, checksums verbatim
# --------------------------------------------------------------------

# dstpu: hot-path — per migrated page on the export leg
def entry_to_wire(entry) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """One host-resident :class:`TierEntry` as (header, blobs).  The
    demote-time per-buffer crc32s ride the header untouched — the
    importer's promotion check, not the wire, is what proves the
    payload."""
    blobs = [np.ascontiguousarray(a) for a in (entry.data or ())]
    meta = {
        "key": entry.key.hex(),
        # dstpu: host-sync-ok: header build — entry fields are host
        # scalars/tuples, the page buffers pass through as raw blobs
        "quantized": bool(entry.quantized),
        "dtype": str(entry.dtype),
        "buffers": [[n, list(s), str(d)] for n, s, d in entry.buffers],
        "nbytes": int(entry.nbytes),
        "tick": int(entry.tick),
        "checksums": (list(map(int, entry.checksums))
                      if entry.checksums is not None else None),
        "nblobs": len(blobs),
    }
    return meta, blobs


# dstpu: hot-path — per migrated page on the admit leg
def entry_from_wire(meta: Dict[str, Any], blobs: Sequence[np.ndarray]):
    """Rebuild a host-resident :class:`TierEntry` from the wire form.
    ``location`` is always ``"host"`` on arrival — whatever tier the
    page came FROM, the copy that crossed the wire lives in memory."""
    from deepspeed_tpu.inference.prefix_cache import TierEntry
    cks = meta.get("checksums")
    return TierEntry(
        key=bytes.fromhex(meta["key"]),
        location="host",
        # dstpu: host-sync-ok: JSON-header coercion — every value here
        # is decoded wire metadata, no device arrays in this function
        quantized=bool(meta["quantized"]),
        dtype=str(meta["dtype"]),
        buffers=tuple((n, tuple(s), d) for n, s, d in meta["buffers"]),
        nbytes=int(meta["nbytes"]),
        data=tuple(blobs) if blobs else None,
        tick=int(meta.get("tick", 0)),
        checksums=tuple(int(c) for c in cks) if cks else None)


def entries_to_frame(entries, extra: Optional[Dict[str, Any]] = None
                     ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Pack N entries into one message: headers in the JSON, every
    buffer flattened into the blob train (each header's ``nblobs``
    tells the decoder where its slice ends)."""
    metas, blobs = [], []
    for e in entries:
        m, bl = entry_to_wire(e)
        metas.append(m)
        blobs.extend(bl)
    msg = dict(extra or {})
    msg["entries"] = metas
    return msg, blobs


def entries_from_frame(msg: Dict[str, Any],
                       blobs: Sequence[np.ndarray]) -> List[Any]:
    out, off = [], 0
    for m in msg.get("entries", []):
        n = int(m.get("nblobs", 0))
        out.append(entry_from_wire(m, blobs[off:off + n]))
        off += n
    return out


# --------------------------------------------------------------------
# shared-memory ring: file-backed mmap, SPSC per direction
# --------------------------------------------------------------------

_SHM_MAGIC = 0x44535452            # "DSTR"
_SHM_HDR = 64                      # magic,slot,nslots,pad + head,tail
_SLOT_HDR = 24                     # seq u64 | total u32 | frag u32 | crc u32 | pad u32
_HEAD_OFF = 16
_TAIL_OFF = 24


class ShmRing:
    """One direction of a same-host pair: fixed-slot mmap ring.

    Single producer, single consumer.  A frame larger than one slot
    fragments across consecutive slots; the producer publishes the
    shared head ONCE after the last fragment, so ``head > tail``
    guarantees the whole frame is readable.  Torn/overwritten slots
    surface as :class:`TransportCorrupt` (sequence or crc mismatch),
    never as silently wrong bytes — and the cursor still advances past
    the bad frame so the stream recovers."""

    def __init__(self, path: str, role: str, *, _create: bool = False,
                 slot_bytes: int = 1 << 14, n_slots: int = 64):
        if role not in ("producer", "consumer"):
            raise ValueError(f"role must be producer|consumer: {role}")
        self.path, self.role = path, role
        self._closed = False
        if _create:
            if slot_bytes <= _SLOT_HDR:
                raise ValueError(f"slot_bytes {slot_bytes} too small")
            size = _SHM_HDR + n_slots * slot_bytes
            with open(path, "wb") as f:
                f.write(b"\0" * size)
            self._f = open(path, "r+b")
            self.mm = mmap.mmap(self._f.fileno(), size)
            struct.pack_into("<IIII", self.mm, 0, _SHM_MAGIC,
                             slot_bytes, n_slots, 0)
        else:
            self._f = open(path, "r+b")
            size = os.fstat(self._f.fileno()).st_size
            self.mm = mmap.mmap(self._f.fileno(), size)
        magic, self.slot_bytes, self.n_slots, _ = struct.unpack_from(
            "<IIII", self.mm, 0)
        if magic != _SHM_MAGIC:
            raise TransportError(f"not a dstpu shm ring: {path}")
        self._cap = self.slot_bytes - _SLOT_HDR
        # each side owns exactly one cursor; the other is read from the
        # map (SPSC — no locks, publication order is the fence)
        self._head = struct.unpack_from("<Q", self.mm, _HEAD_OFF)[0]
        self._tail = struct.unpack_from("<Q", self.mm, _TAIL_OFF)[0]

    @classmethod
    def create(cls, path: str, *, slot_bytes: int = 1 << 14,
               n_slots: int = 64) -> "ShmRing":
        return cls(path, "producer", _create=True,
                   slot_bytes=slot_bytes, n_slots=n_slots)

    @classmethod
    def attach(cls, path: str, role: str) -> "ShmRing":
        return cls(path, role)

    # ------------------------------------------------------------ send
    # dstpu: hot-path — the same-host data plane's write side
    def send_bytes(self, data: bytes,
                   timeout_s: Optional[float] = 5.0) -> None:
        if self._closed:
            raise TransportClosed(f"shm ring {self.path} closed")
        if self.role != "producer":
            raise TransportError("consumer side cannot send")
        need = max(1, -(-len(data) // self._cap))
        if need > self.n_slots:
            raise TransportError(
                f"frame of {len(data)} B needs {need} slots > ring's "
                f"{self.n_slots} — raise transport.slot_bytes/ring_slots")
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        # backpressure: a full ring parks the producer (bounded), it
        # never overwrites unconsumed slots
        while self.n_slots - (self._head - self._read_tail()) < need:
            if deadline is not None and time.monotonic() > deadline:
                raise TransportError(
                    f"shm ring {self.path} full for {timeout_s}s "
                    "(consumer stalled) — backpressure timeout")
            time.sleep(2e-4)
        off = 0
        for i in range(need):
            frag = data[off:off + self._cap]
            off += len(frag)
            base = _SHM_HDR + ((self._head + i) % self.n_slots) \
                * self.slot_bytes
            # publication order IS the torn-write guard: payload, then
            # length/crc, then the sequence word — a reader whose
            # cursor doesn't match seq rejects the slot
            self.mm[base + _SLOT_HDR:base + _SLOT_HDR + len(frag)] = frag
            struct.pack_into("<III", self.mm, base + 8, len(data),
                             len(frag), zlib.crc32(frag) & 0xFFFFFFFF)
            struct.pack_into("<Q", self.mm, base, self._head + i)
        self._head += need
        struct.pack_into("<Q", self.mm, _HEAD_OFF, self._head)

    # ------------------------------------------------------------ recv
    # dstpu: hot-path — the same-host data plane's read side
    def recv_bytes(self, timeout_s: float = 0.0) -> Optional[bytes]:
        if self._closed:
            raise TransportClosed(f"shm ring {self.path} closed")
        if self.role != "consumer":
            raise TransportError("producer side cannot recv")
        deadline = time.monotonic() + max(0.0, timeout_s)
        while self._read_head() <= self._tail:
            if time.monotonic() >= deadline:
                return None
            time.sleep(2e-4)
        base = self._slot_base(self._tail)
        seq, total, _frag, _crc = struct.unpack_from(
            "<QIII", self.mm, base)
        if seq != self._tail:
            # a slot whose sequence lags the cursor was torn or never
            # fully published; skip it so the stream can recover
            self._advance(1)
            raise TransportCorrupt(
                f"shm ring {self.path}: torn slot (seq {seq} != "
                f"cursor {self._tail - 1})")
        need = max(1, -(-total // self._cap))
        if need > self.n_slots:
            self._advance(1)
            raise TransportCorrupt(
                f"shm ring {self.path}: implausible frame length "
                f"{total}")
        parts: List[bytes] = []
        for i in range(need):
            b = self._slot_base(self._tail + i)
            seq_i, total_i, frag_i, crc_i = struct.unpack_from(
                "<QIII", self.mm, b)
            frag = bytes(self.mm[b + _SLOT_HDR:b + _SLOT_HDR + frag_i])
            if (seq_i != self._tail + i or total_i != total
                    or frag_i > self._cap
                    or (zlib.crc32(frag) & 0xFFFFFFFF) != crc_i):
                self._advance(need)
                raise TransportCorrupt(
                    f"shm ring {self.path}: fragment {i}/{need} failed "
                    "seq/crc verification (torn or corrupted write)")
            parts.append(frag)
        self._advance(need)
        data = b"".join(parts)
        if len(data) != total:
            raise TransportCorrupt(
                f"shm ring {self.path}: reassembled {len(data)} B != "
                f"declared {total}")
        return data

    # --------------------------------------------------------- plumbing
    def _slot_base(self, seq: int) -> int:
        return _SHM_HDR + (seq % self.n_slots) * self.slot_bytes

    def _advance(self, n: int) -> None:
        self._tail += n
        struct.pack_into("<Q", self.mm, _TAIL_OFF, self._tail)

    def _read_head(self) -> int:
        return struct.unpack_from("<Q", self.mm, _HEAD_OFF)[0]

    def _read_tail(self) -> int:
        return struct.unpack_from("<Q", self.mm, _TAIL_OFF)[0]

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.mm.close()
            self._f.close()
        except Exception:
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class ShmEndpoint:
    """Duplex same-host endpoint: two SPSC rings, one per direction."""

    kind = "shm"

    def __init__(self, tx: ShmRing, rx: ShmRing):
        self.tx, self.rx = tx, rx

    def send_bytes(self, data: bytes, timeout_s=5.0) -> None:
        self.tx.send_bytes(data, timeout_s=timeout_s)

    def recv_bytes(self, timeout_s: float = 0.0) -> Optional[bytes]:
        return self.rx.recv_bytes(timeout_s=timeout_s)

    def close(self, unlink: bool = False) -> None:
        self.tx.close(unlink=unlink)
        self.rx.close(unlink=unlink)


# --------------------------------------------------------------------
# TCP: length-prefixed frames on a stream socket
# --------------------------------------------------------------------

class TcpEndpoint:
    """Duplex general-path endpoint: ``[u32 length]``-prefixed frames
    on one TCP connection (TCP_NODELAY — frames are latency-bound
    control messages or already-batched page trains)."""

    kind = "tcp"

    def __init__(self, sock: socket.socket):
        self.sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._closed = False

    # dstpu: hot-path — the general-path data plane's write side
    def send_bytes(self, data: bytes, timeout_s=5.0) -> None:
        if self._closed:
            raise TransportClosed("tcp endpoint closed")
        try:
            self.sock.settimeout(timeout_s)
            self.sock.sendall(struct.pack("<I", len(data)) + data)
        except socket.timeout:
            raise TransportError(f"tcp send timed out after {timeout_s}s")
        except OSError as e:
            raise TransportClosed(f"tcp send failed: {e}")

    def _recv_exact(self, n: int, deadline: float) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            rem = deadline - time.monotonic()
            if rem <= 0:
                return None
            try:
                self.sock.settimeout(min(rem, 0.5))
                chunk = self.sock.recv(n - len(buf))
            except socket.timeout:
                continue
            except OSError as e:
                raise TransportClosed(f"tcp recv failed: {e}")
            if not chunk:
                raise TransportClosed("tcp peer closed the stream")
            buf.extend(chunk)
        return bytes(buf)

    # dstpu: hot-path — the general-path data plane's read side
    def recv_bytes(self, timeout_s: float = 0.0) -> Optional[bytes]:
        if self._closed:
            raise TransportClosed("tcp endpoint closed")
        deadline = time.monotonic() + max(1e-4, timeout_s)
        hdr = self._recv_exact(4, deadline)
        if hdr is None:
            return None
        (n,) = struct.unpack("<I", hdr)
        # the header committed us to a frame: wait out the body past the
        # soft timeout rather than desynchronize the stream
        body = self._recv_exact(n, time.monotonic() + 10.0)
        if body is None:
            raise TransportError(
                f"tcp frame truncated mid-body ({n} B promised)")
        return body

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class TcpListener:
    """Ephemeral-port listener for a replica child's transport plane."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(4)
        self.host, self.port = self.sock.getsockname()[:2]

    def accept(self, timeout_s: float = 10.0) -> TcpEndpoint:
        self.sock.settimeout(timeout_s)
        try:
            conn, _ = self.sock.accept()
        except socket.timeout:
            raise TransportError(
                f"no transport connection within {timeout_s}s")
        return TcpEndpoint(conn)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def connect_tcp(host: str, port: int, *, attempts: int = 5,
                backoff_s: float = 0.05,
                timeout_s: float = 5.0) -> TcpEndpoint:
    """Dial a replica child's transport port, retrying with backoff
    (the child may still be binding when the parent reads the
    handshake)."""
    def dial():
        s = socket.create_connection((host, port), timeout=timeout_s)
        return TcpEndpoint(s)
    return _faults.retry_with_backoff(
        dial, attempts=attempts, backoff_s=backoff_s,
        retry_on=(OSError,))


# --------------------------------------------------------------------
# Channel: framed messages + faults + metrics + RPC over any endpoint
# --------------------------------------------------------------------

class Channel:
    """One peer-pair message channel over an :class:`ShmEndpoint` or
    :class:`TcpEndpoint`.

    Injected ``transport`` fault rules hook three keys per peer:
    ``send:<peer>`` / ``recv:<peer>`` (latency rules sleep, error
    rules raise :class:`TransportError` — the reconnect/backoff path)
    and ``corrupt:<peer>`` (one byte of the encoded frame flips AFTER
    the crc was computed, so the receiver must detect it).  A
    ``reconnect`` callable makes send-side endpoint failures retriable
    through :func:`~deepspeed_tpu.faults.retry_with_backoff`."""

    def __init__(self, endpoint, peer: str = "peer", *,
                 registry: Optional[MetricsRegistry] = None,
                 reconnect: Optional[Callable[[], Any]] = None,
                 io_timeout_s: float = 5.0,
                 reconnect_attempts: int = 3,
                 reconnect_backoff_s: float = 0.05):
        self.endpoint = endpoint
        self.peer = peer
        self.reconnect = reconnect
        self.io_timeout_s = float(io_timeout_s)
        self.reconnect_attempts = int(reconnect_attempts)
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self._seq = 0
        r = registry if registry is not None \
            else MetricsRegistry(enabled=False)
        self._c_tx = r.counter("transport_tx_frames",
                               "frames sent on this peer channel")
        self._c_rx = r.counter("transport_rx_frames",
                               "frames received on this peer channel")
        self._c_txb = r.counter("transport_tx_bytes",
                                "payload bytes sent (frame-encoded)")
        self._c_rxb = r.counter("transport_rx_bytes",
                                "payload bytes received")
        self._c_corrupt = r.counter(
            "transport_corrupt_frames",
            "frames rejected by crc/sequence verification")
        self._c_reconnects = r.counter(
            "transport_reconnects",
            "endpoint re-dials after a send-side failure")
        self._c_injected = r.counter(
            "transport_injected_faults",
            "transport fault rules that fired on this channel")
        self._h_rpc = r.histogram(
            "transport_rpc_seconds",
            "request() round trips on this channel")

    # ------------------------------------------------------------ send
    # dstpu: hot-path — every control message and page train
    def send(self, msg: Dict[str, Any],
             blobs: Sequence[np.ndarray] = ()) -> None:
        delay, err = _faults.poll("transport", f"send:{self.peer}")
        if delay:
            self._c_injected.inc()
            time.sleep(delay)
        if err is not None:
            self._c_injected.inc()
            raise TransportError(
                f"injected transport send failure ({self.peer})")
        frame = encode_frame(msg, blobs)
        _d, corrupt = _faults.poll("transport", f"corrupt:{self.peer}")
        if corrupt is not None:
            # flip one payload byte AFTER the crc was stamped: the
            # receiver's decode_frame must catch it — this is the
            # injected analogue of a torn DMA or flaky NIC
            self._c_injected.inc()
            fb = bytearray(frame)
            fb[-1] ^= 0xFF
            frame = bytes(fb)
        try:
            self.endpoint.send_bytes(frame, timeout_s=self.io_timeout_s)
        except TransportClosed:
            if self.reconnect is None:
                raise
            self._redial()
            self.endpoint.send_bytes(frame, timeout_s=self.io_timeout_s)
        self._c_tx.inc()
        self._c_txb.inc(len(frame))

    def _redial(self) -> None:
        def again():
            ep = self.reconnect()
            if ep is None:
                raise TransportError(f"reconnect to {self.peer} failed")
            return ep
        logger.warning("transport: channel to %s dropped — redialing",
                       self.peer)
        self.endpoint = _faults.retry_with_backoff(
            again, attempts=self.reconnect_attempts,
            backoff_s=self.reconnect_backoff_s)
        self._c_reconnects.inc()

    # ------------------------------------------------------------ recv
    # dstpu: hot-path — the receive side of every message
    def recv(self, timeout_s: float = 0.0
             ) -> Optional[Tuple[Dict[str, Any], List[np.ndarray]]]:
        delay, err = _faults.poll("transport", f"recv:{self.peer}")
        if delay:
            self._c_injected.inc()
            time.sleep(delay)
        if err is not None:
            self._c_injected.inc()
            raise TransportError(
                f"injected transport recv failure ({self.peer})")
        buf = self.endpoint.recv_bytes(timeout_s=timeout_s)
        if buf is None:
            return None
        try:
            msg, blobs = decode_frame(buf)
        except TransportCorrupt:
            self._c_corrupt.inc()
            raise
        self._c_rx.inc()
        self._c_rxb.inc(len(buf))
        return msg, blobs

    # ------------------------------------------------------------- rpc
    def request(self, msg: Dict[str, Any],
                blobs: Sequence[np.ndarray] = (),
                timeout_s: float = 10.0
                ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        """Client-side RPC: stamp a sequence number, send, wait for
        the matching reply.  Replies carrying an older sequence (a
        previously timed-out call finally answered) are drained and
        dropped — the stream never desynchronizes."""
        self._seq += 1
        seq = self._seq
        t0 = time.perf_counter()
        self.send(dict(msg, _seq=seq), blobs)
        deadline = time.monotonic() + timeout_s
        while True:
            rem = deadline - time.monotonic()
            if rem <= 0:
                raise TransportError(
                    f"rpc {msg.get('op')!r} to {self.peer} timed out "
                    f"after {timeout_s}s")
            got = self.recv(timeout_s=min(rem, 0.25))
            if got is None:
                continue
            rmsg, rblobs = got
            if rmsg.get("_seq") != seq:
                continue            # stale reply from a timed-out call
            self._h_rpc.observe(time.perf_counter() - t0)
            return rmsg, rblobs

    def close(self, **kw) -> None:
        try:
            self.endpoint.close(**kw)
        except TypeError:
            self.endpoint.close()
        except Exception:
            pass


# --------------------------------------------------------------------
# pair construction: what proc_fleet/replica_child use to wire a peer
# --------------------------------------------------------------------

def create_shm_pair(dirpath: str, name: str, *,
                    slot_bytes: int = 1 << 14,
                    n_slots: int = 64) -> Tuple[str, str]:
    """Create the two ring files for one parent<->child pair and
    return ``(c2s_path, s2c_path)`` — client-to-server and back.  The
    CREATOR initializes both; each side attaches with its own role."""
    c2s = os.path.join(dirpath, f"{name}.c2s.ring")
    s2c = os.path.join(dirpath, f"{name}.s2c.ring")
    for p in (c2s, s2c):
        ShmRing.create(p, slot_bytes=slot_bytes, n_slots=n_slots).close()
    return c2s, s2c


def attach_shm_pair(c2s: str, s2c: str, side: str) -> ShmEndpoint:
    """Attach one side of a ring pair: the ``"client"`` produces into
    c2s and consumes s2c; the ``"server"`` mirrors it."""
    if side == "client":
        return ShmEndpoint(tx=ShmRing.attach(c2s, "producer"),
                           rx=ShmRing.attach(s2c, "consumer"))
    if side == "server":
        return ShmEndpoint(tx=ShmRing.attach(s2c, "producer"),
                           rx=ShmRing.attach(c2s, "consumer"))
    raise ValueError(f"side must be client|server: {side}")
