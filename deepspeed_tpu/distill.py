"""Knowledge distillation (ref: the DeepSpeed compression suite's KD
flow — deepspeed/compression/ is used with a teacher-student soft-label
loss in the reference's Model-Compression recipes; layer_reduction's
``teacher_layer`` exists precisely to initialize a student from teacher
layers before distilling).

TPU design: the teacher is a PURE function + param pytree traced into
the SAME jitted loss as the student under ``stop_gradient`` — no second
engine, no host round-trip for teacher logits; XLA overlaps the teacher
forward with the student forward inside one program, and the teacher
params ride along as ordinary (frozen) jit constants exactly like
LoRA's frozen base (lora.py).

Loss (Hinton et al., the reference recipes' formulation):

    L = (1 - alpha) * CE(student, targets)
      + alpha * T^2 * KL(softmax(teacher/T) || softmax(student/T))

The T^2 factor keeps soft-gradient magnitudes comparable across
temperatures.  All soft-label math runs in f32.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def kd_kl_loss(student_logits, teacher_logits, temperature: float = 1.0,
               mask=None) -> jnp.ndarray:
    """Masked mean KL(teacher || student) at ``temperature``, scaled by
    T^2.  logits: [..., V]; mask broadcasts over the leading dims."""
    t = jnp.float32(temperature)
    slog = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t,
                              axis=-1)
    tlog = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / t,
                              axis=-1)
    tp = jnp.exp(tlog)
    kl = jnp.sum(tp * (tlog - slog), axis=-1)      # [...positions]
    if mask is None:
        return jnp.mean(kl) * t * t
    from deepspeed_tpu.ops.losses import _masked_mean

    return _masked_mean(kl.reshape(-1), mask.reshape(-1)) * t * t


def distillation_loss(student_logits, teacher_logits, targets, *,
                      alpha: float = 0.5, temperature: float = 1.0,
                      mask=None):
    """Combined hard-CE + soft-KL loss.  Returns (loss, aux dict with
    ``hard_loss`` and ``kd_loss``).  The teacher term carries no
    gradient (stop_gradient on the teacher logits)."""
    logp = jax.nn.log_softmax(student_logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        hard = jnp.mean(nll)
    else:
        from deepspeed_tpu.ops.losses import _masked_mean

        hard = _masked_mean(nll.reshape(-1), mask.reshape(-1))
    soft = kd_kl_loss(student_logits,
                      jax.lax.stop_gradient(teacher_logits),
                      temperature=temperature, mask=mask)
    loss = (1.0 - alpha) * hard + alpha * soft
    return loss, {"hard_loss": hard, "kd_loss": soft}


class Distiller:
    """Wraps a student forward into an engine-ready distillation loss.

    ``teacher_fn(teacher_params, tokens) -> logits`` is traced into the
    student's jitted step under stop_gradient; ``teacher_params`` are
    captured as frozen constants.
    """

    def __init__(self, teacher_fn: Callable, teacher_params: Any,
                 alpha: float = 0.5, temperature: float = 2.0):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        if temperature <= 0:
            raise ValueError(f"temperature must be > 0, got {temperature}")
        self.teacher_fn = teacher_fn
        # jnp leaves: numpy teacher params would fail on tracer indexing
        # (np_embed[traced_tokens]) when traced into the student's jit
        self.teacher_params = jax.tree.map(jnp.asarray, teacher_params)
        self.alpha = float(alpha)
        self.temperature = float(temperature)

    def loss_fn(self, student_fn: Callable,
                has_aux: bool = False) -> Callable:
        """``student_fn(params, tokens) -> logits`` → engine loss_fn over
        ``batch = {tokens, (loss_mask)}`` (next-token LM convention:
        inputs tokens[:, :-1], targets tokens[:, 1:])."""

        def f(params, batch):
            tokens = batch["tokens"]
            inputs, targets = tokens[:, :-1], tokens[:, 1:]
            mask = batch.get("loss_mask")
            if mask is not None:
                mask = mask[:, 1:]
            s_logits = student_fn(params, inputs)
            t_logits = self.teacher_fn(self.teacher_params, inputs)
            loss, aux = distillation_loss(
                s_logits, t_logits, targets, alpha=self.alpha,
                temperature=self.temperature, mask=mask)
            return (loss, aux) if has_aux else loss

        return f


def init_distillation(config: Any, teacher_fn: Callable,
                      teacher_params: Any) -> Optional[Distiller]:
    """Build a Distiller from the ``compression_training.
    knowledge_distillation`` block ({enabled, alpha, temperature});
    None when absent/disabled."""
    if hasattr(config, "raw"):
        config = config.raw
    kd = (config.get("compression_training", {})
          .get("knowledge_distillation", {}))
    if not kd.get("enabled"):
        return None
    return Distiller(teacher_fn, teacher_params,
                     alpha=float(kd.get("alpha", 0.5)),
                     temperature=float(kd.get("temperature", 2.0)))
