"""ZeRO-Infinity parameter offload: layer-streamed training where the
bf16 compute params NEVER fully reside in HBM.

Reference: deepspeed/runtime/swap_tensor/partitioned_param_swapper.py
(AsyncPartitionedParameterSwapper) + zero/stage3.py's parameter
prefetch/release around each submodule — the other half of
ZeRO-Infinity, which is what makes 100B+ models fit: params as well as
optimizer state swap between NVMe/host and the accelerator, with a
working set of O(layers-in-flight), not O(model).

TPU design.  The reference hooks torch submodule pre/post-forward to
fault params in and release them.  Under XLA there are no hooks inside a
compiled program, so the schedule is HOST-side and the programs are
per-LAYER jits (compiled once each, reused for every layer — all layers
share shapes):

    stem:      (stem_params, batch) -> x0            [embed; resident]
    block:     (layer_params, x) -> x                [one transformer layer]
    head_grad: (head_params, xL, batch) -> loss, dhead, dxL
    block_vjp: (layer_params, x_in, dy) -> (dlayer_grads, dx)
    stem_vjp:  (stem_params, batch, dx0) -> dstem

Forward streams layer k+1's bf16 params host→device (aio read + async
device_put) while layer k computes; the backward streams them again in
reverse order (params transit the link twice per step — same as the
reference's swap-in for backward).  Layer-boundary activations are kept
in HBM (one [B, T, d] per layer — the layer-granular remat the reference
gets from activation checkpointing).  Peak param HBM = 2 layers (the
double buffer), so the trainable size is bounded by host/NVMe capacity
and step time by link bandwidth — not by the 2N bf16 residency that caps
:class:`~deepspeed_tpu.infinity.InfinityEngine` at ~HBM/2.

Gradients land in pinned host f32 buffers as the backward drains them
(device→host overlaps the next layer's vjp); each layer's finite check
and grad-norm contribution are computed in the drain worker, hidden
behind the next layer's vjp.  By default (``offload_param.overlap_step``,
on unless gradient clipping needs the global norm first) layer ``l``'s
fused C++ CPU-Adam update (ops/cpu_adam.py) launches the moment its
grads finish draining, so the optimizer pass and tier writes overlap the
vjps of layers ``l-1..0`` — the analogue of the reference overlapping
``swap_out_and_release`` with backward compute.  Updates run on a
dedicated worker with their OWN aio channel (per-key tier files make
concurrent access to distinct keys safe; the read/write slot state of an
aio channel is single-thread only).

Overflow semantics: a nonfinite LOSS (the overwhelmingly common case —
bf16 shares f32's exponent range, so compute overflow propagates to the
loss) is detected before the backward starts and skips the whole step
exactly, updates never launched.  The pathological remainder — a
nonfinite grad under a finite loss — raises ``FloatingPointError`` in
overlapped mode (earlier layers have already committed their update);
set ``offload_param.overlap_step: false`` to restore the reference's
strict whole-step skip at the cost of serializing the optimizer pass
after the backward.

Multi-process: each process stores a contiguous 1/process_count row
slice of every block leaf's f32 master/moment state (the per-process
row IO analogue of the optimizer-only engine's [dp, chunk] partition),
so the 12-byte/param state footprint splits across hosts.  Grads are
replicated across processes (the data axis spans them, XLA's psum makes
every drained grad global), updates run on the local rows only, and the
fresh bf16 image is re-assembled with a per-leaf cross-process
all-gather.  Collective ordering requires the strict update path, so
``overlap_step`` is forced off when ``process_count > 1`` (the stem/
head state is small and updated redundantly on every process — zero
communication, deterministic).  The bf16 compute image on the tier
stays full per process (it is what streams to the local devices).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu import lr_schedules, precision
from deepspeed_tpu.config import Config
from deepspeed_tpu.infinity import _NvmeTier, _RamTier, _Tier
from deepspeed_tpu.ops.optim import default_lr
from deepspeed_tpu.telemetry import MetricsRegistry
from deepspeed_tpu.topology import MeshSpec
from deepspeed_tpu.utils.logging import logger


class TierLayerReader:
    """Double-buffered tier→device per-layer read pipeline.

    The streaming core shared by the training :class:`ParamStreamEngine`
    and the ZeRO-Inference serving streamer
    (:mod:`deepspeed_tpu.inference.zero_inference`): while the caller
    computes on layer ``order[i]``, layer ``order[i+1]``'s tier reads
    (NVMe: aio submits on the alternating read slots; RAM: host buffers)
    and its async H2D upload are already in flight, so the link hides
    behind compute and the device-side working set stays
    O(``depth`` + 1) layers instead of O(model).

    ``names_fn(l)`` → the tier keys of layer ``l``'s leaves; ``shapes``/
    ``dtypes`` align with those keys; ``to_device(bufs, l)`` turns the
    fenced host buffers into the device tree handed to the caller (the
    device_put — and any TP resharding — lives there).  NVMe tiers pin
    ``depth`` to 1: the alternating aio read slots hold exactly one
    layer's reads in flight (the double buffer).  The RAM tier accepts
    deeper prefetch — device_puts are async, so up to ``depth`` layer
    uploads ride the link ahead of the one being consumed.
    """

    def __init__(self, tier: _Tier, names_fn: Callable[[int], List[str]],
                 shapes, dtypes, to_device, depth: int = 1,
                 registry=None, prefix: str = "tier_reader",
                 tracer=None, retries: int = 2,
                 retry_backoff_s: float = 0.05):
        from deepspeed_tpu import request_trace as _request_trace
        from deepspeed_tpu import telemetry as _telemetry

        self.tier = tier
        # flight-recorder hookup: fetch issue/arrive/stall events under
        # `{prefix}_` phases — the per-layer timeline the hit/stall
        # COUNTERS above summarize.  No tracer → shared no-op.
        self._tracer = (tracer if tracer is not None
                        else _request_trace.NULL_TRACER)
        self._trace_on = self._tracer.enabled
        self._prefix = prefix
        self._nvme = isinstance(tier, _NvmeTier)
        self.names_fn = names_fn
        self.shapes = list(shapes)
        self.dtypes = list(dtypes)
        self.to_device = to_device
        self.depth = 1 if self._nvme else max(1, int(depth))
        # NVMe prefetch effectiveness: a HIT means the layer's reads had
        # already landed when the sweep reached it (fence was free)
        self.hits = 0
        self.stalls = 0
        # graceful degradation of the read path: a failed fence
        # resubmits the item's reads up to `retries` times (exponential
        # backoff), then falls over to the tier's synchronous read
        # (bypassing aio), and only then raises a structured fatal —
        # AFTER dumping a flight-recorder postmortem
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.io_retries = 0
        self.sync_fallbacks = 0
        # optional MetricsRegistry fan-out (prefetch hit/stall counters,
        # bytes read off the tier, fence-wait distribution); with no
        # registry the handles are shared no-ops — zero branches on the
        # sweep path either way
        self._layer_bytes = int(sum(
            int(np.prod(s)) * np.dtype(d).itemsize
            for s, d in zip(self.shapes, self.dtypes)))
        if registry is None or not registry.enabled:
            null = _telemetry.NULL_METRIC
            self._c_hits = self._c_stalls = self._c_bytes = null
            self._h_wait = null
            self._c_retries = self._c_sync_fb = null
        else:
            self._c_retries = registry.counter(
                f"{prefix}_io_retries",
                "tier-read fences retried after a transient aio error")
            self._c_sync_fb = registry.counter(
                f"{prefix}_sync_fallbacks",
                "tier reads served by the synchronous fallback after "
                "aio retries exhausted (degraded but correct)")
            self._c_hits = registry.counter(
                f"{prefix}_prefetch_hits",
                "layer reads already landed when the sweep arrived")
            self._c_stalls = registry.counter(
                f"{prefix}_prefetch_stalls",
                "sweep reached a layer with reads still in flight")
            self._c_bytes = registry.counter(
                f"{prefix}_bytes_read", "bytes read off the tier")
            self._h_wait = registry.histogram(
                f"{prefix}_wait_seconds",
                "time blocked on a tier fence (exposed IO cost)")

    def _meta(self, l: int):
        """``(names, shapes, dtypes, nbytes)`` of item ``l``'s tier
        reads.  The default geometry is FIXED across items (every layer
        shares shapes); subclasses with per-item geometry — the KV-page
        promotion reader, whose items are groups of pages that may mix
        quantized/plain encodings — override this one hook and inherit
        the whole double-buffered pipeline."""
        return self.names_fn(l), self.shapes, self.dtypes, \
            self._layer_bytes

    def _submit(self, l: int):
        names, shapes, dtypes, nbytes = self._meta(l)
        if self._trace_on:
            self._tracer.event(f"{self._prefix}_fetch_issue", attrs={
                "layer": l, "bytes": nbytes})
        return [self.tier.get_submit(n, s, d)
                for n, s, d in zip(names, shapes, dtypes)]

    # dstpu: hot-path
    def _fence_retry(self, l: int, pending):
        """Fence item ``l``'s reads with graceful degradation: a
        transient IO failure resubmits the item's reads (bounded,
        exponential backoff); exhausted retries fall over to the
        tier's synchronous ``read_sync`` path (aio bypassed, degraded
        but correct); if that too fails — or the tier has no sync
        path — a flight-recorder postmortem is dumped and a structured
        :class:`~deepspeed_tpu.faults.FatalStreamError` raised.
        Returns the VALID buffers (resubmits replace ``pending``)."""
        from deepspeed_tpu.faults import FatalStreamError

        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                self.tier.fence_reads()
                return pending
            except (IOError, OSError) as e:
                last = e
                if attempt >= self.retries:
                    break
                self.io_retries += 1
                self._c_retries.inc()
                logger.warning(
                    "%s: tier fence failed (%s) — retry %d/%d",
                    self._prefix, e, attempt + 1, self.retries)
                if self.retry_backoff_s:
                    time.sleep(self.retry_backoff_s * (2 ** attempt))
                pending = self._submit(l)
        read_sync = getattr(self.tier, "read_sync", None)
        if read_sync is not None:
            try:
                names, shapes, dtypes, _nb = self._meta(l)
                bufs = [read_sync(n, s, d)
                        for n, s, d in zip(names, shapes, dtypes)]
                self.sync_fallbacks += 1
                self._c_sync_fb.inc()
                logger.warning(
                    "%s: aio retries exhausted for item %s — served by "
                    "synchronous fallback reads", self._prefix, l)
                if self._trace_on:
                    self._tracer.event(
                        f"{self._prefix}_sync_fallback",
                        attrs={"layer": l})
                return bufs
            except Exception as e:
                last = e
        from deepspeed_tpu import faults as _faults_mod

        paths = _faults_mod.guarded_postmortem(
            f"{self._prefix}_stream_fatal")
        raise FatalStreamError(
            f"{self._prefix}: tier read of item {l} failed after "
            f"{self.retries} retries and the synchronous fallback "
            f"({last!r}); flight-recorder postmortem: "
            f"{paths or 'no recorder live'}", postmortem_paths=paths)

    # dstpu: hot-path
    def presubmit(self, l: int):
        """Submit item ``l``'s tier reads NOW, outside the sweep
        generator (generators are lazy — the first ``_submit`` would
        otherwise wait for the first ``next()``), and return the
        pending buffers; hand them to :meth:`sweep` via ``primed=`` so
        consumption continues the pipeline.  The KV promotion path uses
        this to start an admission's NVMe reads at admission time, so
        they overlap every step the engine runs before the first
        suffix-prefill chunk needs the pages."""
        return self._submit(l)

    # dstpu: hot-path
    def sweep(self, order, on_wait=None, primed=None):
        """Yield ``(l, device_tree)`` over ``order`` with the next
        layer's reads/upload in flight; ``on_wait(seconds)`` reports
        time blocked on a fence (the exposed — non-hidden — IO cost).
        ``primed``: buffers from :meth:`presubmit` of ``order[0]``."""
        order = list(order)
        if not order:
            return
        if self._nvme:
            pending = primed if primed is not None \
                else self._submit(order[0])
            for i, l in enumerate(order):
                hit = self.tier.reads_pending() == 0
                if hit:
                    self.hits += 1
                    self._c_hits.inc()
                else:
                    self.stalls += 1
                    self._c_stalls.inc()
                t0 = time.perf_counter()
                pending = self._fence_retry(l, pending)
                dt = time.perf_counter() - t0
                self._h_wait.observe(dt)
                if on_wait is not None:
                    on_wait(dt)
                if self._trace_on:
                    # a stall's blocked interval renders as a slice in
                    # the Chrome export; a hit is a point arrival
                    if hit:
                        self._tracer.event(
                            f"{self._prefix}_fetch_arrive",
                            attrs={"layer": l})
                    else:
                        self._tracer.event(
                            f"{self._prefix}_stall",
                            attrs={"layer": l, "wait_s": dt})
                self.tier.next_read_slot()
                self._c_bytes.inc(self._meta(l)[3])
                bufs = pending
                if i + 1 < len(order):
                    pending = self._submit(order[i + 1])
                yield l, self.to_device(bufs, l)
            return
        ready: collections.deque = collections.deque()
        idx = 0

        def pump():
            # ready never exceeds `depth`: with the layer in use that
            # caps the device working set at depth + 1 layer trees
            nonlocal idx
            while idx < len(order) and len(ready) < self.depth:
                nxt = order[idx]
                idx += 1
                self._c_bytes.inc(self._meta(nxt)[3])
                ready.append((nxt, self.to_device(self._submit(nxt), nxt)))

        pump()
        while ready:
            l, tree = ready.popleft()
            pump()            # next uploads dispatch before l's compute
            yield l, tree


class TierPageReader(TierLayerReader):
    """Double-buffered tier→HBM promotion pipeline for demoted KV
    pages, sharing the :class:`TierLayerReader` core.

    Where the layer reader's items are transformer layers, this
    reader's items are GROUPS of demoted pages (``group_pages`` per
    item): while group ``g`` is being fenced, dequantized and uploaded
    into its freshly allocated HBM pages (the ``to_device`` callback —
    the serving engine's batched page scatter), group ``g+1``'s tier
    reads are already in flight — NVMe aio on the pool's alternating
    slots, or zero-copy host arrays that fence for free.  A 100k-token
    promoted prefix therefore streams at link speed instead of paying
    one exposed read per page.

    ``pool`` is a :class:`~deepspeed_tpu.inference.kv_tier.KVTierPool`
    (the ``_Tier`` read interface plus per-entry geometry); per-item
    shapes come from the pool's entry records via the ``_meta`` hook,
    since a group may mix 2-buffer bit-exact and 4-buffer quantized
    encodings.  ONE reader streams through a pool at a time — the
    engine serializes admissions with tier hits."""

    def __init__(self, pool, keys, to_device, group_pages: int = 8,
                 registry=None, prefix: str = "kv_tier", tracer=None,
                 retries: int = 2, retry_backoff_s: float = 0.05):
        group_pages = max(1, int(group_pages))
        self._pool = pool
        self._groups = [list(keys[i:i + group_pages])
                        for i in range(0, len(keys), group_pages)]
        super().__init__(pool, names_fn=lambda g: [], shapes=(),
                         dtypes=(), to_device=to_device, depth=1,
                         registry=registry, prefix=prefix, tracer=tracer,
                         retries=retries,
                         retry_backoff_s=retry_backoff_s)
        # always the aio-style submit/fence path: host-resident entries
        # report zero pending reads, so they fence free and count as
        # prefetch hits — one pipeline serves mixed host/NVMe chains
        self._nvme = True
        self.depth = 1

    @property
    def n_groups(self) -> int:
        return len(self._groups)

    def group_keys(self, g: int):
        return self._groups[g]

    def _meta(self, g: int):
        names, shapes, dtypes = [], [], []
        nbytes = 0
        for key in self._groups[g]:
            n, s, d = self._pool.entry_meta(key)
            names += n
            shapes += list(s)
            dtypes += list(d)
            nbytes += sum(int(np.prod(sh)) * np.dtype(dt).itemsize
                          for sh, dt in zip(s, d))
        return names, shapes, dtypes, int(nbytes)


@dataclasses.dataclass
class LayeredModel:
    """A model factored for layer streaming.

    ``stem_fn(stem_params, batch) -> x`` (embedding / input projection),
    ``block_fn(layer_params, x) -> x`` (ONE layer; all layers share
    shapes), ``head_fn(head_params, x, batch) -> scalar f32 loss``.
    ``blocks`` is the stacked [L, ...] pytree; stem/head stay resident.
    Models provide builders (e.g. ``models.llama.layered_model``).
    """
    stem_fn: Callable
    block_fn: Callable
    head_fn: Callable
    stem: Any
    blocks: Any
    head: Any
    n_layers: int
    # optional: (stem, blocks, head) -> the ORIGINAL param-tree layout,
    # so master_params() round-trips into init_params-shaped models
    assemble: Optional[Callable] = None
    # True: block_fn returns (x, aux_scalar) — per-layer auxiliary loss
    # terms (MoE load-balance + z losses) that ADD to the total loss;
    # the backward pulls cotangent 1.0 on each layer's aux output, so
    # router gradients flow exactly as in the fused training step
    block_has_aux: bool = False
    # optional: original-layout PartitionSpec tree -> (stem_specs,
    # blocks_specs, head_specs), the same split as the param factoring —
    # lets initialize(param_specs=...) compose TP with layer streaming
    # (blocks_specs are STACKED-layout: dim 0 is the layer axis)
    factor_specs: Optional[Callable] = None
    # lazy blocks init (the host-side analogue of zero.Init, ref:
    # deepspeed.zero.Init partitioned construction): ``blocks`` may be a
    # CALLABLE ``blocks(l) -> per-layer pytree`` instead of a stacked
    # tree; then ``blocks_spec`` must give the stacked abstract shapes
    # (pytree of ShapeDtypeStruct with a leading [L] dim).  Only one
    # layer is ever materialized outside the engine's tier, so a model
    # whose FULL host image would not fit in RAM can still stream-init.
    blocks_spec: Any = None


class ParamStreamEngine:
    """Host-scheduled layer-streaming engine (params + optimizer state
    offloaded; HBM holds a 2-layer param working set + activations)."""

    def __init__(self, layered: LayeredModel, config: Config,
                 mesh: Optional[MeshSpec] = None, lr_scheduler=None,
                 param_specs=None):
        self.config = config
        self.mesh = mesh or MeshSpec.build(
            config.mesh.axis_sizes(jax.device_count()))
        config.resolve_batch_sizes(self.mesh.size("data"))
        self._pc = jax.process_count()
        self._pid = jax.process_index()
        self.layered = layered
        self.L = layered.n_layers
        self._last_grad_norm = 0.0     # TrainingEngine pre-step parity
        # seqlen curriculum (ref: engine.curriculum_scheduler + megatron
        # truncation): same batch preprocessing as TrainingEngine — the
        # layer jits compile once per quantized curriculum length, the
        # identical trade the monolithic step makes
        self.curriculum_scheduler = None
        if config.curriculum is not None and config.curriculum.enabled:
            from deepspeed_tpu.data.curriculum import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(
                config.curriculum)
        self._specs = None
        if param_specs is not None:
            if layered.factor_specs is None:
                raise ValueError(
                    "param_specs given but this LayeredModel has no "
                    "factor_specs hook mapping the original-layout specs "
                    "onto the factored stem/blocks/head layout")
            self._specs = layered.factor_specs(param_specs)

        off = dict(config.zero.offload_param or {})
        opt_off = config.zero.offload_optimizer or {}
        self.device_tier = off.get("device", "cpu")
        # overlap_step: launch layer l's CPU-Adam as soon as its grads
        # drain, behind the remaining vjps.  Clipping forces the strict
        # path — the global norm isn't known until every grad is home.
        # Multi-process also forces strict: the update path all-gathers
        # the fresh bf16 image, and cross-process collectives must be
        # enqueued in identical order on every process, which an update
        # worker racing the vjp launches cannot guarantee.
        self.overlap_step = bool(off.get("overlap_step", True)) and not (
            config.gradient_clipping and config.gradient_clipping > 0
        ) and self._pc == 1
        if self._pc > 1 and off.get("overlap_step", True):
            logger.info("param-stream: overlap_step disabled under "
                        "process_count=%d (collective ordering)", self._pc)
        if self.device_tier == "nvme":
            # per-process subdir (like infinity.py's tiers): each
            # process's tier holds a DIFFERENT row-partition of the
            # master state, so co-hosted processes sharing an nvme_path
            # must not write the same leaf files
            swap = os.path.join(
                off.get("nvme_path", "/tmp/dstpu_nvme_swap"),
                f"pstream_proc{self._pid}")
            self.tier: _Tier = _NvmeTier(swap)
            # the update worker's own aio channel: slot state is
            # single-thread, but per-key files make cross-channel access
            # to distinct keys safe (and same-key access is ordered by
            # the schedule: p_l is re-written only after its last read
            # of the step has fenced)
            self._utier: _Tier = _NvmeTier(swap)
        else:
            self.tier = _RamTier()
            self._utier = self.tier

        opt_type = config.optimizer.type.lower()
        if opt_type not in ("adam", "adamw", "fusedadam"):
            raise ValueError(
                f"param-stream engine supports the Adam family (the "
                f"reference's swappable optimizer is CPU-Adam), got "
                f"{opt_type!r}")
        oparams = dict(config.optimizer.params)
        opt_lr = float(oparams.pop("lr", default_lr(opt_type)))
        self.lr_schedule = (
            lr_scheduler if callable(lr_scheduler)
            else lr_schedules.from_config(config.scheduler.type,
                                          config.scheduler.params,
                                          fallback_lr=opt_lr))
        oparams.pop("torch_adam", None)
        self._hyp = {
            "betas": tuple(oparams.get("betas", (0.9, 0.999))),
            "eps": float(oparams.get("eps", 1e-8)),
            "wd": float(oparams.get("weight_decay", 0.0)),
            "adamw": bool(oparams.pop("adam_w_mode", True)),
            "bias_correction": bool(oparams.get("bias_correction", True)),
        }
        self.optimizer = None          # the engine IS the optimizer here

        self._compute_dtype = precision.compute_dtype(config.precision)
        if self._compute_dtype != jnp.bfloat16:
            raise NotImplementedError(
                "param-stream engine streams bf16 compute images (the "
                "fused CPU-Adam emits bf16); set bf16.enabled")
        self._cdt_np = np.dtype(jnp.bfloat16)

        # ---- block leaves: per-layer files on the tier
        lazy = callable(layered.blocks)
        blocks_shape_src = layered.blocks_spec if lazy else layered.blocks
        if lazy and blocks_shape_src is None:
            raise ValueError(
                "callable LayeredModel.blocks (lazy init) requires "
                "blocks_spec — the stacked abstract shapes")
        spec_leaves, self._btree = jax.tree_util.tree_flatten(
            blocks_shape_src)
        self._bpaths = [
            jax.tree_util.keystr(p) for p, _ in
            jax.tree_util.tree_flatten_with_path(blocks_shape_src)[0]]
        self._bshapes = [tuple(a.shape[1:]) for a in spec_leaves]
        self._bsizes = [int(np.prod(s)) for s in self._bshapes]
        self._bnames = [f"b{i}" for i in range(len(spec_leaves))]
        # per-process row partition of the f32 state: leaf rows pad to
        # pc x chunk and each process's tier holds one chunk (pc=1:
        # chunk == size, no padding, identical to single-controller)
        self._schunks = [-(-sz // self._pc) for sz in self._bsizes]

        def layer_arrays(l):
            if lazy:
                lv, td = jax.tree_util.tree_flatten(layered.blocks(l))
                if td != self._btree:
                    raise ValueError(
                        f"blocks({l}) structure {td} != blocks_spec "
                        f"structure {self._btree}")
                # lazy leaves are freshly built per call — the tier may
                # own them without a defensive copy
                return [np.asarray(a) for a in lv]
            # np.array: force copies — asarray views of jax CPU
            # buffers must never land on the (mutating) tier.  In the
            # eager path spec_leaves ARE the stacked block leaves.
            return [np.array(leaf[l]) for leaf in spec_leaves]

        for l in range(self.L):
            arrs = layer_arrays(l)
            for nm, a, i in zip(self._bnames, arrs, range(len(arrs))):
                if tuple(a.shape) != self._bshapes[i]:
                    raise ValueError(
                        f"layer {l} leaf {self._bpaths[i]}: shape "
                        f"{a.shape} != spec {self._bshapes[i]}")
                self.tier.put(f"p_{l}_{nm}", a.astype(self._cdt_np)
                              if a.dtype != self._cdt_np else a)
                f32 = np.ascontiguousarray(
                    a.astype(np.float32, copy=True)).reshape(-1)
                self.tier.put(f"w_{l}_{nm}", self._local_slice(f32, i))
                z = np.zeros(self._schunks[i], np.float32)
                self.tier.put(f"m_{l}_{nm}", z)
                self.tier.put(f"v_{l}_{nm}", z.copy())
            del arrs
        if isinstance(self.tier, _NvmeTier):
            self.tier.fence_all()

        # ---- shardings: TP composes with streaming — each uploaded
        # layer is sharded over the model axis (the 2-layer HBM working
        # set shrinks by 1/tp per device), activations stay data-sharded,
        # and XLA inserts the Megatron psums inside the block programs.
        # Host-side state (tier, grads, CPU-Adam) is whole-leaf either way.
        repl = self.mesh.replicated()
        self._repl = repl
        from jax.sharding import PartitionSpec as _P

        def shard_of(spec, drop_layer_dim=False):
            if spec is None:
                return repl
            if drop_layer_dim:
                if len(spec) and spec[0] is not None:
                    raise ValueError(
                        f"stacked block spec {spec} shards the layer "
                        "axis — the streaming engine owns that axis "
                        "(host schedule), use pipe via the pipeline "
                        "engine instead")
                spec = _P(*spec[1:])
            return self.mesh.sharding(spec)

        if self._specs is not None:
            stem_sp, blocks_sp, head_sp = self._specs
            self._lp_shards_flat = [
                shard_of(s, True)
                for s in self._btree.flatten_up_to(blocks_sp)]
            self._lp_shard_tree = jax.tree_util.tree_unflatten(
                self._btree, self._lp_shards_flat)
            self._stem_shards = jax.tree.map(
                lambda a, s: shard_of(s), layered.stem, stem_sp)
            self._head_shards = jax.tree.map(
                lambda a, s: shard_of(s), layered.head, head_sp)
        else:
            self._lp_shards_flat = [repl] * len(self._bnames)
            self._lp_shard_tree = repl
            self._stem_shards = repl
            self._head_shards = repl

        def host_state(tree):
            flat, td = jax.tree_util.tree_flatten(tree)
            paths = [jax.tree_util.keystr(p) for p, _ in
                     jax.tree_util.tree_flatten_with_path(tree)[0]]
            # np.array, not np.asarray: on the CPU backend asarray gives a
            # ZERO-COPY view of the jax buffer, and the in-place CPU-Adam
            # would then silently mutate the caller's param tree
            st = [{"w": np.array(a, np.float32).reshape(-1),
                   "m": np.zeros(a.size, np.float32),
                   "v": np.zeros(a.size, np.float32),
                   "shape": tuple(a.shape), "path": p}
                  for a, p in zip(flat, paths)]
            return st, td

        self._stem_state, self._stem_td = host_state(layered.stem)
        self._head_state, self._head_td = host_state(layered.head)
        self.stem_c = jax.device_put(jax.tree.map(
            lambda a: jnp.asarray(a, self._cdt_np), layered.stem),
            self._stem_shards)
        self.head_c = jax.device_put(jax.tree.map(
            lambda a: jnp.asarray(a, self._cdt_np), layered.head),
            self._head_shards)

        self.batch_sharding = self.mesh.sharding(self.mesh.batch_spec())
        self._jits_built = False
        # registry: streaming reader hit/stall/bytes/wait metrics fan in
        # here; per-step phase seconds land as counters so the overlap
        # accounting phase_report() already computes becomes scrapable
        self.registry = MetricsRegistry(
            enabled=config.telemetry.enabled)
        self._c_steps = self.registry.counter(
            "pstream_steps", "optimizer steps taken")
        self._c_skipped = self.registry.counter(
            "pstream_skipped_steps", "overflow-skipped steps")
        self._h_step = self.registry.histogram(
            "pstream_step_seconds", "train_batch wall time",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0))
        self._preader = self._make_reader()

        self.global_steps = 0
        self._opt_steps = 0
        self.skipped_steps = 0
        self.step_times: List[float] = []
        self.phase_times: Dict[str, float] = {}
        self._last_metrics: Dict[str, Any] = {}
        import threading
        from concurrent.futures import ThreadPoolExecutor

        self._d2h_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="dstpu-pstream-d2h")
        # single worker: tier updates must serialize among themselves
        # (one aio channel, and layer-ordered writes keep the NVMe queue
        # depth steady); overlap comes from running BESIDE the vjps
        self._upd_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dstpu-pstream-upd")
        self._ph_lock = threading.Lock()
        logger.info(
            "ParamStreamEngine: tier=%s layers=%d block-leaves=%d "
            "per-layer=%d params (%.1f MB bf16), stem+head resident",
            self.device_tier, self.L, len(self._bnames),
            sum(self._bsizes), 2 * sum(self._bsizes) / 1e6)

    # ------------------------------------------------------------- programs
    def _build_jits(self):
        lm = self.layered
        bs = self.batch_sharding

        self._stem_jit = jax.jit(lm.stem_fn,
                                 in_shardings=(self._stem_shards, bs))

        # donate lp: the uploaded double-buffer entry is dead after its
        # single use (re-uploaded for the backward pass)
        if lm.block_has_aux:
            def block_fwd(lp, x, aux_acc):
                x, aux = lm.block_fn(lp, x)
                return x, aux_acc + aux.astype(jnp.float32)
            self._block_jit = jax.jit(block_fwd, donate_argnums=(0,))
        else:
            self._block_jit = jax.jit(lm.block_fn, donate_argnums=(0,))

        def head_grad(hp, x, batch):
            (loss, _), (dh, dx) = jax.value_and_grad(
                lambda h, xx: (lm.head_fn(h, xx, batch)
                               .astype(jnp.float32),) * 2,
                argnums=(0, 1), has_aux=True)(hp, x)
            return loss, dh, dx

        self._head_grad_jit = jax.jit(
            head_grad, out_shardings=(None, self._head_shards, None))

        def block_vjp(lp, x_in, dy):
            _, pull = jax.vjp(lm.block_fn, lp, x_in)
            if lm.block_has_aux:
                # total = head(x_L) + sum_l aux_l, so each layer's aux
                # output carries cotangent 1; dx already carries the
                # downstream layers' aux dependence by induction
                dlp, dx = pull((dy, jnp.float32(1.0)))
            else:
                dlp, dx = pull(dy)
            return dlp, dx

        # donate dy → dx reuses its buffer; lp dead after the pull
        self._block_vjp_jit = jax.jit(
            block_vjp, donate_argnums=(0, 2),
            out_shardings=(self._lp_shard_tree, None))

        def stem_vjp(sp, batch, dx):
            _, pull = jax.vjp(lambda s: lm.stem_fn(s, batch), sp)
            return pull(dx)[0]

        # no donation: dstem ([V, d]) shares no shape with dx ([B, T, d])
        self._stem_vjp_jit = jax.jit(stem_vjp,
                                     out_shardings=self._stem_shards)
        self._jits_built = True

    # ------------------------------------------------------------ streaming
    def _layer_keys(self, l: int) -> List[str]:
        """The tier key scheme for layer ``l``'s bf16 compute leaves —
        single source for the reader pipeline AND the direct read path
        below, so the two can never drift."""
        return [f"p_{l}_{nm}" for nm in self._bnames]

    def _make_reader(self) -> TierLayerReader:
        from deepspeed_tpu.request_trace import default_tracer

        return TierLayerReader(
            self.tier, names_fn=self._layer_keys,
            shapes=[(sz,) for sz in self._bsizes],
            dtypes=[self._cdt_np] * len(self._bnames),
            to_device=lambda bufs, _l: self._bufs_to_device(bufs),
            registry=self.registry, prefix="pstream",
            tracer=default_tracer())

    def _submit_layer_read(self, l: int):
        return [self.tier.get_submit(n, (sz,), self._cdt_np)
                for n, sz in zip(self._layer_keys(l), self._bsizes)]

    def _bufs_to_device(self, bufs):
        flat = [jax.device_put(
            jnp.asarray(b).reshape(s), sh)
            for b, s, sh in zip(bufs, self._bshapes,
                                self._lp_shards_flat)]
        return jax.tree_util.tree_unflatten(self._btree, flat)

    # ------------------------------------------- per-process row partition
    def _local_slice(self, flat: np.ndarray, i: int) -> np.ndarray:
        """This process's chunk of leaf ``i``'s flat array (zero-padded
        at the tail process); pc=1 returns the array unchanged.  This is
        on the per-leaf per-layer update path, so non-tail processes
        slice directly — O(chunk) copy, never O(leaf)."""
        if self._pc == 1:
            return flat
        c = self._schunks[i]
        lo = self._pid * c
        if lo + c <= flat.size:
            # .copy(), not a view: a contiguous slice would keep the
            # FULL leaf alive via .base for the tier's lifetime,
            # defeating the 1/pc state-footprint split
            return flat[lo:lo + c].copy()
        out = np.zeros(c, flat.dtype)
        if lo < flat.size:
            out[:flat.size - lo] = flat[lo:]
        return out

    def _allgather_slices(self, local: np.ndarray, i: int) -> np.ndarray:
        """Re-assemble a full flat leaf from per-process chunks (COLLECTIVE
        across processes — every process must call in the same order)."""
        if self._pc == 1:
            return local
        from jax.experimental import multihost_utils

        full = np.asarray(
            multihost_utils.process_allgather(local, tiled=True))
        return full[:self._bsizes[i]]

    def _phase_reset(self):
        self.phase_times = {
            "fwd_compute": 0.0, "bwd_compute": 0.0, "param_read_wait": 0.0,
            "grad_d2h_wait": 0.0, "host_adam": 0.0, "tier_write": 0.0,
            "update_wait": 0.0, "total": 0.0}
        return self.phase_times

    def _ph_add(self, ph, key, dt):
        """Worker-thread-safe phase accounting (+= is not atomic)."""
        with self._ph_lock:
            ph[key] += dt

    def phase_report(self) -> Dict[str, float]:
        """Per-phase seconds of the last step.  Phases overlap by design
        (param reads and grad D2H run behind the layer computes; in
        overlap_step mode host_adam/tier_write run behind bwd_compute),
        so the parts can sum past 'total'.  The exposed cost of the
        optimizer pass is 'update_wait' — how long the step blocked at
        the end for in-flight layer updates to finish; host_adam largely
        hidden means update_wait ≪ host_adam."""
        return dict(self.phase_times)

    # ------------------------------------------------------------------ step
    def curriculum_difficulty(self):
        """Current curriculum difficulty (TrainingEngine parity), or
        None when no curriculum is configured."""
        if self.curriculum_scheduler is None:
            return None
        return self.curriculum_scheduler.get_difficulty(self.global_steps)

    def _apply_curriculum(self, batch):
        from deepspeed_tpu.data.curriculum import apply_seqlen_curriculum

        return apply_seqlen_curriculum(batch, self.curriculum_scheduler,
                                       self.global_steps)

    def train_batch(self, batch) -> jnp.ndarray:
        t0 = time.perf_counter()
        batch = self._apply_curriculum(batch)
        if not self._jits_built:
            self._build_jits()
        ph = self._phase_reset()
        nvme = isinstance(self.tier, _NvmeTier)
        accum = self.config.gradient_accumulation_steps
        if accum > 1:
            from deepspeed_tpu.engine import accum_split

            micros = accum_split(batch, accum, self.mesh.size("data"))
            micros = [jax.tree.map(lambda x, _i=i: x[_i], micros)
                      for i in range(accum)]
        else:
            micros = [batch]

        # host f32 grad accumulators, one per block leaf per layer
        gbuf: List[Optional[List[np.ndarray]]] = [None] * self.L
        gstem = ghead = None
        loss_sum = 0.0
        loss_bad = False               # nonfinite loss → exact whole-step skip
        stats: Dict[int, tuple] = {}   # layer → (ssq, finite) of final grads
        upd_futs: List[Any] = []       # in-flight overlapped layer updates
        t_step = self._opt_steps + 1
        lr = float(self.lr_schedule(jnp.int32(t_step)))
        inv = 1.0 / accum

        for im, mb in enumerate(micros):
            final_mb = im == accum - 1
            mb = jax.device_put(mb, self.batch_sharding)
            # ---------------- forward: stream layers up (shared
            # double-buffer pipeline — layer l+1's tier read + upload in
            # flight behind layer l's block program)
            t1 = time.perf_counter()
            x = self._stem_jit(self.stem_c, mb)
            aux_acc = jnp.float32(0.0)
            xs: List[Any] = []
            read_wait = lambda dt: self._ph_add(ph, "param_read_wait", dt)
            for l, lp in self._preader.sweep(range(self.L),
                                             on_wait=read_wait):
                xs.append(x)
                if self.layered.block_has_aux:
                    x, aux_acc = self._block_jit(lp, x, aux_acc)
                else:
                    x = self._block_jit(lp, x)
            ph["fwd_compute"] += time.perf_counter() - t1

            # ---------------- head
            t1 = time.perf_counter()
            loss, dhead, dx = self._head_grad_jit(self.head_c, x, mb)
            mb_loss = float(loss)                # sync: fwd+head done
            if self.layered.block_has_aux:
                mb_loss += float(aux_acc)        # total = lm + aux terms
            loss_sum += mb_loss
            # the loss gate: checked BEFORE any update can launch, so a
            # compute overflow (which propagates to the loss under bf16)
            # always skips the step exactly, even in overlap mode
            if not math.isfinite(mb_loss):
                loss_bad = True
            ph["bwd_compute"] += time.perf_counter() - t1

            def fetch(tree_or_list):
                return [np.asarray(a, np.float32).reshape(-1)
                        for a in jax.tree.leaves(tree_or_list)]

            hfut = self._d2h_pool.submit(fetch, dhead)

            # ---------------- backward: stream layers down
            t1 = time.perf_counter()
            can_update = final_mb and not loss_bad and self.overlap_step
            dfuts: List[Any] = []
            for l, lp in self._preader.sweep(range(self.L - 1, -1, -1),
                                             on_wait=read_wait):
                dlp, dx = self._block_vjp_jit(lp, xs[l], dx)
                xs[l] = None
                # bound in-flight drains (device grad buffers alive until
                # their fetch lands) at the pool width
                while len(dfuts) >= 2:
                    tw = time.perf_counter()
                    dfuts.pop(0).result()
                    ph["grad_d2h_wait"] += time.perf_counter() - tw
                dfuts.append(self._d2h_pool.submit(
                    self._drain_block, l, dlp, gbuf, final_mb, can_update,
                    stats, upd_futs, lr, t_step, inv, ph))
            ds = self._stem_vjp_jit(self.stem_c, mb, dx)
            sflat = fetch(ds)
            gstem = sflat if gstem is None else [
                a + b for a, b in zip(gstem, sflat)]
            hflat = hfut.result()
            ghead = hflat if ghead is None else [
                a + b for a, b in zip(ghead, hflat)]
            tw = time.perf_counter()
            for f in dfuts:
                f.result()
            ph["grad_d2h_wait"] += time.perf_counter() - tw
            ph["bwd_compute"] += time.perf_counter() - t1

        loss = loss_sum * inv

        # ---------------- finite consensus + unconditional grad norm
        if loss_bad:
            # no update launched anywhere (the loss gate precedes every
            # drain finalize): the reference's exact whole-step skip
            self._last_grad_norm = float("inf")
            self.global_steps += 1
            self.skipped_steps += 1
            self._last_metrics = {"loss": jnp.float32(loss),
                                  "overflow": jnp.int32(1)}
            self.step_times.append(time.perf_counter() - t0)
            ph["total"] = self.step_times[-1]
            self._record_step_telemetry(ph, skipped=True)
            return jnp.float32(loss)

        res_ssq, res_fin = 0.0, True
        for gs in (gstem, ghead):
            for g in gs:
                res_ssq += float(np.vdot(g, g))
                res_fin = res_fin and bool(np.isfinite(g).all())
        ssq = res_ssq + sum(s[0] for s in stats.values())
        norm = math.sqrt(ssq) * inv if math.isfinite(ssq) else float("inf")
        self._last_grad_norm = norm          # every step, clip or not
        finite = res_fin and all(s[1] for s in stats.values())
        if not finite:
            if upd_futs:
                # overlap mode already committed earlier layers: torn
                # step — unrecoverable by design, so fail loudly
                for f in upd_futs:
                    f.result()
                if isinstance(self._utier, _NvmeTier):
                    self._utier.fence_all()
                raise FloatingPointError(
                    "param-stream overlap_step: nonfinite gradient under "
                    "a finite loss after some layers already updated; "
                    "set offload_param.overlap_step=false for strict "
                    "whole-step overflow skipping")
            self.global_steps += 1
            self.skipped_steps += 1
            self._last_metrics = {"loss": jnp.float32(loss),
                                  "overflow": jnp.int32(1)}
            self.step_times.append(time.perf_counter() - t0)
            ph["total"] = self.step_times[-1]
            self._record_step_telemetry(ph, skipped=True)
            return jnp.float32(loss)

        clip = self.config.gradient_clipping
        if clip and clip > 0:
            # same semantics as engine.clip_by_global_norm, on the host
            # copies: the clipped quantity is the MEAN grad (hence inv²)
            inv = inv * min(1.0, clip / (norm + 1e-6))
        if self.overlap_step:
            tw = time.perf_counter()
            for f in upd_futs:
                f.result()               # propagate worker errors too
            if isinstance(self._utier, _NvmeTier):
                self._utier.fence_all()
            ph["update_wait"] += time.perf_counter() - tw
        else:
            self._update_blocks(gbuf, lr, t_step, inv, ph, nvme)
        self._update_resident(self._stem_state, gstem, "stem", lr, t_step,
                              inv, ph)
        self._update_resident(self._head_state, ghead, "head", lr, t_step,
                              inv, ph)
        if nvme:
            t1 = time.perf_counter()
            self.tier.fence_all()
            ph["tier_write"] += time.perf_counter() - t1

        self.global_steps += 1
        self._opt_steps += 1
        self._last_metrics = {"loss": jnp.float32(loss),
                              "overflow": jnp.int32(0)}
        self.step_times.append(time.perf_counter() - t0)
        ph["total"] = self.step_times[-1]
        self._record_step_telemetry(ph, skipped=False)
        return jnp.float32(loss)

    def _record_step_telemetry(self, ph, skipped: bool) -> None:
        """Fold one step's phase accounting into the registry (phase
        seconds as counters — their ratios are the overlap story
        phase_report() tells, now scrapable across the run)."""
        if not self.registry.enabled:
            return
        self._c_steps.inc()
        if skipped:
            self._c_skipped.inc()
        self._h_step.observe(ph.get("total", 0.0))
        for k, v in ph.items():
            if k != "total" and v > 0:
                self.registry.counter(f"pstream_phase_{k}_seconds").inc(v)
        from deepspeed_tpu.request_trace import default_tracer

        tr = default_tracer()
        if tr.enabled:
            # one flight-recorder event per train step carrying the
            # whole phase breakdown — a hang postmortem shows which
            # phase the last completed step spent its time in
            attrs = {k: round(v, 6) for k, v in ph.items()}
            attrs["step"] = self.global_steps
            attrs["skipped"] = skipped
            tr.event("pstream_step", attrs=attrs)

    # ------------------------------------------------------------- updates
    def _accum_layer(self, gbuf, l: int, flat: List[np.ndarray]) -> None:
        if gbuf[l] is None:
            gbuf[l] = flat
        else:
            for a, b in zip(gbuf[l], flat):
                a += b

    def _drain_block(self, l, dlp, gbuf, finalize, can_update, stats,
                     upd_futs, lr, t, inv, ph):
        """d2h-pool job: land layer ``l``'s device grads on the host and
        accumulate.  On the final microbatch also compute the layer's
        finite bit + norm contribution (hidden behind the next vjp) and,
        in overlap mode, hand the grads straight to the update worker —
        the vjps of layers ``l-1..0`` then hide the CPU-Adam + tier
        write.  Jobs for different layers touch disjoint ``gbuf``/
        ``stats`` slots, so two drain workers never race."""
        flat = [np.asarray(a, np.float32).reshape(-1)
                for a in jax.tree.leaves(dlp)]
        self._accum_layer(gbuf, l, flat)
        if not finalize:
            return
        g = gbuf[l]
        ssq = sum(float(np.vdot(a, a)) for a in g)
        fin = all(bool(np.isfinite(a).all()) for a in g)
        stats[l] = (ssq, fin)
        if can_update and fin:
            # backpressure: a lagging CPU-Adam must not let un-updated
            # layers' f32 grads pile up on the host (at 8B+ scale the
            # full-depth backlog is tens of GB).  Blocking HERE stalls
            # the drain worker, which stalls the vjp loop at its dfuts
            # bound — so device-side backward pauses until the update
            # queue shrinks, and host grad residency stays O(5 layers).
            while sum(1 for f in upd_futs if not f.done()) > 4:
                pending = next((f for f in upd_futs if not f.done()),
                               None)
                if pending is None:
                    break
                pending.result()
            upd_futs.append(self._upd_pool.submit(
                self._update_one_layer, l, g, gbuf, lr, t, inv, ph))

    def _update_one_layer(self, l, grads, gbuf, lr, t, inv, ph):
        """Update worker: fused CPU-Adam for one layer's leaves + fresh
        bf16 image, on the update channel (own aio slots)."""
        nvme = isinstance(self._utier, _NvmeTier)
        bufs = [(self._utier.get_submit(f"w_{l}_{nm}", (sz,), np.float32),
                 self._utier.get_submit(f"m_{l}_{nm}", (sz,), np.float32),
                 self._utier.get_submit(f"v_{l}_{nm}", (sz,), np.float32))
                for nm, sz in zip(self._bnames, self._schunks)]
        if nvme:
            t1 = time.perf_counter()
            self._utier.fence_reads()
            self._ph_add(ph, "param_read_wait", time.perf_counter() - t1)
            self._utier.next_read_slot()
        self._apply_layer_update(self._utier, l, bufs, grads, lr, t, inv,
                                 ph)
        gbuf[l] = None

    def _apply_layer_update(self, tier, l, bufs, grads, lr, t, inv, ph):
        """Per-leaf adam + write-back sequence shared by the overlap
        (update worker, ``_utier``) and strict (main thread, ``tier``)
        paths — one body so the slot protocol can never diverge.
        Multi-process: adam runs on this process's row slice and the
        fresh bf16 image is re-assembled collectively (strict path
        only — overlap is forced off under process_count > 1)."""
        nvme = isinstance(tier, _NvmeTier)
        for i, ((w, m, v), g) in enumerate(zip(bufs, grads)):
            nm = self._bnames[i]
            g = self._local_slice(g, i)
            if inv != 1.0:
                g *= inv
            t1 = time.perf_counter()
            w = np.asarray(w, np.float32)
            m = np.asarray(m, np.float32)
            v = np.asarray(v, np.float32)
            bf16 = self._adam_inplace(w, m, v, g, lr, t, True)
            self._ph_add(ph, "host_adam", time.perf_counter() - t1)
            t1 = time.perf_counter()
            full_bf16 = self._allgather_slices(
                bf16.view(self._cdt_np), i)
            if nvme:
                tier.fence_writes()
            tier.put(f"w_{l}_{nm}", w)
            tier.put(f"m_{l}_{nm}", m)
            tier.put(f"v_{l}_{nm}", v)
            tier.put(f"p_{l}_{nm}", full_bf16)
            if nvme:
                tier.next_write_slot()
            self._ph_add(ph, "tier_write", time.perf_counter() - t1)

    def _adam_inplace(self, w, m, v, g, lr, t, emit_bf16):
        from deepspeed_tpu.ops.cpu_adam import cpu_adam_step

        b1, b2 = self._hyp["betas"]
        return cpu_adam_step(
            w, m, v, g, lr=lr, b1=b1, b2=b2, eps=self._hyp["eps"],
            wd=self._hyp["wd"], adamw=self._hyp["adamw"], t=t,
            bias_correction=self._hyp["bias_correction"],
            emit_bf16=emit_bf16)

    def _update_blocks(self, gbuf, lr, t, inv, ph, nvme) -> None:
        """Fused CPU-Adam per layer leaf; fresh bf16 image to the tier.
        Tier state reads are double-buffered ahead of the update."""
        def read_layer(l):
            return [(self.tier.get_submit(f"w_{l}_{nm}", (sz,), np.float32),
                     self.tier.get_submit(f"m_{l}_{nm}", (sz,), np.float32),
                     self.tier.get_submit(f"v_{l}_{nm}", (sz,), np.float32))
                    for nm, sz in zip(self._bnames, self._schunks)]

        pending = read_layer(0)
        for l in range(self.L):
            if nvme:
                t1 = time.perf_counter()
                self.tier.fence_reads()
                ph["param_read_wait"] += time.perf_counter() - t1
                self.tier.next_read_slot()
            bufs = pending
            if l + 1 < self.L:
                pending = read_layer(l + 1)
            self._apply_layer_update(self.tier, l, bufs, gbuf[l], lr, t,
                                     inv, ph)
            gbuf[l] = None

    def _update_resident(self, state, grads, which, lr, t, inv, ph) -> None:
        """Stem/head update: host adam + fresh resident compute copy."""
        t1 = time.perf_counter()
        fresh = []
        for st, g in zip(state, grads):
            if inv != 1.0:
                g *= inv
            bf16 = self._adam_inplace(st["w"], st["m"], st["v"], g, lr, t,
                                      True)
            fresh.append(jnp.asarray(bf16.view(self._cdt_np)
                                     .reshape(st["shape"])))
        ph["host_adam"] += time.perf_counter() - t1
        stem = which == "stem"
        td = self._stem_td if stem else self._head_td
        tree = jax.device_put(
            jax.tree_util.tree_unflatten(td, fresh),
            self._stem_shards if stem else self._head_shards)
        if stem:
            self.stem_c = tree
        else:
            self.head_c = tree

    # ----------------------------------------------------------- inspection
    @property
    def metrics(self):
        return self._last_metrics

    def get_lr(self):
        return [float(self.lr_schedule(jnp.int32(self._opt_steps)))]

    def get_global_grad_norm(self):
        """Pre-clip global norm of the last step's mean grad, computed
        every step (clipping on or off) from the per-layer partial sums
        the drain workers already produce; ``inf`` on overflow-skipped
        steps, 0.0 before the first step — metric parity with
        TrainingEngine."""
        return self._last_grad_norm

    @property
    def train_batch_size(self):
        return self.config.train_batch_size

    def hbm_param_working_set_bytes(self) -> int:
        """Peak bf16 PARAM bytes resident during a step: the 2-layer
        double buffer + stem/head — the streaming contract (compare:
        2N for any engine that keeps the full compute copy)."""
        per_layer = 2 * sum(self._bsizes)
        stem_head = sum(x.nbytes for x in jax.tree.leaves(self.stem_c)) + \
            sum(x.nbytes for x in jax.tree.leaves(self.head_c))
        return 2 * per_layer + stem_head

    def total_param_count(self) -> int:
        n = self.L * sum(self._bsizes)
        n += sum(int(np.prod(s["shape"])) for s in self._stem_state)
        n += sum(int(np.prod(s["shape"])) for s in self._head_state)
        return n

    def master_params(self) -> Any:
        """Consolidated f32 masters — in the ORIGINAL model layout when
        the LayeredModel provides ``assemble`` (llama's does, so the
        export round-trips into init_params-shaped models exactly like
        InfinityEngine.master_params); otherwise the factored
        {stem, blocks, head} dict.  NVMe reads batch per leaf: all L
        rows submitted into one preallocated stack, one fence."""
        nvme = isinstance(self.tier, _NvmeTier)
        blocks = []
        for i, (nm, sz, shape) in enumerate(zip(
                self._bnames, self._bsizes, self._bshapes)):
            stack = np.empty((self.L,) + shape, np.float32)
            if self._pc > 1:
                # COLLECTIVE consolidation: local rows → full leaf, one
                # layer at a time, identical call order on all processes
                for l in range(self.L):
                    buf = self.tier.get_submit(
                        f"w_{l}_{nm}", (self._schunks[i],), np.float32)
                    self.tier.fence_reads()
                    stack[l] = self._allgather_slices(
                        np.asarray(buf), i).reshape(shape)
                blocks.append(stack)
                continue
            bufs = [self.tier.get_submit(
                f"w_{l}_{nm}", (sz,), np.float32,
                out=stack[l].reshape(-1)) for l in range(self.L)]
            self.tier.fence_reads()
            if not nvme:          # RAM tier returned its stored arrays
                for l, b in enumerate(bufs):
                    stack[l] = np.asarray(b).reshape(shape)
            blocks.append(stack)
        blocks_tree = jax.tree_util.tree_unflatten(self._btree, blocks)
        stem, head = ({pre: jax.tree_util.tree_unflatten(
            td, [s["w"].reshape(s["shape"]).copy() for s in st])
            for pre, st, td in ((0, self._stem_state, self._stem_td),
                                (1, self._head_state, self._head_td))}[i]
            for i in (0, 1))
        if self.layered.assemble is not None:
            return self.layered.assemble(stem, blocks_tree, head)
        return {"stem": stem, "blocks": blocks_tree, "head": head}

    def wait_for_checkpoint(self) -> None:
        """Drop-in parity: saves here are synchronous."""

    # ---------------------------------------------------------- checkpoint
    def _manifest(self) -> dict:
        """Layout descriptor saved into meta.json so offline tooling
        (zero_to_fp32) can reassemble the factored state without the
        model: per-block-leaf key/path/per-layer-shape, plus stem/head
        leaves (universal-checkpoint semantics — the tier layout is a
        save-time detail that must not leak into the format)."""
        return {
            "version": 1, "n_layers": self.L,
            "blocks": [{"key": nm, "path": p, "shape": list(s)}
                       for nm, p, s in zip(self._bnames, self._bpaths,
                                           self._bshapes)],
            "stem": [{"path": s["path"], "shape": list(s["shape"])}
                     for s in self._stem_state],
            "head": [{"path": s["path"], "shape": list(s["shape"])}
                     for s in self._head_state]}

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None,
                        async_save: bool = False):
        """Per-leaf universal layout via the shared
        :class:`~deepspeed_tpu.checkpoint.UniversalLeafCheckpointer` —
        one orbax item per (layer, leaf, kind), flat unpadded f32, so
        the transient footprint is a single layer leaf (never a
        monolithic state blob) and the next tier read overlaps the
        previous leaf's background disk commit."""
        from deepspeed_tpu.checkpoint import (UniversalLeafCheckpointer,
                                              finalize_checkpoint_dir)

        tag = tag or f"global_step{self.global_steps}"
        d = os.path.join(save_dir, tag)
        os.makedirs(d, exist_ok=True)
        ulc = UniversalLeafCheckpointer(d)
        for l in range(self.L):
            for i, nm in enumerate(self._bnames):
                for kind in ("w", "m", "v"):
                    buf = self.tier.get_submit(
                        f"{kind}_{l}_{nm}", (self._schunks[i],),
                        np.float32)
                    self.tier.fence_reads()
                    # copy: the RAM tier returns its live array, which
                    # the next step's in-place CPU-Adam would mutate
                    # under orbax's background serializer.  Multi-
                    # process: consolidate collectively — the universal
                    # format stores full unpadded leaves, topology-free.
                    item = self._allgather_slices(np.array(buf), i)
                    ulc.save(f"{kind}{l:04d}_{nm}", item)
        for pre, st in (("stem", self._stem_state),
                        ("head", self._head_state)):
            for i, s in enumerate(st):
                for kind in ("w", "m", "v"):
                    ulc.save(f"{pre}{kind}_{i:03d}", s[kind].copy())
        ulc.wait()
        if isinstance(self.tier, _NvmeTier):
            self.tier.fence_all()
        finalize_checkpoint_dir(save_dir, tag, {
            "global_steps": self.global_steps,
            "opt_steps": self._opt_steps,
            "skipped_steps": self.skipped_steps,
            "pstream_universal": self._manifest(),
            "client_state": client_state or {}})
        return d

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None):
        import json

        from deepspeed_tpu.ops.cpu_adam import f32_to_bf16

        from deepspeed_tpu.checkpoint import _resolve_tag

        tag = _resolve_tag(load_dir, tag, required=False)
        if tag is None:
            # pre-pointer checkpoints: numerically newest global_step dir
            tags = [t for t in os.listdir(load_dir)
                    if os.path.isdir(os.path.join(load_dir, t))
                    and os.path.exists(os.path.join(load_dir, t,
                                                    "meta.json"))]
            if not tags:
                raise FileNotFoundError(f"no checkpoints under {load_dir}")
            tag = max(tags, key=lambda t: (
                int(t.rsplit("global_step", 1)[-1])
                if t.rsplit("global_step", 1)[-1].isdigit() else -1, t))
        d = os.path.join(load_dir, tag)
        legacy = os.path.join(d, "pstream_state.npz")
        if os.path.exists(legacy):        # pre-universal monolithic npz
            arrays = np.load(legacy)

            def block_item(kind, l, nm):
                return np.ascontiguousarray(arrays[f"{kind}_{l}_{nm}"])

            def res_item(pre, kind, i):
                return arrays[f"{pre}{kind}_{i}"]
        else:
            from deepspeed_tpu.checkpoint import UniversalLeafCheckpointer

            ulc = UniversalLeafCheckpointer(d)

            def block_item(kind, l, nm):
                return ulc.restore(f"{kind}{l:04d}_{nm}")

            def res_item(pre, kind, i):
                return ulc.restore(f"{pre}{kind}_{i:03d}")

        for l in range(self.L):
            for i, nm in enumerate(self._bnames):
                w = block_item("w", l, nm)
                # items are full unpadded leaves; each process keeps its
                # row slice (any process count restores any checkpoint)
                self.tier.put(f"w_{l}_{nm}", self._local_slice(w, i))
                self.tier.put(f"m_{l}_{nm}",
                              self._local_slice(block_item("m", l, nm), i))
                self.tier.put(f"v_{l}_{nm}",
                              self._local_slice(block_item("v", l, nm), i))
                self.tier.put(f"p_{l}_{nm}",
                              f32_to_bf16(w).view(self._cdt_np))
        fresh = {"stem": [], "head": []}
        for pre, st in (("stem", self._stem_state),
                        ("head", self._head_state)):
            for i, s in enumerate(st):
                for kind in ("w", "m", "v"):
                    s[kind][...] = res_item(pre, kind, i)
                fresh[pre].append(jnp.asarray(
                    f32_to_bf16(s["w"]).view(self._cdt_np)
                    .reshape(s["shape"])))
        self.stem_c = jax.device_put(jax.tree_util.tree_unflatten(
            self._stem_td, fresh["stem"]), self._stem_shards)
        self.head_c = jax.device_put(jax.tree_util.tree_unflatten(
            self._head_td, fresh["head"]), self._head_shards)
        if isinstance(self.tier, _NvmeTier):
            self.tier.fence_all()
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        self.global_steps = meta["global_steps"]
        self._opt_steps = meta["opt_steps"]
        self.skipped_steps = meta["skipped_steps"]
        return d, meta.get("client_state", {})
