"""ZeRO redundancy elimination as GSPMD shardings.

Reference: deepspeed/runtime/zero/stage_1_and_2.py (DeepSpeedZeroOptimizer),
deepspeed/runtime/zero/stage3.py + partition_parameters.py.

The reference implements ZeRO imperatively: flatten params into contiguous
buffers, round-robin 1-D chunks across the DP group, hook backward to
reduce-scatter gradients, and all-gather params around each use (stage 3),
with bucketing/overlap machinery to hide latency.

On TPU none of that machinery is needed — ZeRO *is* a sharding decision:

========  ======================  ==================  =====================
stage     optimizer state         gradients           parameters
========  ======================  ==================  =====================
0         replicated              replicated (psum)   replicated
1         sharded over data       replicated (psum)   replicated
2         sharded over data       sharded (r-scatter) replicated
3         sharded over data       sharded             sharded (AG at use)
========  ======================  ==================  =====================

We express each column as a per-leaf ``NamedSharding`` and let XLA insert
the exact all-gather / reduce-scatter schedule the reference hand-codes —
overlapped with compute by the XLA latency-hiding scheduler, riding ICI.

Model-parallel (TP) shardings compose: callers pass ``param_specs`` — a
pytree of ``PartitionSpec`` matching the params pytree (or a callable
``leaf -> spec``) — and the ZeRO data axis is layered onto the remaining
unsharded dimension.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.topology import MeshSpec, ZERO_AXES, shard_leaf_spec

SpecTree = Union[None, Callable, Any]


def resolve_specs(params: Any, param_specs: SpecTree) -> Any:
    """Normalize ``param_specs`` (None | callable | pytree) to a spec pytree.

    In the pytree form, a ``None`` leaf means replicated (the usual JAX
    convention) and is normalized to ``P()``.
    """
    if param_specs is None:
        return jax.tree.map(lambda _: P(), params)
    if callable(param_specs):
        return jax.tree.map(param_specs, params)
    return jax.tree.map(lambda s: P() if s is None else s, param_specs,
                        is_leaf=lambda x: x is None or isinstance(x, P))


def _zero_axis_size(ms: MeshSpec) -> int:
    n = 1
    for a in ZERO_AXES:
        n *= ms.size(a)
    return n


def _zero_spec(leaf, base: P, ms: MeshSpec) -> P:
    """Layer the data axis onto ``base`` for one leaf."""
    shape = getattr(leaf, "shape", ())
    if len(shape) == 0:
        return P()
    base = () if base is None else base
    # truncate: state leaves may have lower rank than the param they mirror
    # (e.g. factored second moments)
    taken = list(base)[:len(shape)] + [None] * max(0, len(shape) - len(base))
    return shard_leaf_spec(shape, "data", ms.size("data"), taken=taken)


def param_shardings(params: Any, ms: MeshSpec, stage: int,
                    param_specs: SpecTree = None):
    """Shardings for the master parameter pytree (stage 3 adds data axis)."""
    specs = resolve_specs(params, param_specs)

    def one(leaf, base):
        if stage >= 3 and _zero_axis_size(ms) > 1:
            return ms.sharding(_zero_spec(leaf, base, ms))
        return ms.sharding(base)

    return jax.tree.map(one, params, specs)


def optstate_shardings(opt_state: Any, params: Any, ms: MeshSpec, stage: int,
                       param_specs: SpecTree = None):
    """Shardings for optimizer-state pytrees.

    Subtrees that mirror the params structure (moments, master copies) get
    the params' specs (+ data axis for stage >=1, ref: stage_1_and_2.py
    partitioning of fp32 optimizer state); stray leaves are replicated.
    """
    specs = resolve_specs(params, param_specs)
    pstruct = jax.tree.structure(params)
    shard_state = stage >= 1 and _zero_axis_size(ms) > 1

    def spec_for(leaf, base):
        if shard_state:
            return ms.sharding(_zero_spec(leaf, base, ms))
        return ms.sharding(base if getattr(leaf, "ndim", 0) else P())

    def rec(node):
        if node is None:
            return None
        try:
            if jax.tree.structure(node) == pstruct:
                return jax.tree.map(spec_for, node, specs)
        except Exception:
            pass
        if jax.tree_util.all_leaves([node]):
            # stray leaf (step counters etc.): shard if it's a real array,
            # replicate scalars
            if shard_state and getattr(node, "ndim", 0) >= 1:
                return ms.sharding(_zero_spec(node, P(), ms))
            return ms.replicated()
        # generic one-level recursion — works for any registered pytree
        # container (dataclass states, optax NamedTuples, dicts, ...)
        one_level = jax.tree.structure(node, is_leaf=lambda x: x is not node)
        children = one_level.flatten_up_to(node)
        return jax.tree.unflatten(one_level, [rec(c) for c in children])

    return rec(opt_state)


def grad_constraint(grads: Any, ms: MeshSpec, stage: int,
                    param_specs: SpecTree = None):
    """Stage >=2: constrain grads to the data-sharded layout so XLA emits a
    reduce-scatter instead of an all-reduce (ref: stage_1_and_2.py
    ``reduce_scatter_gradients``)."""
    if stage < 2 or _zero_axis_size(ms) == 1:
        return grads
    specs = resolve_specs(grads, param_specs)
    return jax.tree.map(
        lambda g, base: jax.lax.with_sharding_constraint(
            g, ms.sharding(_zero_spec(g, base, ms))), grads, specs)


def estimate_memory(num_params: int, dp_world: int, stage: int,
                    offload_optimizer: bool = False,
                    compute_bytes: int = 2, master_bytes: int = 4,
                    activation_bytes: int = 0) -> dict:
    """Per-device memory plan for a ZeRO stage (ref:
    deepspeed/runtime/zero/stage3.py estimate_zero3_model_states_mem_needs*
    / stage_1_and_2.py estimate_zero2_model_states_mem_needs*).

    Returns bytes per device for each state class plus the total.  The
    model: bf16 compute copy (replicated below stage 3, sharded at 3),
    f32 master + two Adam moments (sharded from stage 1; on host when
    ``offload_optimizer``), grads in compute dtype (sharded from stage 2).
    """
    if not 0 <= stage <= 3:
        raise ValueError(f"stage must be 0..3, got {stage}")
    if offload_optimizer and stage == 0:
        # reachable but degenerate: engine_offload_shardings applies the
        # host tier at any stage, so stage 0 pins the FULL replicated
        # optimizer copy to every host (the reference estimators only
        # model offload for ZeRO 1-3) — model it, but say so
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "offload_optimizer at ZeRO stage 0 keeps the full replicated "
            "optimizer state on every host; use stage >= 1 to shard it")
    n, w = num_params, max(dp_world, 1)
    shard = lambda b: b // w
    opt = 3 * master_bytes * n                      # master + m + v
    plan = {
        "compute_params": shard(compute_bytes * n) if stage >= 3
        else compute_bytes * n,
        "gradients": shard(compute_bytes * n) if stage >= 2
        else compute_bytes * n,
        "optimizer_states": 0 if offload_optimizer
        else (shard(opt) if stage >= 1 else opt),
        "host_optimizer_states": (shard(opt) if stage >= 1 else opt)
        if offload_optimizer else 0,
        "activations": activation_bytes,
    }
    plan["device_total"] = (plan["compute_params"] + plan["gradients"]
                            + plan["optimizer_states"]
                            + plan["activations"])
    return plan


def sharded_init(init_fn: Callable[[], Any], ms: MeshSpec, stage: int,
                 param_specs: SpecTree = None) -> Any:
    """Materialize a parameter pytree directly into its ZeRO shardings.

    ref: deepspeed/runtime/zero/partition_parameters.py ``zero.Init`` — the
    reference intercepts ``Module.__init__`` so each rank only allocates its
    partition of every parameter.  Here the same guarantee falls out of XLA:
    ``init_fn`` is jitted with sharded ``out_shardings``, so (with JAX's
    partitionable threefry PRNG) each device generates and keeps only its
    own shard; the full tree never exists on one device.

    ``TrainingEngine`` applies this automatically when ``initialize()`` is
    given a callable ``params``; this helper is the standalone form.
    """
    abstract = jax.eval_shape(init_fn)
    shardings = param_shardings(abstract, ms, stage, param_specs)
    return jax.jit(init_fn, out_shardings=shardings)()


def unshard_params(params: Any, ms: MeshSpec):
    """Gather a stage-3 sharded pytree to replicated (for export/eval).

    ref: deepspeed/runtime/zero/partition_parameters.py GatheredParameters.
    """
    repl = ms.replicated()
    return jax.jit(lambda p: p, out_shardings=jax.tree.map(lambda _: repl, params))(params)
