"""ZeRO redundancy elimination as GSPMD shardings.

Reference: deepspeed/runtime/zero/stage_1_and_2.py (DeepSpeedZeroOptimizer),
deepspeed/runtime/zero/stage3.py + partition_parameters.py.

The reference implements ZeRO imperatively: flatten params into contiguous
buffers, round-robin 1-D chunks across the DP group, hook backward to
reduce-scatter gradients, and all-gather params around each use (stage 3),
with bucketing/overlap machinery to hide latency.

On TPU none of that machinery is needed — ZeRO *is* a sharding decision:

========  ======================  ==================  =====================
stage     optimizer state         gradients           parameters
========  ======================  ==================  =====================
0         replicated              replicated (psum)   replicated
1         sharded over data       replicated (psum)   replicated
2         sharded over data       sharded (r-scatter) replicated
3         sharded over data       sharded             sharded (AG at use)
========  ======================  ==================  =====================

We express each column as a per-leaf ``NamedSharding`` and let XLA insert
the exact all-gather / reduce-scatter schedule the reference hand-codes —
overlapped with compute by the XLA latency-hiding scheduler, riding ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.topology import MeshSpec, ZERO_AXES, shard_leaf_spec


def _zero_axis_size(ms: MeshSpec) -> int:
    n = 1
    for a in ZERO_AXES:
        n *= ms.size(a)
    return n


def _leaf_spec(leaf, ms: MeshSpec, base_spec_fn: Optional[Callable] = None) -> P:
    """Shard one leaf over the ZeRO (data) axis, on top of any model-parallel
    sharding the model already declared via ``base_spec_fn``."""
    shape = getattr(leaf, "shape", ())
    if len(shape) == 0:
        return P()
    base = base_spec_fn(leaf) if base_spec_fn else P()
    taken = list(base) + [None] * (len(shape) - len(base))
    return shard_leaf_spec(shape, "data", ms.size("data"), taken=taken)


def param_shardings(params: Any, ms: MeshSpec, stage: int,
                    base_spec_fn: Optional[Callable] = None):
    """Shardings for the master parameter pytree.

    ``base_spec_fn(leaf) -> PartitionSpec`` supplies model-parallel (TP)
    sharding; ZeRO stage 3 layers the data axis on top of it.
    """
    def one(leaf):
        base = base_spec_fn(leaf) if base_spec_fn else P()
        if stage >= 3 and _zero_axis_size(ms) > 1:
            return ms.sharding(_leaf_spec(leaf, ms, base_spec_fn))
        return ms.sharding(base)

    return jax.tree.map(one, params)


def optstate_shardings(opt_state: Any, ms: MeshSpec, stage: int,
                       base_spec_fn: Optional[Callable] = None):
    """Shardings for optimizer-state pytrees (m, v, master copies …).

    Stage >=1 shards every non-scalar leaf over the data axis
    (ref: stage_1_and_2.py partitions fp32 optimizer state).
    """
    def one(leaf):
        if stage >= 1 and _zero_axis_size(ms) > 1:
            return ms.sharding(_leaf_spec(leaf, ms, base_spec_fn))
        base = base_spec_fn(leaf) if base_spec_fn else P()
        return ms.sharding(base if getattr(leaf, "ndim", 0) else P())

    return jax.tree.map(one, opt_state)


def grad_constraint(grads: Any, ms: MeshSpec, stage: int,
                    base_spec_fn: Optional[Callable] = None):
    """Apply in-jit sharding constraints to gradients.

    Stage >=2: constrain each grad leaf to the data-sharded layout, which
    makes XLA produce a reduce-scatter instead of an all-reduce
    (ref: stage_1_and_2.py ``reduce_scatter_gradients``).
    """
    if stage < 2 or _zero_axis_size(ms) == 1:
        return grads

    def one(g):
        return jax.lax.with_sharding_constraint(
            g, ms.sharding(_leaf_spec(g, ms, base_spec_fn)))

    return jax.tree.map(one, grads)


def unshard_params(params: Any, ms: MeshSpec):
    """Gather a stage-3 sharded pytree to replicated (for export/eval).

    ref: deepspeed/runtime/zero/partition_parameters.py GatheredParameters.
    """
    repl = ms.replicated()
    return jax.jit(lambda p: p, out_shardings=jax.tree.map(lambda _: repl, params))(params)
