#!/usr/bin/env python
"""Param-stream phase evidence: is the optimizer pass hidden?

Runs the layer-streaming engine twice on the same model/batch —
``overlap_step`` on (default) vs off (the strict serialized pass) — and
records each mode's ``phase_report()``.  The claim under test (round-4
verdict weak #6): with overlap on, layer l's CPU-Adam + tier write runs
behind the vjps of layers l-1..0, so the EXPOSED optimizer cost is
``update_wait`` (the end-of-step join), which should be well under the
total ``host_adam`` work actually done — and the step should be faster
than strict mode by roughly the hidden fraction.

CPU-tier by default so it runs on any backend; --nvme measures the aio
tier.  Writes PARAM_STREAM_PHASES.json.

Usage:  python tools/pstream_phases.py [--layers 8] [--dim 256] [--nvme]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-tier phase evidence must NEVER land on the TPU (it would contend
# with on-chip benchmarking for HBM).  The container's sitecustomize
# pre-registers the axon backend, so the env var alone is not enough —
# force the platform in-process before any backend init (conftest trick).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def build(overlap, args, nvme_dir=None):
    import jax

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(
        dim=args.dim, n_layers=args.layers, n_heads=8, n_kv_heads=4,
        vocab_size=2048)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    off = {"device": "nvme", "nvme_path": nvme_dir} if nvme_dir else \
        {"device": "cpu", "scheduled": True}
    off["overlap_step"] = overlap
    eng, _, _, _ = dstpu.initialize(
        params=llama.layered_model(cfg, params),
        config={"train_micro_batch_size_per_gpu": args.batch,
                "zero_optimization": {"stage": 3, "offload_param": off},
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True}})
    return cfg, eng


def measure(eng, cfg, steps, seq):
    import numpy as np

    import jax.numpy as jnp

    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (eng.train_batch_size, seq + 1))
    batch = {"tokens": jnp.asarray(toks, jnp.int32)}
    eng.train_batch(batch)                       # compile + warm tier
    reports, times = [], []
    for _ in range(steps):
        t0 = time.perf_counter()
        eng.train_batch(batch)
        times.append(time.perf_counter() - t0)
        reports.append(eng.phase_report())
    mean = {k: round(sum(r[k] for r in reports) / len(reports), 4)
            for k in reports[0]}
    mean["step_s"] = round(sum(times) / len(times), 4)
    return mean


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--nvme", action="store_true")
    ap.add_argument("--json-out", default=os.path.join(
        REPO, "PARAM_STREAM_PHASES.json"))
    args = ap.parse_args()

    import tempfile

    import jax

    out = {"backend": jax.default_backend(),
           "model": {"layers": args.layers, "dim": args.dim,
                     "batch": args.batch, "seq": args.seq},
           "tier": "nvme" if args.nvme else "cpu", "modes": {}}
    for overlap in (True, False):
        nvme_dir = tempfile.mkdtemp(prefix="dstpu_phases_") \
            if args.nvme else None
        cfg, eng = build(overlap, args, nvme_dir)
        out["modes"]["overlap" if overlap else "strict"] = measure(
            eng, cfg, args.steps, args.seq)
    ov, st = out["modes"]["overlap"], out["modes"]["strict"]
    out["exposed_optimizer_s"] = {
        "overlap (update_wait)": ov["update_wait"],
        "strict (host_adam+tier_write)":
            round(st["host_adam"] + st["tier_write"], 4)}
    out["hidden_fraction"] = round(
        1.0 - ov["update_wait"] / max(ov["host_adam"], 1e-9), 4)
    out["step_speedup_strict_over_overlap"] = round(
        st["step_s"] / max(ov["step_s"], 1e-9), 4)
    with open(args.json_out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
