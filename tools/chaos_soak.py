#!/usr/bin/env python
"""Chaos soak: drive serving traffic under a deterministic injected
fault schedule and assert graceful degradation (ISSUE 9 acceptance).

The engine under test runs the full I/O-dependent stack — prefix
cache, tiered KV with an NVMe spill dir, SLO tiers, tracing, load
shedding — while a seeded :class:`~deepspeed_tpu.faults.FaultPlan`
injects aio read/write failures, read-latency spikes, spilled-page
corruption, slot-level exceptions, and a queue-pressure burst.  A
fault-free ORACLE engine (no tier, no faults, no shedding) serves
every distinct prompt first; the soak then asserts:

1. **zero token mismatches**: every request the chaos engine COMPLETED
   is token-identical to the oracle (greedy decode: output is a pure
   function of the prompt, so degraded paths — retries, sync
   fallbacks, checksum re-prefills, tier disablement — must never
   change tokens);
2. **no hangs**: a watchdog petted per step never fires, and the drive
   loop finishes under its wall cap;
3. **clean drain**: ``has_work`` goes false and the page-accounting
   leak check (``engine.check_leaks``) comes back empty;
4. **failures accounted for**: submitted == completed + failed + shed,
   and the counts reconcile across the typed results, the telemetry
   registry, the SLO per-tier lifetime counters, and the flight
   recorder's ``request_failed``/``request_shed`` events.

Stamped as CHAOS_SOAK.json (atomic) and gated by tools/bench_gate.py
(mismatched_requests / leak_count / watchdog_fired must stay 0,
accounting_ok must stay 1).

    python tools/chaos_soak.py --cpu --json-out CHAOS_SOAK.json
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MAX_NEW = 6
STEP_CAP = 3000
WALL_CAP_S = 480.0

# history + incidents blocks for the soaks (ISSUE 15): fast cadences
# AND a 50 ms fine ring so the short CPU run records real trajectories
# (the default 1 s ring would fold a whole soak wave into one bucket),
# a dedup window longer than any soak so each incident class yields
# EXACTLY one bundle, and a 60 s pre-window on a ring set whose span
# covers the >= 30 s acceptance bound
HISTORY_BLOCK = {"sample_interval_s": 0.05,
                 "rings": ((0.05, 600), (1.0, 120), (10.0, 360))}


def incidents_block(out_dir):
    return {"dir": out_dir, "eval_interval_s": 0.05,
            "pre_window_s": 60.0, "dedup_window_s": 600.0,
            "max_bundles": 8}


def incidents_summary(mgr, oracle_bundles=None):
    """Per-class bundle accounting for a soak stamp."""
    by_class = {}
    for b in mgr.bundles:
        by_class[b["incident"]] = by_class.get(b["incident"], 0) + 1
    out = {
        "bundles": len(mgr.bundles),
        "by_class": by_class,
        "suppressed": int(mgr.snapshot().get("suppressed", 0)),
        "pre_window_s": mgr.cfg.pre_window_s,
    }
    if oracle_bundles is not None:
        out["oracle_bundles"] = oracle_bundles
    return out


def load_bundle(mgr, cls):
    """First on-disk bundle of one incident class (None if absent)."""
    for b in mgr.bundles:
        if b["incident"] == cls and b.get("path"):
            with open(b["path"]) as f:
                return json.load(f)
    return None


def bundle_well_formed(bundle, trigger_phase):
    """The acceptance shape: the bundle's timeline carries the
    triggering event and the configured pre-window covers >= 30 s of
    history for the tracked series."""
    if bundle is None:
        return False
    trig = bundle.get("trigger", {})
    if trig.get("phase") != trigger_phase:
        return False
    if bundle.get("pre_window_s", 0) < 30.0:
        return False
    hist = bundle.get("history", {})
    rings = hist.get("rings", [])
    span_ok = any(r["period_s"] * r["capacity"] >= 30.0 for r in rings)
    return span_ok and bool(hist.get("series")) and \
        bool(bundle.get("ring"))


def build_traffic(vocab):
    """Deterministic phased workload: warm a shared prefix, flush it
    out of the small HBM pool (demote to the tier), revisit it (tier
    promotion), plus a burst wave and born-expired requests.  Returns
    ``(waves, burst_prompts, expired_prompts)`` — waves drain between
    submissions so the churn is reproducible."""
    import numpy as np

    rng = np.random.default_rng(11)
    pref = rng.integers(1, vocab, 16).tolist()
    mk = lambda: pref + rng.integers(1, vocab, 3).tolist()
    flush = [rng.integers(1, vocab, 24).tolist() for _ in range(4)]
    waves = [
        [mk(), mk()],                     # warm the shared prefix
        flush,                            # churn: prefix demotes
        [mk(), mk()],                     # revisit: tier promotion
        flush[:2] + [mk()],               # churn again + revisit
        [mk(), mk()],
    ]
    burst = [rng.integers(1, vocab, 12).tolist() for _ in range(10)]
    expired = [rng.integers(1, vocab, 8).tolist() for _ in range(3)]
    return waves, burst, expired


FAULT_RULES = [
    # transient aio read failures: retried, then sync-fallback
    {"subsystem": "aio_read", "rate": 0.5, "count": 8},
    # read-latency spikes
    {"subsystem": "aio_read", "mode": "latency", "latency_s": 0.02,
     "count": 5},
    # spill-write failures: bounded retry, then the entry drops
    {"subsystem": "aio_write", "rate": 0.3, "count": 4},
    # corrupt the first eight demoted pages: promote-side checksums
    # must catch every revisit of them and fall back to re-prefill
    {"subsystem": "kv_corrupt", "rate": 1.0, "count": 8},
    # slot-level exceptions targeting two requests that serve (r03 is
    # a burst request that beats the shed cut; r16 a tier revisit)
    {"subsystem": "slot", "match": "r03", "count": 1},
    {"subsystem": "slot", "match": "r16", "count": 1},
    # one queue-pressure burst (consumed by the traffic generator)
    {"subsystem": "burst", "rate": 1.0, "count": 1},
]


FLEET_FAULT_RULES = [
    # kill replica r1 on its 4th router-step poll — mid-traffic, with
    # requests queued and in flight there (failover: queued and
    # zero-token work re-submits to survivors, token-bearing slots
    # fail typed)
    {"subsystem": "replica", "mode": "error", "match": "r1",
     "count": 1, "after": 3},
    # one queue-pressure burst (consumed by the traffic generator):
    # aggregate depth past the fleet shed threshold → fleet-level
    # typed sheds on top of any per-replica ones
    {"subsystem": "burst", "rate": 1.0, "count": 1},
]


def fleet_main(args) -> int:
    """--fleet: the 3-replica soak (ISSUE 10 acceptance).  A seeded
    schedule kills one replica mid-traffic while the script drains and
    rejoins another; asserts every accepted request completes token-
    identical to a single-replica oracle or returns typed, zero leaks
    on every replica (dead one included), zero orphans, bounded
    failover recovery, and fleet accounting that reconciles across
    typed results, router counters and the rollup registry.  Stamps
    FLEET_SOAK.json, gated by tools/bench_gate.py."""
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu import faults
    from deepspeed_tpu.fleet import DEAD, DRAINING, fleet_router
    from deepspeed_tpu.inference.serving import (RequestFailed,
                                                 RequestShed,
                                                 serving_engine)
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.utils.evidence import atomic_write_json

    t_start = time.perf_counter()
    cfg = gpt2.GPT2Config.tiny(dim=64, n_layers=2, n_heads=4,
                               max_seq_len=128)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    waves, burst, expired = build_traffic(cfg.vocab_size)
    kw = dict(max_batch=2, page_size=8, num_pages=12, max_seq=64,
              prefill_bucket=8)

    # ---- single-replica fault-free oracle
    oracle_eng = serving_engine(params, cfg, prefix_cache=True, **kw)
    distinct, seen = [], set()
    for p in [p for w in waves for p in w] + burst + expired:
        t = tuple(p)
        if t not in seen:
            seen.add(t)
            distinct.append(p)
    for i, p in enumerate(distinct):
        oracle_eng.submit(f"o{i}", p, max_new_tokens=MAX_NEW)
    oracle_out = oracle_eng.run()
    oracle = {tuple(p): oracle_out[f"o{i}"]
              for i, p in enumerate(distinct)}
    oracle_eng.shutdown()

    router = fleet_router(
        params, cfg,
        fleet={"replicas": 3, "retry_budget": 2, "shed_queue_depth": 10,
               "digest_refresh_steps": 2},
        prefix_cache=True,
        slo={"tiers": {
            "interactive": {"ttft_s": 60.0, "deadline_s": 300.0},
            "expired": {"deadline_s": 0.001, "target": 0.5}},
            "default_tier": "interactive"},
        tracing={"ring_capacity": 65536},
        faults={"seed": args.seed, "rules": FLEET_FAULT_RULES},
        shed_queue_depth=4, shed_expired_deadline=True, **kw)

    prompts_by_id = {}
    rid = 0

    def submit(p, tier=None):
        nonlocal rid
        req_id = f"r{rid:02d}"
        rid += 1
        prompts_by_id[req_id] = p
        router.submit(req_id, p, max_new_tokens=MAX_NEW, tier=tier)
        return req_id

    t_kill = None
    salvaged = set()
    recovery_s = None

    def drive():
        nonlocal t_kill, salvaged, recovery_s
        steps = 0
        while router.has_work:
            router.step()
            if t_kill is None and router.last_failover is not None:
                # failover just ran inside this step: the router's
                # ledger names exactly the requests salvage re-placed
                # (inferring from resubmit counts would also catch
                # unrelated shed retries)
                t_kill = router.last_failover["t"]
                salvaged = set(router.last_failover["resubmitted"])
            if t_kill is not None and recovery_s is None and \
                    all(k in router.finished for k in salvaged):
                recovery_s = time.perf_counter() - t_kill
            steps += 1
            if steps > STEP_CAP or \
                    time.perf_counter() - t_start > WALL_CAP_S:
                return False
        return True

    hang = False
    drain_ok = True
    for w, wave in enumerate(waves):
        for p in wave:
            submit(p)
        _delay, fire = faults.poll("burst")
        if fire is not None:
            for p in burst:
                submit(p)
        hang = hang or not drive()
        if w == 1:
            # planned drain + rejoin of r2 between waves (the rolling-
            # restart primitive), while r1's kill rule is arming
            router.drain("r2")
            hang = hang or not drive()
            drain_ok = drain_ok and router.drained("r2") and \
                router.replicas["r2"].state == DRAINING
            router.rejoin("r2")
            drain_ok = drain_ok and \
                router.replicas["r2"].state == "healthy"
    for p in expired:
        submit(p, tier="expired")
    time.sleep(0.05)
    hang = hang or not drive()
    if recovery_s is None and t_kill is not None:
        recovery_s = time.perf_counter() - t_kill

    # ---- reconcile
    finished = dict(router.finished)
    completed = {k: v for k, v in finished.items()
                 if isinstance(v, list)}
    failed = {k: v for k, v in finished.items()
              if isinstance(v, RequestFailed)}
    shed = {k: v for k, v in finished.items()
            if isinstance(v, RequestShed)}
    mismatched = [k for k, v in completed.items()
                  if v != oracle[tuple(prompts_by_id[k])]]
    leaks = router.check_leaks()
    orphaned = router.orphaned()
    cnt = router.registry.snapshot()["counters"]
    status = router.statusz()
    ring = router.replicas["r0"].engine.tracer.recorder.events()
    checks = {
        "typed_results_partition":
            len(finished) == rid and
            len(completed) + len(failed) + len(shed) == rid,
        "router_counts":
            router._n_completed == len(completed) and
            router._n_failed == len(failed) and
            router._n_shed == len(shed),
        "registry_counters":
            int(cnt.get("fleet_completed_requests", 0)) ==
            len(completed) and
            int(cnt.get("fleet_failed_requests", 0)) == len(failed)
            and int(cnt.get("fleet_shed_requests", 0)) == len(shed),
        "failover_happened":
            router.replicas["r1"].state == DEAD and
            int(cnt.get("fleet_failovers", 0)) == 1,
        "trace_replica_events":
            sum(1 for e in ring if e[3] == "replica_dead") == 1 and
            sum(1 for e in ring if e[3] == "replica_drain") == 1 and
            sum(1 for e in ring if e[3] == "replica_rejoin") == 1,
        "drain_rejoin": drain_ok,
    }
    plan_snap = router._fault_plan.snapshot()
    router.shutdown()
    ok = (not mismatched and not hang and not leaks and not orphaned
          and all(checks.values()) and plan_snap["injected"] > 0
          and recovery_s is not None and recovery_s < 60.0)
    stamp = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "seed": args.seed,
        "replicas": 3,
        "ok": ok,
        "submitted": rid,
        "completed": len(completed),
        "failed": len(failed),
        "shed": len(shed),
        "shed_by_reason": dict(router._shed_by_reason),
        "resubmits": router._n_resubmits,
        "mismatched_requests": len(mismatched),
        "mismatched_ids": mismatched[:8],
        "hang": int(hang),
        "leak_count": len(leaks),
        "leaks": leaks[:8],
        "orphaned_requests": len(orphaned),
        "recovery_s": round(recovery_s, 3)
        if recovery_s is not None else None,
        "accounting_ok": int(all(checks.values())),
        "accounting": checks,
        "fleet": {k: v for k, v in status["fleet"].items()
                  if k != "replicas"},
        "replica_states": {r["replica"]: r["state"]
                           for r in status["fleet"]["replicas"]},
        "injected": plan_snap,
        "duration_s": round(time.perf_counter() - t_start, 2),
    }
    atomic_write_json(stamp, args.json_out)
    print(json.dumps({k: v for k, v in stamp.items()
                      if k not in ("injected", "fleet")},
                     indent=1, sort_keys=True))
    print("→", args.json_out)
    return 0 if ok else 1


DISAGG_FAULT_RULES = [
    # the first fabric EXPORT opportunity fails: that migration falls
    # back to re-prefill (counted, never wrong)
    {"subsystem": "fabric", "mode": "error", "match": "export",
     "count": 1},
    # fetch-latency spikes push migrations toward their timeout
    {"subsystem": "fabric", "mode": "latency", "match": "fetch",
     "latency_s": 0.01, "count": 3},
    # corrupt the first two pages published INTO the fabric after
    # their checksums were recorded: the admitting replica's
    # promotion-time crc must catch them and re-prefill (the
    # corrupt-after-checksum leg)
    {"subsystem": "fabric", "mode": "error", "match": "corrupt",
     "count": 2},
    # kill decode replica r2 mid-traffic — handed-off decode legs
    # queued or zero-token in flight there re-place on the survivors,
    # prefill legs re-run from the prompt
    {"subsystem": "replica", "mode": "error", "match": "r2",
     "count": 1, "after": 4},
    # one queue-pressure burst (consumed by the traffic generator)
    {"subsystem": "burst", "rate": 1.0, "count": 1},
]


def disagg_main(args) -> int:
    """--disagg: the disaggregated prefill/decode + KV-fabric soak
    (ISSUE 12 acceptance).  A roles-split fleet (1 prefill, 2 decode)
    serves phased shared-prefix traffic while the seeded schedule
    fails fabric exports, delays fetches, corrupts in-fabric pages
    after their checksums, and kills a decode replica mid-handoff;
    the script also drains + rejoins the ONLY prefill replica (role
    fallback).  Asserts: every completed request token-identical to a
    single-engine oracle, typed partition with zero orphans, zero
    leaks on every replica (dead one included), handoffs + migrations
    actually happened, and the corruption was caught by the importer's
    checksum.  Stamps DISAGG_SOAK.json, gated by bench_gate."""
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu import faults
    from deepspeed_tpu.fleet import DEAD, DRAINING, fleet_router
    from deepspeed_tpu.inference.serving import (RequestFailed,
                                                 RequestShed,
                                                 serving_engine)
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.utils.evidence import atomic_write_json

    t_start = time.perf_counter()
    cfg = gpt2.GPT2Config.tiny(dim=64, n_layers=2, n_heads=4,
                               max_seq_len=128)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    waves, burst, expired = build_traffic(cfg.vocab_size)
    kw = dict(max_batch=2, page_size=8, num_pages=24, max_seq=64,
              prefill_bucket=8, prefix_cache=True,
              kv_tier={"host_pool_bytes": 64 << 20})

    # ---- single-engine fault-free oracle
    oracle_eng = serving_engine(params, cfg, **kw)
    distinct, seen = [], set()
    for p in [p for w in waves for p in w] + burst + expired:
        t = tuple(p)
        if t not in seen:
            seen.add(t)
            distinct.append(p)
    for i, p in enumerate(distinct):
        oracle_eng.submit(f"o{i}", p, max_new_tokens=MAX_NEW)
    oracle_out = oracle_eng.run()
    oracle = {tuple(p): oracle_out[f"o{i}"]
              for i, p in enumerate(distinct)}
    oracle_eng.shutdown()

    router = fleet_router(
        params, cfg,
        fleet={"replicas": 3, "retry_budget": 2,
               "shed_queue_depth": 10, "digest_refresh_steps": 2,
               "roles": {"prefill": 1, "decode": 2}},
        fabric=True,
        slo={"tiers": {
            "interactive": {"ttft_s": 60.0, "deadline_s": 300.0},
            "expired": {"deadline_s": 0.001, "target": 0.5}},
            "default_tier": "interactive"},
        tracing={"ring_capacity": 65536},
        faults={"seed": args.seed, "rules": DISAGG_FAULT_RULES},
        shed_queue_depth=4, shed_expired_deadline=True, **kw)

    prompts_by_id = {}
    rid = 0

    def submit(p, tier=None):
        nonlocal rid
        req_id = f"r{rid:02d}"
        rid += 1
        prompts_by_id[req_id] = p
        router.submit(req_id, p, max_new_tokens=MAX_NEW, tier=tier)
        return req_id

    t_kill = None
    salvaged = set()
    recovery_s = None

    def drive():
        nonlocal t_kill, salvaged, recovery_s
        steps = 0
        while router.has_work:
            router.step()
            if t_kill is None and router.last_failover is not None:
                t_kill = router.last_failover["t"]
                salvaged = set(router.last_failover["resubmitted"])
            if t_kill is not None and recovery_s is None and \
                    all(k in router.finished for k in salvaged):
                recovery_s = time.perf_counter() - t_kill
            steps += 1
            if steps > STEP_CAP or \
                    time.perf_counter() - t_start > WALL_CAP_S:
                return False
        return True

    hang = False
    drain_ok = True
    for w, wave in enumerate(waves):
        for p in wave:
            submit(p)
        _delay, fire = faults.poll("burst")
        if fire is not None:
            for p in burst:
                submit(p)
        hang = hang or not drive()
        if w == 1:
            # drain + rejoin the ONLY prefill replica mid-soak: role
            # preference must degrade (prefill legs fall back to the
            # decode pool) and come back after rejoin
            router.drain("r0")
            hang = hang or not drive()
            drain_ok = drain_ok and router.drained("r0") and \
                router.replicas["r0"].state == DRAINING
            router.rejoin("r0")
            drain_ok = drain_ok and \
                router.replicas["r0"].state == "healthy"
    for p in expired:
        submit(p, tier="expired")
    time.sleep(0.05)
    hang = hang or not drive()
    if recovery_s is None and t_kill is not None:
        recovery_s = time.perf_counter() - t_kill

    # ---- reconcile
    finished = dict(router.finished)
    completed = {k: v for k, v in finished.items()
                 if isinstance(v, list)}
    failed = {k: v for k, v in finished.items()
              if isinstance(v, RequestFailed)}
    shed = {k: v for k, v in finished.items()
            if isinstance(v, RequestShed)}
    mismatched = [k for k, v in completed.items()
                  if v != oracle[tuple(prompts_by_id[k])]]
    leaks = router.check_leaks()
    orphaned = router.orphaned()
    cnt = router.registry.snapshot()["counters"]
    status = router.statusz()
    fab = status["fleet"]["fabric"]
    checksum_caught = sum(
        int(rep.engine.registry.snapshot()["counters"].get(
            "kv_tier_checksum_failures", 0))
        for rep in router.replicas.values())
    checks = {
        "typed_results_partition":
            len(finished) == rid and
            len(completed) + len(failed) + len(shed) == rid,
        "router_counts":
            router._n_completed == len(completed) and
            router._n_failed == len(failed) and
            router._n_shed == len(shed),
        "registry_counters":
            int(cnt.get("fleet_completed_requests", 0)) ==
            len(completed) and
            int(cnt.get("fleet_failed_requests", 0)) == len(failed)
            and int(cnt.get("fleet_shed_requests", 0)) == len(shed),
        "failover_happened":
            router.replicas["r2"].state == DEAD and
            int(cnt.get("fleet_failovers", 0)) == 1,
        "handoffs_happened": fab["handoffs"] > 0,
        "migrations_happened": fab["migrations"] >= 1,
        "export_faults_fell_back":
            fab["export_failures"] >= 1 and
            fab["migration_fallbacks"] >= 1,
        "corruption_caught_by_importer":
            fab["corrupted"] >= 1 and checksum_caught >= 1,
        "drain_rejoin": drain_ok,
    }
    plan_snap = router._fault_plan.snapshot()
    router.shutdown()
    ok = (not mismatched and not hang and not leaks and not orphaned
          and all(checks.values()) and plan_snap["injected"] > 0
          and recovery_s is not None and recovery_s < 60.0)
    stamp = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "seed": args.seed,
        "replicas": 3,
        "roles": {"prefill": 1, "decode": 2},
        "ok": ok,
        "submitted": rid,
        "completed": len(completed),
        "failed": len(failed),
        "shed": len(shed),
        "shed_by_reason": dict(router._shed_by_reason),
        "resubmits": router._n_resubmits,
        "handoffs": fab["handoffs"],
        "migrations": fab["migrations"],
        "migration_fallbacks": fab["migration_fallbacks"],
        "fabric_bytes_moved": fab["bytes_moved"],
        "checksum_caught": checksum_caught,
        "mismatched_requests": len(mismatched),
        "mismatched_ids": mismatched[:8],
        "hang": int(hang),
        "leak_count": len(leaks),
        "leaks": leaks[:8],
        "orphaned_requests": len(orphaned),
        "recovery_s": round(recovery_s, 3)
        if recovery_s is not None else None,
        "accounting_ok": int(all(checks.values())),
        "accounting": checks,
        "replica_states": {r["replica"]: r["state"]
                           for r in status["fleet"]["replicas"]},
        "injected": plan_snap,
        "duration_s": round(time.perf_counter() - t_start, 2),
    }
    atomic_write_json(stamp, args.json_out)
    print(json.dumps({k: v for k, v in stamp.items()
                      if k not in ("injected",)},
                     indent=1, sort_keys=True))
    print("→", args.json_out)
    return 0 if ok else 1


ELASTIC_FAULT_RULES = [
    # the FIRST autoscaler spawn attempt: engine-factory failure (the
    # scale-up aborts, is counted, and retries next evaluation)
    {"subsystem": "scale", "mode": "error", "count": 1},
    # the retry: a 30 ms slow cold-start (lands in the
    # autoscale_cold_start_seconds histogram)
    {"subsystem": "scale", "mode": "latency", "latency_s": 0.03,
     "count": 1, "after": 1},
]


def elastic_main(args) -> int:
    """--elastic: the autoscaler soak (ISSUE 11 acceptance).  A
    scripted load sine wave drives replica count up (through an
    injected factory failure + slow cold-start) and back down, a
    rolling weight update runs with one scripted mid-rollout replica
    kill, and a second rollout is halted and rolled back by an
    injected burn-rate trip.  Asserts: every completed request
    token-identical to the oracle (rollouts swap VALUE-identical
    weights relabeled v2/v3, so greedy outputs never change), every
    submitted request reaches a typed terminal result (nothing
    dropped), zero orphans and leaks on every replica, scale events
    observed in both directions, and every scale/rollout event in the
    trace ring exactly once.  Stamps ELASTIC_SOAK.json, gated by
    tools/bench_gate.py."""
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu.autoscale import FleetAutoscaler
    from deepspeed_tpu.fleet import DEAD, fleet_router
    from deepspeed_tpu.inference.serving import (RequestFailed,
                                                 RequestShed,
                                                 serving_engine)
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.telemetry import MetricsRegistry
    from deepspeed_tpu.utils.evidence import atomic_write_json

    t_start = time.perf_counter()
    cfg = gpt2.GPT2Config.tiny(dim=64, n_layers=2, n_heads=4,
                               max_seq_len=128)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    # value-identical trees under new version labels: the swap/rollback
    # machinery runs for real, while greedy outputs stay a pure
    # function of the prompt — the oracle stays valid across versions
    v2_params = jax.tree.map(lambda x: x, params)
    v3_params = jax.tree.map(lambda x: x, params)

    import numpy as np

    rng = np.random.default_rng(17)
    pref = rng.integers(1, cfg.vocab_size, 16).tolist()
    mk = lambda: pref + rng.integers(1, cfg.vocab_size, 3).tolist()
    low = [[rng.integers(1, cfg.vocab_size, 10).tolist(), mk()]
           for _ in range(3)]
    crest = [rng.integers(1, cfg.vocab_size, 12).tolist()
             for _ in range(22)]
    trickle = [mk() for _ in range(6)]
    strict_wave = [rng.integers(1, cfg.vocab_size, 8).tolist()
                   for _ in range(8)]

    all_prompts = [p for w in low for p in w] + crest + trickle \
        + strict_wave
    distinct, seen = [], set()
    for p in all_prompts:
        t = tuple(p)
        if t not in seen:
            seen.add(t)
            distinct.append(p)
    kw = dict(max_batch=2, page_size=8, num_pages=12, max_seq=64,
              prefill_bucket=8)
    oracle_eng = serving_engine(params, cfg, prefix_cache=True, **kw)
    for i, p in enumerate(distinct):
        oracle_eng.submit(f"o{i}", p, max_new_tokens=MAX_NEW)
    oracle_out = oracle_eng.run()
    oracle = {tuple(p): oracle_out[f"o{i}"]
              for i, p in enumerate(distinct)}
    oracle_eng.shutdown()

    slo = {"tiers": {
        "lax": {"ttft_s": 60.0, "deadline_s": 300.0, "target": 0.5},
        # impossible objective: any finished strict request violates,
        # so burn = 1/(1-0.5) = 2.0 — the injected burn-rate trip.
        # Short window: after the rollback the violations must age
        # out fast enough for the final trough to read as calm (a
        # burn still in-window is up-pressure, by design)
        "strict": {"ttft_s": 1e-6, "target": 0.5}},
        "default_tier": "lax", "window_s": 8.0,
        "burn_windows_s": [8.0]}
    ekw = dict(prefix_cache=True, slo=slo, shed_queue_depth=6, **kw)
    inc_dir = tempfile.mkdtemp(prefix="dstpu_elastic_inc_")
    router = fleet_router(
        params, cfg,
        fleet={"replicas": 2, "retry_budget": 2,
               "shed_queue_depth": 16,
               # scaling, not quarantine, is the elastic response to
               # crest-of-wave shed activity
               "quarantine_after": 10_000,
               "digest_refresh_steps": 2},
        tracing={"ring_capacity": 131072},
        faults={"seed": args.seed, "rules": ELASTIC_FAULT_RULES},
        # fleet-level incident engine (ISSUE 15): the shared flight
        # recorder carries every replica's slo_burn_alert plus the
        # autoscaler's rollout_halt/rolled_back — the scripted burn
        # rollback below must land a "rollback" bundle
        history=dict(HISTORY_BLOCK),
        incidents=incidents_block(inc_dir),
        **ekw)

    def factory(rid, streamed=False):
        return serving_engine(
            params, cfg, replica_id=rid, tracing=router.tracer,
            telemetry=MetricsRegistry(namespace=f"dstpu_{rid}"),
            **ekw)

    auto = FleetAutoscaler(router, factory, autoscale={
        # floor 2: the trough must not shrink the fleet below the
        # rollout script's needs (a real fleet would pick its floor
        # for the same reason — rolling updates need a survivor)
        "min_replicas": 2, "max_replicas": 3,
        "eval_interval_steps": 2, "scale_up_queue_depth": 3.0,
        "scale_down_queue_depth": 0.5, "up_after": 1, "down_after": 6,
        # the tiny CPU model drains a burst in tens of milliseconds —
        # any wall-clock cooldown would outlive the pressure window,
        # so the soak runs uncooled and leans on the streak hysteresis
        "cooldown_s": 0.0, "rollout_soak_steps": 25,
        "rollback_burn_threshold": 1.0, "rollback_min_finished": 1})

    prompts_by_id = {}
    rid_n = 0

    def submit(p, tier=None):
        nonlocal rid_n
        req_id = f"r{rid_n:03d}"
        rid_n += 1
        prompts_by_id[req_id] = p
        router.submit(req_id, p, max_new_tokens=MAX_NEW, tier=tier)
        return req_id

    hang = False

    def drive(until=None):
        """Step until idle (and `until` satisfied, when given)."""
        nonlocal hang
        steps = 0
        while router.has_work or auto.rollout_active \
                or auto._retiring or (until is not None and
                                      not until()):
            auto.step()
            steps += 1
            if steps > STEP_CAP or \
                    time.perf_counter() - t_start > WALL_CAP_S:
                hang = True
                return

    def idle_until_live(n, timeout_s=20.0):
        """Tick the idle fleet until the live replica count reaches
        ``n`` (scale-down retires the surplus; heal spawns cover a
        deficit) — the trough half of the sine wave."""
        nonlocal hang
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < timeout_s:
            auto.step()
            live_n = sum(1 for rep in router.replicas.values()
                         if rep.state != DEAD)
            if live_n == n and not auto._retiring \
                    and not router.has_work:
                return
            time.sleep(0.002)
        hang = True

    # ---- phase A: trough traffic (2 replicas idle along)
    for wave in low:
        for p in wave:
            submit(p)
        drive()
    # ---- phase B: crest — a burst the 2-replica fleet cannot absorb
    # scales up THROUGH the injected factory failure (first attempt)
    # and the slow cold-start (the retry lands at the next pressured
    # evaluation while the queue is still deep)
    for p in crest:
        submit(p)
    drive()
    scale_up_seen = auto.status()["scale_ups"]
    # ---- phase B2: trough — sustained idle retires the crest's
    # extra replica back down to the floor
    idle_until_live(2)
    scale_down_seen = auto.status()["scale_downs"]
    # ---- phase C: rolling update to v2 with one scripted mid-rollout
    # replica kill (the next not-yet-updated target dies right after
    # the first replica updates; the walk continues on survivors)
    auto.rollout(v2_params, version="v2")
    killed = None
    ti = 0
    steps = 0
    while auto.rollout_active or router.has_work:
        if ti < len(trickle):
            submit(trickle[ti])
            ti += 1
        auto.step()
        ro = auto._rollout
        if killed is None and ro is not None and ro["updated"]:
            nxt = next(
                (r for r in ro["plan"][ro["i"]:]
                 if r in router.replicas
                 and router.replicas[r].state != DEAD
                 and r not in ro["updated"]), None)
            if nxt is not None:
                router.kill(nxt, error="scripted mid-rollout death")
                killed = nxt
        steps += 1
        if steps > STEP_CAP or \
                time.perf_counter() - t_start > WALL_CAP_S:
            hang = True
            break
    rollout1 = dict(auto.last_rollout or {})
    # ---- phase C2: the kill left the fleet under its floor — the
    # next evaluations heal it back up, and the fresh replica swaps
    # onto v2 (the completed rollout's version) before it serves
    idle_until_live(2)
    # ---- phase D: rollout to v3 halted by the strict tier's burn
    # trip and rolled back — versions must return to v2
    auto.rollout(v3_params, version="v3")
    si = 0
    steps = 0
    while auto.rollout_active or router.has_work:
        if si < len(strict_wave):
            submit(strict_wave[si], tier="strict")
            si += 1
        auto.step()
        steps += 1
        if steps > STEP_CAP or \
                time.perf_counter() - t_start > WALL_CAP_S:
            hang = True
            break
    rollout2 = dict(auto.last_rollout or {})
    # ---- phase E: final trough — the fleet settles at its floor
    idle_until_live(auto.cfg.min_replicas)
    # final evaluation: classify anything the last steps landed
    router.incident_mgr.evaluate()

    # ---- reconcile
    finished = dict(router.finished)
    completed = {k: v for k, v in finished.items()
                 if isinstance(v, list)}
    failed = {k: v for k, v in finished.items()
              if isinstance(v, RequestFailed)}
    shed = {k: v for k, v in finished.items()
            if isinstance(v, RequestShed)}
    mismatched = [k for k, v in completed.items()
                  if v != oracle[tuple(prompts_by_id[k])]]
    leaks = router.check_leaks()
    orphaned = router.orphaned()
    cnt = router.registry.snapshot()["counters"]
    st = auto.status()
    # incidents (ISSUE 15): the burn-tripped rollback must have
    # produced a (deduped) rollback bundle carrying the rollout_halt
    # trigger and the pre-trip history window
    inc = incidents_summary(router.incident_mgr)
    inc["rollback_bundles"] = inc["by_class"].get("rollback", 0)
    rb_bundle = load_bundle(router.incident_mgr, "rollback")
    inc["rollback_bundle_well_formed"] = int(
        bundle_well_formed(rb_bundle, "rollout_halt"))
    incidents_ok = (inc["rollback_bundles"] >= 1
                    and inc["rollback_bundle_well_formed"] == 1)
    live_versions = {rep.id: str(rep.version)
                     for rep in router.replicas.values()
                     if rep.state != DEAD}
    ring = router.tracer.recorder.events()
    from collections import Counter
    ring_kinds = Counter(e[3] for e in ring
                         if e[3].startswith(("autoscale_",
                                             "rollout_")))
    led_kinds = Counter(e["kind"] for e in auto.events)
    checks = {
        "typed_results_partition":
            len(finished) == rid_n and
            len(completed) + len(failed) + len(shed) == rid_n,
        "router_counts":
            router._n_completed == len(completed) and
            router._n_failed == len(failed) and
            router._n_shed == len(shed),
        "registry_counters":
            int(cnt.get("fleet_completed_requests", 0)) ==
            len(completed) and
            int(cnt.get("fleet_failed_requests", 0)) == len(failed)
            and int(cnt.get("fleet_shed_requests", 0)) == len(shed),
        "scaled_up": st["scale_ups"] >= 2 and scale_up_seen >= 1,
        "scaled_down": st["scale_downs"] >= 1
            and scale_down_seen >= 1,
        "factory_failure_retried":
            st["factory_failures"] == 1 and st["scale_ups"] >= 1,
        "rollout_completed_with_kill":
            rollout1.get("completed", False) and killed is not None
            and rollout1.get("skipped") == [killed],
        "rollback_on_burn_trip":
            rollout2.get("halted", False)
            and rollout2.get("rolled_back", False),
        "versions_on_v2":
            bool(live_versions)
            and all(v == "v2" for v in live_versions.values()),
        "events_exactly_once":
            bool(led_kinds) and dict(ring_kinds) == dict(led_kinds),
    }
    plan_snap = router._fault_plan.snapshot()
    router.shutdown()
    ok = (not mismatched and not hang and not leaks and not orphaned
          and all(checks.values()) and plan_snap["injected"] >= 2
          and incidents_ok)
    stamp = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "seed": args.seed,
        "ok": ok,
        "submitted": rid_n,
        "completed": len(completed),
        "failed": len(failed),
        "shed": len(shed),
        "shed_by_reason": dict(router._shed_by_reason),
        "mismatched_requests": len(mismatched),
        "mismatched_ids": mismatched[:8],
        "hang": int(hang),
        "leak_count": len(leaks),
        "leaks": leaks[:8],
        "orphaned_requests": len(orphaned),
        "accounting_ok": int(all(checks.values())),
        "accounting": checks,
        "scale_ups": st["scale_ups"],
        "scale_downs": st["scale_downs"],
        "factory_failures": st["factory_failures"],
        "killed_mid_rollout": killed,
        "rollout_v2": rollout1,
        "rollout_v3": rollout2,
        "live_versions": live_versions,
        "event_counts": dict(led_kinds),
        "incidents": inc,
        "injected": plan_snap,
        "duration_s": round(time.perf_counter() - t_start, 2),
    }
    atomic_write_json(stamp, args.json_out)
    print(json.dumps({k: v for k, v in stamp.items()
                      if k not in ("injected",)},
                     indent=1, sort_keys=True))
    print("→", args.json_out)
    return 0 if ok else 1


PROC_FAULT_RULES = [
    # one corrupted parent->r0 frame: the CHILD's ring consumer
    # rejects it by crc (the frame never decodes to wrong bytes) and
    # the proxy's idempotent rpc retry resends — correctness never
    # rides the wire.  Armed past the warmup sends so the one rpc
    # timeout it costs lands mid-soak, not on a first-compile step
    {"subsystem": "transport", "mode": "error", "match": "corrupt:r0",
     "count": 1, "after": 30},
    # recv-side latency spikes on r2's channel (the wire slows, the
    # stream stays ordered)
    {"subsystem": "transport", "mode": "latency", "match": "recv:r2",
     "latency_s": 0.01, "count": 5},
    # one injected recv failure on r2, absorbed by the rpc retry
    {"subsystem": "transport", "mode": "error", "match": "recv:r2",
     "count": 1},
]


def procs_main(args) -> int:
    """--procs: the out-of-process fleet soak (ISSUE 20 acceptance).
    Three REAL child replica processes serve behind the wire while the
    scripted schedule corrupts and delays transport frames, and the
    soak delivers an ACTUAL SIGKILL to one child mid-generation.
    Asserts: every completed request token-identical to a single
    in-process oracle, typed partition (nothing silently dropped,
    nothing generated twice), zero leaks on the survivors and zero
    orphaned requests, bounded recovery measured from the kill
    SIGNAL, exactly one replica_failover incident bundle, and no
    orphan child processes after shutdown.  Stamps PROC_SOAK.json,
    gated by tools/bench_gate.py."""
    import signal as _signal

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    # the children pin this flag (tools/replica_child.py); the oracle
    # must draw the same init params or every token comparison is
    # cross-model noise
    jax.config.update("jax_threefry_partitionable", True)

    import numpy as np

    from deepspeed_tpu.inference.serving import (RequestFailed,
                                                 RequestShed,
                                                 serving_engine)
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.proc_fleet import (DEFAULT_CHILD_SPEC,
                                          proc_fleet_router)
    from deepspeed_tpu.utils.evidence import atomic_write_json

    t_start = time.perf_counter()
    spec = DEFAULT_CHILD_SPEC
    cfg = gpt2.GPT2Config.tiny(**{k: v for k, v in
                                  spec["model"].items()
                                  if k != "family"})
    params = gpt2.init_params(jax.random.PRNGKey(spec["seed"]), cfg)
    rng = np.random.default_rng(args.seed + 31)
    # enough tokens that the fleet is still mid-generation when the
    # kill lands: the children step their engines autonomously
    # between polls, so a short workload can drain before the router
    # ever observes the death
    max_new = 12
    prompts = [rng.integers(1, cfg.vocab_size, 6).tolist()
               for _ in range(24)]

    # ---- single in-process fault-free oracle (identical params: the
    # children rebuild from the same (model, seed) spec)
    oracle_eng = serving_engine(params, cfg, **spec["engine"])
    for i, p in enumerate(prompts):
        oracle_eng.submit(f"o{i}", p, max_new_tokens=max_new)
    oracle_out = oracle_eng.run()
    oracle = {f"r{i:02d}": oracle_out[f"o{i}"]
              for i in range(len(prompts))}
    oracle_eng.shutdown()

    inc_dir = tempfile.mkdtemp(prefix="dstpu-proc-incidents-")
    # poll_timeout_s stays at its 10 s default: a child's FIRST steps
    # pay XLA compiles, and a tighter rpc bound reads a compiling
    # child as a dead one on a slow box
    router = proc_fleet_router(
        spec,
        proc_fleet={"replicas": 3},
        fleet={"replicas": 3, "retry_budget": 2,
               "digest_refresh_steps": 2},
        tracing={"ring_capacity": 65536},
        faults={"seed": args.seed, "rules": PROC_FAULT_RULES},
        history=dict(HISTORY_BLOCK),
        incidents=incidents_block(inc_dir))

    spawn_s = time.perf_counter() - t_start
    t_kill = None
    salvaged = set()
    recovery_s = None
    hang = False
    try:
        for i, p in enumerate(prompts):
            router.submit(f"r{i:02d}", p, max_new_tokens=max_new)
        steps = 0
        while router.has_work:
            router.step()
            steps += 1
            if t_kill is None and steps == 1:
                # a REAL SIGKILL mid-generation, right after the first
                # harvest: no drain, no goodbye frame — the address
                # space just vanishes with requests queued and in
                # flight on r1
                t_kill = router.kill_child("r1", _signal.SIGKILL)
            fo_now = router.last_failover
            if not salvaged and fo_now is not None and \
                    fo_now.get("replica") == "r1":
                salvaged = set(fo_now["resubmitted"])
            if t_kill is not None and recovery_s is None and \
                    fo_now is not None and \
                    fo_now.get("replica") == "r1" and \
                    all(k in router.finished for k in salvaged):
                recovery_s = time.perf_counter() - t_kill
            if steps > STEP_CAP or \
                    time.perf_counter() - t_start > WALL_CAP_S:
                hang = True
                break
        if recovery_s is None and t_kill is not None:
            recovery_s = time.perf_counter() - t_kill

        # ---- reconcile
        finished = dict(router.finished)
        completed = {k: v for k, v in finished.items()
                     if isinstance(v, list)}
        failed = {k: v for k, v in finished.items()
                  if isinstance(v, RequestFailed)}
        shed = {k: v for k, v in finished.items()
                if isinstance(v, RequestShed)}
        mismatched = [k for k, v in completed.items()
                      if list(v) != list(oracle[k])]
        leaks = router.check_leaks()
        orphaned = router.orphaned()
        cnt = router.registry.snapshot()["counters"]
        fo = router.last_failover or {}
        ring = router.tracer.recorder.events()
        # wire accounting: every channel lives in THIS process, so the
        # injected schedule must be visible in the per-replica
        # transport families (the child-side corrupt detection happens
        # in the child; the router sees the injection + the retry)
        wire = {}
        for rep in router.replicas.values():
            c = rep.engine.registry.snapshot()["counters"]
            for k, v in c.items():
                if k.startswith("transport_"):
                    wire[k] = wire.get(k, 0) + int(v)
        inc = incidents_summary(router.incident_mgr)
        fo_bundles = inc["by_class"].get("replica_failover", 0)
        fo_bundle = load_bundle(router.incident_mgr,
                                "replica_failover")
        plan_snap = router._fault_plan.snapshot()
        checks = {
            "typed_results_partition":
                len(finished) == len(prompts) and
                len(completed) + len(failed) + len(shed)
                == len(prompts),
            "failover_happened":
                fo.get("replica") == "r1" and
                int(cnt.get("fleet_failovers", 0)) == 1,
            "never_double_generate":
                set(fo.get("resubmitted", [])).isdisjoint(
                    fo.get("failed_typed", [])),
            "trace_replica_dead":
                sum(1 for e in ring if e[3] == "replica_dead") == 1,
            "failover_bundle":
                fo_bundles == 1 and
                bundle_well_formed(fo_bundle, "replica_dead"),
            "wire_faults_injected":
                wire.get("transport_injected_faults", 0) >= 2 and
                plan_snap["injected"] >= 2,
            "wire_moved_bytes":
                wire.get("transport_tx_frames", 0) > 0 and
                wire.get("transport_rx_bytes", 0) > 0,
        }
        replica_states = {rid: rep.state
                          for rid, rep in router.replicas.items()}
    finally:
        procs = [rep.engine.proc
                 for rep in router.replicas.values()]
        router.shutdown()
    reaped = all(p.poll() is not None for p in procs)
    checks["no_orphan_processes"] = reaped
    ok = (not mismatched and not hang and not leaks and not orphaned
          and all(checks.values())
          and recovery_s is not None and recovery_s < 60.0)
    stamp = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "seed": args.seed,
        "replicas": 3,
        "transport": "shm",
        "ok": ok,
        "submitted": len(prompts),
        "completed": len(completed),
        "failed": len(failed),
        "shed": len(shed),
        "resubmitted": len(fo.get("resubmitted", [])),
        "failed_typed": len(fo.get("failed_typed", [])),
        "mismatched_requests": len(mismatched),
        "mismatched_ids": mismatched[:8],
        "hang": int(hang),
        "leak_count": len(leaks),
        "orphaned_requests": len(orphaned),
        "orphan_processes": int(not reaped),
        "recovery_s": round(recovery_s, 3)
        if recovery_s is not None else None,
        "spawn_s": round(spawn_s, 2),
        "accounting_ok": int(all(checks.values())),
        "accounting": checks,
        "replica_states": replica_states,
        "wire": wire,
        "incidents": inc,
        "injected": plan_snap,
        "duration_s": round(time.perf_counter() - t_start, 2),
    }
    atomic_write_json(stamp, args.json_out)
    print(json.dumps({k: v for k, v in stamp.items()
                      if k not in ("injected", "wire", "incidents")},
                     indent=1, sort_keys=True))
    print("→", args.json_out)
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend in-process")
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-plan seed (same seed = same schedule)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the 3-replica fleet soak (replica kill + "
                         "drain/rejoin) instead of the single-engine "
                         "soak; stamps FLEET_SOAK.json by default")
    ap.add_argument("--elastic", action="store_true",
                    help="run the autoscaler soak (load sine wave, "
                         "scale up/down through injected scale "
                         "faults, rolling update with a mid-rollout "
                         "kill, burn-trip rollback); stamps "
                         "ELASTIC_SOAK.json by default")
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregated prefill/decode + KV "
                         "fabric soak (fabric export/fetch/corrupt "
                         "faults + mid-handoff decode-replica kill + "
                         "prefill-pool drain); stamps "
                         "DISAGG_SOAK.json by default")
    ap.add_argument("--procs", action="store_true",
                    help="run the out-of-process fleet soak (3 child "
                         "replica processes over the shm wire, "
                         "scripted transport corrupt/latency faults, "
                         "a real mid-generation SIGKILL); stamps "
                         "PROC_SOAK.json by default")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()
    if args.json_out is None:
        args.json_out = os.path.join(
            REPO, "ELASTIC_SOAK.json" if args.elastic
            else "DISAGG_SOAK.json" if args.disagg
            else "FLEET_SOAK.json" if args.fleet
            else "PROC_SOAK.json" if args.procs
            else "CHAOS_SOAK.json")
    if args.elastic:
        return elastic_main(args)
    if args.disagg:
        return disagg_main(args)
    if args.fleet:
        return fleet_main(args)
    if args.procs:
        return procs_main(args)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu import faults
    from deepspeed_tpu.inference.serving import (RequestFailed,
                                                 RequestShed,
                                                 serving_engine)
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.utils.evidence import atomic_write_json
    from deepspeed_tpu.utils.watchdog import Watchdog

    t_start = time.perf_counter()
    cfg = gpt2.GPT2Config.tiny(dim=64, n_layers=2, n_heads=4,
                               max_seq_len=128)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    waves, burst, expired = build_traffic(cfg.vocab_size)

    kw = dict(max_batch=2, page_size=8, num_pages=12, max_seq=64,
              prefill_bucket=8,
              # compile sentinel on BOTH arms: a soak that survives
              # faults, shedding and tier churn must also never
              # recompile after its first token — the stamp's
              # steady_state_recompiles is gated at exactly 0
              devprof={"sample_rate": 0.05})

    # ---- fault-free oracle: every distinct prompt's greedy completion.
    # The oracle ALSO runs history+incidents (same cadences as the
    # chaos arm): it is the false-positive gate — a fault-free run must
    # produce ZERO bundles (gated in BENCH_BASELINE).
    oracle_inc_dir = tempfile.mkdtemp(prefix="dstpu_chaos_oracle_inc_")
    oracle_eng = serving_engine(params, cfg, prefix_cache=True,
                                history=dict(HISTORY_BLOCK),
                                incidents=incidents_block(oracle_inc_dir),
                                **kw)
    distinct = []
    seen = set()
    for p in [p for w in waves for p in w] + burst + expired:
        t = tuple(p)
        if t not in seen:
            seen.add(t)
            distinct.append(p)
    for i, p in enumerate(distinct):
        oracle_eng.submit(f"o{i}", p, max_new_tokens=MAX_NEW)
    oracle_out = oracle_eng.run()
    oracle = {tuple(p): oracle_out[f"o{i}"]
              for i, p in enumerate(distinct)}
    oracle_bundles = len(oracle_eng.incident_mgr.bundles)
    oracle_eng.shutdown()

    # ---- the chaos engine: full I/O-tier stack + shedding + faults +
    # the incident engine.  burn_threshold 1.5 makes the expired tier's
    # burn (violation rate 1.0 / budget 0.5 = 2.0) a SCRIPTED trip in
    # every window — the slo_burn_alert the incident engine must turn
    # into exactly one bundle.
    nvme_dir = tempfile.mkdtemp(prefix="dstpu_chaos_nvme_")
    dump_dir = tempfile.mkdtemp(prefix="dstpu_chaos_dump_")
    inc_dir = tempfile.mkdtemp(prefix="dstpu_chaos_inc_")
    eng = serving_engine(
        params, cfg, prefix_cache=True,
        kv_tier={"enabled": True, "host_pool_bytes": 4096,
                 "nvme_dir": nvme_dir, "io_retries": 2,
                 "io_retry_backoff_s": 0.01, "disable_after": 0},
        slo={"tiers": {
            "interactive": {"ttft_s": 60.0, "deadline_s": 300.0},
            "expired": {"deadline_s": 0.001, "target": 0.5}},
            "default_tier": "interactive", "burn_threshold": 1.5},
        tracing={"ring_capacity": 65536, "dump_dir": dump_dir},
        faults={"seed": args.seed, "rules": FAULT_RULES},
        history=dict(HISTORY_BLOCK),
        incidents=incidents_block(inc_dir),
        shed_queue_depth=6, shed_expired_deadline=True, **kw)
    wd = Watchdog(timeout_s=120.0, abort_on_timeout=False).start()
    eng.attach_watchdog(wd)

    prompts_by_id = {}
    rid = 0

    def submit(p, tier=None):
        nonlocal rid
        req_id = f"r{rid:02d}"
        rid += 1
        prompts_by_id[req_id] = p
        eng.submit(req_id, p, max_new_tokens=MAX_NEW, tier=tier)
        return req_id

    def drive():
        steps = 0
        while eng.has_work:
            eng.step()
            wd.pet()
            steps += 1
            if steps > STEP_CAP or \
                    time.perf_counter() - t_start > WALL_CAP_S:
                return False
        return True

    hang = False
    for w, wave in enumerate(waves):
        for p in wave:
            submit(p)
        # the burst rule fires once (deterministically) between waves:
        # a saturation spike past shed_queue_depth → queue-depth sheds
        _delay, fire = faults.poll("burst")
        if fire is not None:
            for p in burst:
                submit(p)
        hang = hang or not drive()
    # born-expired requests: deadline shedding at admission
    for p in expired:
        submit(p, tier="expired")
    time.sleep(0.05)
    hang = hang or not drive()
    wd.stop()
    # one final evaluation: a trigger event landed by the very last
    # step must still be classified (the drive loop exits before the
    # next tick would have drained it)
    eng.incident_mgr.evaluate()

    # ---- reconcile
    finished = dict(eng.finished)
    completed = {k: v for k, v in finished.items()
                 if isinstance(v, list)}
    failed = {k: v for k, v in finished.items()
              if isinstance(v, RequestFailed)}
    shed = {k: v for k, v in finished.items()
            if isinstance(v, RequestShed)}
    mismatched = [k for k, v in completed.items()
                  if v != oracle[tuple(prompts_by_id[k])]]
    leaks = eng.check_leaks()

    cnt = eng.registry.snapshot()["counters"]
    slo_snap = eng.slo_tracker.snapshot()
    slo_shed = sum(t["lifetime"]["shed"]
                   for t in slo_snap["tiers"].values())
    slo_failed = sum(t["lifetime"]["failed"]
                     for t in slo_snap["tiers"].values())
    ring = eng.tracer.recorder.events()
    ring_shed = sum(1 for e in ring if e[3] == "request_shed")
    ring_failed = sum(1 for e in ring if e[3] == "request_failed")
    checks = {
        "typed_results_partition":
            len(finished) == rid and
            len(completed) + len(failed) + len(shed) == rid,
        "engine_counts":
            eng._n_shed == len(shed) and eng._n_failed == len(failed),
        "telemetry_counters":
            int(cnt.get("serving_shed_requests", 0)) == len(shed) and
            int(cnt.get("serving_failed_requests", 0)) == len(failed),
        "slo_lifetime":
            slo_shed == len(shed) and slo_failed == len(failed),
        "trace_events":
            ring_shed == len(shed) and ring_failed == len(failed),
    }
    # ---- incidents (ISSUE 15 acceptance): the scripted burn trip
    # (slot faults -> interactive-tier violations -> multiwindow burn)
    # must yield EXACTLY ONE slo_burn bundle whose timeline carries the
    # triggering event plus a >= 30 s pre-window of history; the
    # fault-free oracle arm must have produced ZERO bundles.
    inc = incidents_summary(eng.incident_mgr,
                            oracle_bundles=oracle_bundles)
    burn_bundle = load_bundle(eng.incident_mgr, "slo_burn")
    inc["burn_bundles"] = inc["by_class"].get("slo_burn", 0)
    inc["burn_bundle_well_formed"] = int(
        bundle_well_formed(burn_bundle, "slo_burn_alert"))
    incidents_ok = (inc["burn_bundles"] == 1
                    and inc["burn_bundle_well_formed"] == 1
                    and inc["oracle_bundles"] == 0)
    if burn_bundle is not None:
        # the committed sample the slow lane re-stamps each cadence:
        # incident_report renders it, tier-1 parses it
        sample_path = os.path.join(REPO, "INCIDENT_SAMPLE.json")
        from deepspeed_tpu.utils.evidence import atomic_write_json \
            as _awj
        _awj(burn_bundle, sample_path)
        inc["sample"] = os.path.basename(sample_path)

    plan_snap = eng._fault_plan.snapshot()
    devprof_snap = eng.statusz().get("devprof", {})
    eng.shutdown()

    healthz = eng.healthz()
    robustness = eng._robustness_status(time.perf_counter())
    ok = (not mismatched and not hang and not wd.fired
          and not leaks and all(checks.values())
          and plan_snap["injected"] > 0 and len(failed) > 0
          and len(shed) > 0 and incidents_ok)
    stamp = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "seed": args.seed,
        "ok": ok,
        "submitted": rid,
        "completed": len(completed),
        "failed": len(failed),
        "shed": len(shed),
        "shed_by_reason": dict(eng._shed_by_reason),
        "mismatched_requests": len(mismatched),
        "mismatched_ids": mismatched[:8],
        "watchdog_fired": int(wd.fired),
        "hang": int(hang),
        "leak_count": len(leaks),
        "leaks": leaks[:8],
        "accounting_ok": int(all(checks.values())),
        "accounting": checks,
        "kv_tier": {
            "demoted": int(eng.allocator.demoted),
            "promoted": int(eng.allocator.promoted),
            "fallback_events": eng._n_kvt_fallbacks,
            "checksum_failures": eng._n_kvt_checksum,
            "spill_failures": eng._kv_pool.spill_failures,
            "disabled": eng._kv_pool.disabled,
        },
        "io_retries": {k: int(v) for k, v in cnt.items()
                       if k.endswith(("_io_retries", "_sync_fallbacks",
                                      "_write_retries")) and v},
        "incidents": inc,
        # the zero-recompile contract under chaos: faults, shedding and
        # tier churn must never push the engine onto an uncompiled
        # shape after its first token (bench_gate pins this at 0)
        "steady_state_recompiles": int(
            devprof_snap.get("compiles_steady", 0)),
        "devprof": {
            "compiles_warmup": int(
                devprof_snap.get("compiles_warmup", 0)),
            "mfu": devprof_snap.get("mfu", 0.0),
            "mbu": devprof_snap.get("mbu", 0.0),
            "host_device_gap_s": devprof_snap.get("host_device_gap_s"),
            "device_seconds": devprof_snap.get("device_seconds", {}),
        },
        "injected": plan_snap,
        "degraded_at_end": healthz["degraded"],
        "robustness": robustness,
        "duration_s": round(time.perf_counter() - t_start, 2),
    }
    atomic_write_json(stamp, args.json_out)
    print(json.dumps({k: v for k, v in stamp.items()
                      if k not in ("injected", "robustness")},
                     indent=1, sort_keys=True))
    print("→", args.json_out)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
