#!/usr/bin/env python
"""dstpu_top: the serving "htop" — poll an engine's ``/statusz`` and
render slots, queue, KV/prefix-cache occupancy, speculation acceptance
and per-tier SLO burn live in the terminal.

The engine side is the introspection server the telemetry HTTP sink
grew in PR 6: point any engine at a port (``telemetry.http_port`` in
the config block) and this tool at the same port.

    python tools/dstpu_top.py --url http://127.0.0.1:8080
    python tools/dstpu_top.py --url ... --interval 1
    python tools/dstpu_top.py --url ... --once        # one frame, exit
    python tools/dstpu_top.py --once --json           # raw snapshot

``--connect URL[,URL...]`` goes through the obs_wire scrape plane
instead of plain fetches: each URL gets a RemoteReplica poller
(timeout/retry/backoff, FRESH→STALE→LOST staleness), frames render
from the LAST-KNOWN snapshot, and every remote carries a staleness
badge — a SIGKILLed replica keeps rendering, flagged ``[LOST]``,
instead of killing the frame.

    python tools/dstpu_top.py --connect http://127.0.0.1:8080
    python tools/dstpu_top.py --connect http://h1:8080,http://h2:8080

Uses curses when stdout is a tty (clean redraws, q to quit); falls
back to plain ANSI-clear refresh otherwise (``--plain`` forces it —
pipeable).  ``--once`` renders a single frame and exits, which is
also what the tests drive.  Only ``--connect`` imports deepspeed_tpu;
the ``--url`` path stays pure stdlib.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _bar(frac: float, width: int = 20) -> str:
    frac = min(max(frac, 0.0), 1.0)
    n = int(round(frac * width))
    return "[" + "#" * n + "." * (width - n) + f"] {100 * frac:5.1f}%"


_SPARK_GLYPHS = " .:-=+*#%@"


def _spark(values, width: int = 32) -> str:
    """ASCII sparkline over the trailing ``width`` points (min-max
    scaled; flat series render mid-glyph so 'no variation' doesn't
    read as 'no data')."""
    vals = [float(v) for v in values if v is not None][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_GLYPHS[len(_SPARK_GLYPHS) // 2] * len(vals)
    return "".join(
        _SPARK_GLYPHS[min(int((v - lo) / span * (len(_SPARK_GLYPHS) - 1)
                              + 0.5), len(_SPARK_GLYPHS) - 1)]
        for v in vals)


# key series rendered as sparklines when /historyz is available —
# label -> history series name (fine ring)
_ENGINE_SPARKS = (
    ("queue", "serving_queue_depth"),
    ("kv util", "serving_kv_page_utilization"),
    ("ttft p95", "serving_ttft_seconds:p95"),
    ("decode/s", "serving_decode_steps:rate"),
    ("mfu", "devprof_mfu"),
    ("mbu", "devprof_mbu"),
)
_FLEET_SPARKS = (
    ("queue", "fleet_queue_depth"),
    ("slots", "fleet_active_slots"),
    ("routable", "fleet_routable_replicas"),
    ("done/s", "fleet_completed_requests:rate"),
)


def _series_points(historyz: dict, name: str):
    """Fine-ring values of one series from a /historyz document."""
    h = (historyz or {}).get("history", {})
    rec = h.get("series", {}).get(name)
    if not rec or not rec.get("rings"):
        return []
    return [v for _t, v in rec["rings"][0].get("points", [])]


def render_history(historyz: dict, sparks, now_monotonic=None) -> list:
    """Sparkline block + incident ticker from a /historyz document.
    Empty list when the document is absent/disabled — callers append
    unconditionally."""
    if not historyz:
        return []
    L = []
    h = historyz.get("history", {})
    if h.get("enabled"):
        for label, name in sparks:
            pts = _series_points(historyz, name)
            if not pts:
                continue
            L.append(f"hist  {label:<9}[{_spark(pts)}]"
                     f"  now {pts[-1]:.3g}")
    inc = historyz.get("incidents", {})
    if inc.get("enabled"):
        recent = inc.get("recent", [])
        line = (f"incid bundles {inc.get('bundles', 0)}"
                f"  suppressed {inc.get('suppressed', 0)}")
        if recent:
            now = (now_monotonic
                   if now_monotonic is not None
                   else (h.get("t_monotonic") or 0.0))
            ticker = "  ".join(
                f"[{b.get('incident', '?')}"
                + (f" {max(now - b.get('t0_monotonic', now), 0.0):.0f}s"
                   if now else "")
                + "]"
                for b in recent[-4:])
            line += "  " + ticker
        L.append(line)
    return L


def render_fleet(status: dict, health: dict | None = None,
                 historyz: dict | None = None) -> list:
    """One frame for a FleetRouter /statusz snapshot: fleet totals +
    one row per replica (state, queue, shed rate, affinity hit rate)
    + the cross-replica SLO rollup + history sparklines and the
    incident ticker when /historyz is served."""
    L = []
    fl = status.get("fleet", {})
    states = " ".join(f"{k}={v}" for k, v in
                      sorted(fl.get("states", {}).items()))
    hdr = (f"FleetRouter  up {status.get('uptime_s', 0):.0f}s"
           f"  replicas {states}")
    if health is not None:
        hdr += ("  READY" if health.get("ready") else "  NOT-READY")
        if health.get("degraded"):
            hdr += "  DEGRADED"
    L.append(hdr)
    L.append("-" * 78)
    aff = fl.get("affinity", {})
    L.append(f"fleet submitted {fl.get('submitted', 0)}"
             f"  completed {fl.get('completed', 0)}"
             f"  failed {fl.get('failed', 0)}"
             f"  shed {fl.get('shed', 0)}"
             f"  resubmits {fl.get('resubmits', 0)}"
             f"  failovers {fl.get('failovers', 0)}"
             f"  drains {fl.get('drains', 0)}")
    L.append(f"route affinity {aff.get('affinity_routed', 0)}"
             f"/{aff.get('affinity_routed', 0) + aff.get('least_loaded_routed', 0)}"
             f"  hit-rate {aff.get('hit_rate', 0.0):.3f}"
             f"  queue {fl.get('queue_depth', 0)}"
             f"  in-flight {fl.get('in_flight', 0)}"
             f"  orphaned {fl.get('orphaned', 0)}")
    fab = fl.get("fabric")
    if fab:
        line = (f"fab   exp {fab.get('exports', 0)}"
                f"  fetch {fab.get('fetches', 0)}"
                f"  moved {fab.get('bytes_moved', 0) / 2**20:.1f}MB"
                f"  mig {fab.get('migrations', 0)}"
                f"  fb {fab.get('migration_fallbacks', 0)}"
                f"  handoff {fab.get('handoffs', 0)}")
        roles = fl.get("roles") or {}
        if roles:
            line += "  | " + "  ".join(
                f"{ro} q={r.get('queue_depth', 0)}"
                f" ({r.get('routable', 0)}/{r.get('replicas', 0)})"
                for ro, r in sorted(roles.items()))
        L.append(line)
    el = status.get("elastic", {})
    if el.get("enabled"):
        ro = el.get("rollout") or {}
        line = (f"elast target {el.get('target_replicas', '?')} "
                f"[{el.get('min_replicas', '?')}"
                f"..{el.get('max_replicas', '?')}]"
                f"  up {el.get('scale_ups', 0)}"
                f"  down {el.get('scale_downs', 0)}"
                f"  cold-starts {el.get('cold_starts_in_flight', 0)}")
        if el.get("cooldown_remaining_s"):
            line += f"  cooldown {el['cooldown_remaining_s']:.1f}s"
        if ro.get("active"):
            line += (f"  ROLLOUT {ro.get('version')} "
                     f"{ro.get('updated', 0)}/{ro.get('total', 0)} "
                     f"({ro.get('state', '?')})")
        elif ro.get("rolled_back"):
            line += f"  ROLLED-BACK {ro.get('version')}"
        L.append(line)
    fm = fl.get("mesh", {})
    if fm.get("tp", 1) > 1 or fm.get("sharded_replicas"):
        L.append(f"mesh  tp={fm.get('tp', 1)}"
                 f"  sharded {fm.get('sharded_replicas', 0)}"
                 f"/{len(fl.get('replicas', []))} replicas")
    L.extend(render_history(historyz, _FLEET_SPARKS))
    L.append("-" * 78)
    L.append(f"{'replica':<9}{'state':<13}{'role':<9}{'ver':<6}"
             f"{'mesh':<7}{'queue':>6}"
             f"{'slots':>6}{'shed%':>7}{'failed':>7}{'aff':>5}"
             f"{'digest':>7}  reasons")
    for r in fl.get("replicas", []):
        reasons = ",".join(r.get("reasons", []))[:24]
        if r.get("stalled_for_s"):
            reasons = (reasons + f" stall {r['stalled_for_s']:.1f}s"
                       ).strip()
        if r.get("scrape_state"):
            # out-of-process replica: staleness badge leads the
            # reasons column so a LOST child is unmissable
            badge = r["scrape_state"]
            if r.get("scrape_age_s") is not None:
                badge += f" {r['scrape_age_s']:.0f}s"
            reasons = (f"[{badge}] " + reasons).strip()
        rm = r.get("mesh", {})
        mesh_col = ("x".join(f"{a}{s}" for a, s in
                             sorted(rm.get("axes", {}).items()))
                    or "1dev") if rm else "-"
        L.append(f"{r['replica']:<9}{r['state']:<13}"
                 f"{str(r.get('role') or '-')[:8]:<9}"
                 f"{str(r.get('version', '-'))[:5]:<6}"
                 f"{mesh_col[:6]:<7}"
                 f"{r.get('queue_depth', 0):>6}"
                 f"{r.get('active_slots', 0):>6}"
                 f"{100 * r.get('shed_rate', 0.0):>6.1f}%"
                 f"{r.get('failed', 0):>7}"
                 f"{r.get('affinity_hits', 0):>5}"
                 f"{r.get('digest_pages', 0):>7}"
                 f"  {reasons}")
    slo = status.get("slo", {})
    if slo.get("enabled"):
        L.append("-" * 78)
        L.append(f"{'tier (fleet)':<14}{'attain':>8}{'target':>8}"
                 f"{'goodput t/s':>13}  {'max burn':<22}{'alert':>6}")
        for name, t in sorted(slo.get("tiers", {}).items()):
            burns = " ".join(f"{w}={b:.1f}"
                             for w, b in sorted(t["burn_rates"].items()))
            L.append(f"{name:<14}{t['attainment']:>8.3f}"
                     f"{t['target']:>8.3f}"
                     f"{t['goodput_tokens_per_s']:>13.1f}  "
                     f"{burns:<22}"
                     f"{'FIRE' if t.get('alert_active') else '-':>6}")
    return L


def render(status: dict, health: dict | None = None,
           historyz: dict | None = None) -> list:
    """One frame of text lines from a /statusz snapshot (plus the
    optional /historyz document for sparklines + incident ticker)."""
    if status.get("engine") == "FleetRouter" or "fleet" in status:
        return render_fleet(status, health, historyz)
    L = []
    hdr = (f"{status.get('engine', '?')}  up {status.get('uptime_s', 0):.0f}s"
           f"  step age {status.get('last_step_age_s')}s")
    if health is not None:
        hdr += ("  READY" if health.get("ready") else "  NOT-READY")
        if health.get("degraded"):
            hdr += "  DEGRADED"
        wd = health.get("watchdog")
        if wd:
            hdr += (f"  wd {'FIRED' if wd['fired'] else 'ok'} "
                    f"({wd['last_heartbeat_age_s']:.0f}s/"
                    f"{wd['timeout_s']:.0f}s)")
    L.append(hdr)
    L.append("-" * 78)

    kv = status.get("kv", {})
    usable = max(kv.get("pages_usable", 1), 1)
    L.append(f"kv    live {_bar(kv.get('pages_live', 0) / usable)}"
             f"  free {kv.get('pages_free', 0)}"
             f"  warm {kv.get('pages_warm', 0)}"
             f"  frag {kv.get('fragmentation', 0.0):.2f}")
    pc = status.get("prefix_cache", {})
    if pc.get("enabled"):
        L.append(f"cache warm {pc.get('warm_pool_pages', 0)} pages"
                 f"  hit-rate {pc.get('token_hit_rate', 0.0):.3f}"
                 f"  published {pc.get('published_lifetime', 0)}"
                 f"  evicted {pc.get('evicted_lifetime', 0)}")
    kt = status.get("kv_tier", {})
    if kt.get("enabled"):
        L.append(f"tier  host {kt.get('host_pages', 0)}p/"
                 f"{kt.get('host_bytes', 0) / 1e6:.0f}MB"
                 f"  nvme {kt.get('nvme_pages', 0)}p/"
                 f"{kt.get('nvme_bytes', 0) / 1e6:.0f}MB"
                 f"  demoted {kt.get('demoted_lifetime', 0)}"
                 f"  promoted {kt.get('promoted_lifetime', 0)}"
                 f"  stall {kt.get('promote_stall_s', 0.0):.2f}s"
                 f"{'  int8' if kt.get('quantize_cold') else ''}")
    sp = status.get("speculative", {})
    if sp.get("enabled"):
        mal = sp.get("mean_accept_len")
        L.append(f"spec  sweeps {sp.get('verify_sweeps', 0)}"
                 f"  mean accept "
                 f"{mal if mal is not None else '-'}")
    em = status.get("mesh", {})
    if em.get("sharded"):
        axes = " ".join(f"{a}={s}" for a, s in
                        sorted(em.get("axes", {}).items()))
        L.append(f"mesh  {em.get('devices', 1)} devices  {axes}"
                 f"  (tp={em.get('tp', 1)} ep={em.get('ep', 1)})")
    rb = status.get("robustness", {})
    rkt = rb.get("kv_tier", {})
    if rb and (rb.get("degraded") or rb.get("shed_requests")
               or rb.get("failed_requests")
               or rkt.get("fallback_events")):
        reasons = " ".join(sorted(f"{k}={v}" for k, v in
                                  rb.get("shed_by_reason", {}).items()))
        L.append(f"rbst  shed {rb.get('shed_requests', 0)}"
                 f"/{100 * rb.get('shed_rate', 0.0):.0f}%"
                 f"{' (' + reasons + ')' if reasons else ''}"
                 f"  failed {rb.get('failed_requests', 0)}"
                 f"  tier-fallback {rkt.get('fallback_events', 0)}"
                 f"  cksum {rkt.get('checksum_failures', 0)}"
                 f"{'  TIER-DISABLED' if rkt.get('disabled') else ''}"
                 + ("  DEGRADED: " + ",".join(rb.get("reasons", []))
                    if rb.get("degraded") else ""))
    zi = status.get("zero_inference")
    if zi:
        L.append(f"zi    streamed {zi['plan'].get('n_streamed', 0)}/"
                 f"{zi['plan'].get('n_layers', 0)} layers"
                 f"  stalls {zi.get('stream_stalls', 0)}"
                 f" ({zi.get('stream_stall_s', 0.0):.2f}s)"
                 f"  {zi.get('bytes_uploaded', 0) / 1e6:.0f} MB up")
    cm = status.get("comm")
    if cm:
        L.append(f"comm  int8 wire {cm.get('bytes_on_wire_int8', 0) / 1e6:.1f}"
                 f" MB (f32 {cm.get('bytes_on_wire_f32', 0) / 1e6:.1f} MB,"
                 f" x{cm.get('compression_ratio', 0.0):.2f})"
                 f"  leaves {cm.get('leaves_quantized', 0)}q"
                 f"/{cm.get('leaves_exact', 0)}x"
                 f"  relerr {cm.get('max_rel_err', 0.0):.1e}"
                 f"<{cm.get('serving_rtol', 0.0):g}")
    dp = status.get("devprof", {})
    if dp.get("enabled"):
        ds = dp.get("device_seconds", {})
        steady = dp.get("compiles_steady", 0)
        L.append(f"dev   mfu {100 * dp.get('mfu', 0.0):.1f}%"
                 f"  mbu {100 * dp.get('mbu', 0.0):.1f}%"
                 f"  gap {1e3 * dp.get('host_device_gap_s', 0.0):.2f}ms"
                 f"  compiles {dp.get('compiles_warmup', 0)}w"
                 f"/{steady}s{'  RECOMPILING' if steady else ''}"
                 f"  dev_s " +
                 " ".join(f"{p[:3]}={ds.get(p, 0.0):.2f}"
                          for p in ("prefill", "decode", "spec_verify",
                                    "promote", "sample")))
    L.extend(render_history(historyz, _ENGINE_SPARKS))

    slo = status.get("slo", {})
    if slo.get("enabled"):
        L.append("-" * 78)
        L.append(f"{'tier':<14}{'attain':>8}{'target':>8}"
                 f"{'goodput t/s':>13}  {'burn':<24}{'alert':>6}")
        for name, t in sorted(slo.get("tiers", {}).items()):
            burns = " ".join(f"{w}={b:.1f}"
                             for w, b in sorted(t["burn_rates"].items()))
            L.append(f"{name:<14}{t['attainment']:>8.3f}"
                     f"{t['target']:>8.3f}"
                     f"{t['goodput_tokens_per_s']:>13.1f}  "
                     f"{burns:<24}"
                     f"{'FIRE' if t.get('alert_active') else '-':>6}")

    L.append("-" * 78)
    q = status.get("queue", {})
    L.append(f"slots {status.get('active_slots', 0)}/"
             f"{status.get('max_batch', 0)} active"
             f"   queue {q.get('depth', 0)}"
             f"   finished-pending {status.get('finished_pending_drain', 0)}")
    L.append(f"{'slot':<5}{'state':<9}{'req':<12}{'tier':<12}"
             f"{'prog':<12}{'seq':>5}{'pages':>6}{'age s':>8}")
    for s in status.get("slots", []):
        if s.get("state") == "idle":
            L.append(f"{s['slot']:<5}idle")
            continue
        if s["state"] == "prefill":
            prog = f"{s.get('prefill_done', 0)}/{s['prompt_tokens']}"
        else:
            prog = f"{s['generated']}/{s['max_new_tokens']}"
        L.append(f"{s['slot']:<5}{s['state']:<9}"
                 f"{str(s['req'])[:11]:<12}"
                 f"{str(s.get('tier') or '-')[:11]:<12}"
                 f"{prog:<12}{s['seq_len']:>5}{s['pages']:>6}"
                 f"{s['age_s']:>8.1f}")
    for r in q.get("head", [])[:8]:
        L.append(f"  ..  queued   {str(r['req'])[:11]:<12}"
                 f"{str(r.get('tier') or '-')[:11]:<12}"
                 f"{r['prompt_tokens']:>4} toks"
                 f"{r['age_s']:>9.1f}")
    return L


def connect_remotes(urls, cfg=None):
    """Build one RemoteReplica scrape client per URL (the --connect
    path).  Imported lazily: --url stays stdlib-only."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from deepspeed_tpu.obs_wire import RemoteReplica

    remotes = []
    for i, u in enumerate(urls):
        u = u.strip().rstrip("/")
        if not u:
            continue
        remotes.append(RemoteReplica(u, f"remote{i}", cfg=cfg))
    return remotes


def remote_badge(rem) -> str:
    """One-line scrape-plane header for a remote: staleness badge +
    scrape accounting."""
    age = rem.age_s()
    badge = rem.state + (f" {age:.1f}s" if age is not None else "")
    line = (f"== {rem.id} [{badge}]  {rem.url}"
            f"  scrapes {rem.scrapes}  errors {rem.scrape_errors}")
    if rem.last_error:
        line += f"  last: {str(rem.last_error)[:40]}"
    return line


def connect_frame(remotes) -> list:
    """One frame over the scrape plane: poll every remote (failures
    land in the staleness machine, never raise), then render each
    remote's last-known statusz/healthz/historyz under its badge."""
    lines = []
    n_lost = sum(1 for r in remotes if r.state == "LOST")
    lines.append(f"obs_wire  remotes {len(remotes)}  lost {n_lost}")
    for rem in remotes:
        try:
            rem.poll()
        except Exception as e:     # WireSchemaError: pin LOST, render on
            rem.force_lost(f"{e}")
        lines.append("")
        lines.append(remote_badge(rem))
        if rem.last_statusz is None:
            lines.append("  (no snapshot yet)")
            continue
        lines.extend(render(rem.last_statusz, rem.last_healthz,
                            rem.last_historyz))
    return lines


def one_frame(base: str):
    status = fetch(base + "/statusz")
    try:
        health = fetch(base + "/healthz")
    except urllib.error.HTTPError as e:       # 503 = not ready, still JSON
        health = json.loads(e.read().decode())
    try:
        # served only when the history/incidents blocks are on —
        # a 404 just means no sparkline/ticker rows this frame
        historyz = fetch(base + "/historyz")
    except Exception:
        historyz = None
    return status, health, historyz


def _frame_lines(base: str) -> list:
    try:
        status, health, historyz = one_frame(base)
        return render(status, health, historyz)
    except Exception as e:
        return [f"dstpu_top: {base} unreachable: {e}"]


def loop_plain(base: str, interval: float, once: bool,
               frame_fn=None) -> int:
    frame_fn = frame_fn or (lambda: _frame_lines(base))
    while True:
        lines = frame_fn()
        if not once:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        print("\n".join(lines), flush=True)
        if once:
            return 0
        time.sleep(interval)


def loop_curses(base: str, interval: float, frame_fn=None) -> int:
    import curses

    frame_fn = frame_fn or (lambda: _frame_lines(base))

    def run(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        while True:
            lines = frame_fn()
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for y, line in enumerate(lines[:maxy - 1]):
                scr.addnstr(y, 0, line, maxx - 1)
            scr.addnstr(maxy - 1, 0,
                        f"q quit   refresh {interval:.1f}s", maxx - 1,
                        curses.A_REVERSE)
            scr.refresh()
            t0 = time.monotonic()
            while time.monotonic() - t0 < interval:
                if scr.getch() in (ord("q"), ord("Q")):
                    return
                time.sleep(0.05)

    curses.wrapper(run)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default="http://127.0.0.1:8080",
                    help="engine introspection base URL "
                         "(telemetry.http_port)")
    ap.add_argument("--connect", default=None, metavar="URL[,URL...]",
                    help="scrape-plane mode: one RemoteReplica poller "
                         "per URL, staleness/LOST badges, last-known "
                         "frames survive a dead replica")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--plain", action="store_true",
                    help="plain refresh instead of curses")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the raw /statusz JSON")
    args = ap.parse_args()
    base = args.url.rstrip("/")
    if args.connect:
        remotes = connect_remotes(args.connect.split(","))
        if not remotes:
            print("dstpu_top: --connect got no URLs", file=sys.stderr)
            return 2
        if args.json:
            for rem in remotes:
                try:
                    rem.poll()
                except Exception as e:
                    rem.force_lost(f"{e}")
            print(json.dumps(
                {rem.id: {"url": rem.url, "scrape_state": rem.state,
                          "statusz": rem.last_statusz}
                 for rem in remotes}, indent=1, sort_keys=True))
            return 0
        frame_fn = lambda: connect_frame(remotes)   # noqa: E731
        if args.once or args.plain or not sys.stdout.isatty():
            return loop_plain(base, args.interval, args.once,
                              frame_fn=frame_fn)
        return loop_curses(base, args.interval, frame_fn=frame_fn)
    if args.json:
        print(json.dumps(fetch(base + "/statusz"), indent=1,
                         sort_keys=True))
        return 0
    if args.once or args.plain or not sys.stdout.isatty():
        return loop_plain(base, args.interval, args.once)
    return loop_curses(base, args.interval)


if __name__ == "__main__":
    sys.exit(main())
