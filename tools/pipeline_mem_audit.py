#!/usr/bin/env python
"""Pipeline peak-memory audit (round-2 verdict task 3): measure the
GPipe-shaped tick scan's compiled memory — with and without remat —
against the analytic 1F1B bound, at M=8 microbatches over S=4 stages.

Why this decides the 1F1B question: 1F1B's only advantage over GPipe is
peak activation memory — it bounds in-flight microbatches per stage at S
instead of M (same bubble, same math).  On TPU the scan+AD pipeline gets
its memory bound from REMAT instead: the backward recomputes each
stage's internals, so only the per-tick boundary activations stay live.
If measured remat-GPipe temp memory is at or below the analytic 1F1B
bound, a hand-scheduled interleaved 1F1B would buy nothing here.

Analytic bounds per stage (activation bytes, excluding params/grads):
  gpipe (no remat):  M * act_layers      (every microbatch's internals)
  1f1b  (no remat):  S * act_layers      (at most S in flight)
  remat-GPipe:       (M+S-1) * act_boundary + 1 * act_layers (recompute
                     live set of ONE microbatch during its bwd tick)
where act_layers = full saved internals of one microbatch through one
stage's layer slab, act_boundary = one microbatch's boundary activation.

Writes PIPELINE_MEM.json with the measured + analytic numbers.

    python tools/pipeline_mem_audit.py
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as dstpu
from deepspeed_tpu.models import llama
from deepspeed_tpu.topology import MeshSpec

S, M = 4, 8
DIM, LAYERS, SEQ, MB = 256, 8, 128, 2  # microbatch rows per stage pass


def build_engine(remat: str):
    ms = MeshSpec.build({"pipe": S, "data": 8 // S})
    cfg = llama.LlamaConfig.tiny(dim=DIM, n_layers=LAYERS, n_heads=8,
                                 n_kv_heads=4, attn_impl="reference",
                                 remat=remat)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    dp = 8 // S
    engine, _, _, _ = dstpu.initialize(
        loss_fn=llama.loss_fn(cfg, n_micro=M), params=params, mesh=ms,
        param_specs=llama.param_specs(cfg, pipeline=True),
        config={
            "train_batch_size": MB * M * dp,
            "gradient_accumulation_steps": M,
            "pipeline": {"stages": S},
            "zero_optimization": {"stage": 0},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        })
    toks = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (engine.train_batch_size, SEQ + 1)), jnp.int32)
    return engine, {"tokens": toks}, cfg


def measure(remat: str):
    engine, batch, cfg = build_engine(remat)
    compiled = engine.lower_step(batch).compile()
    ma = compiled.memory_analysis()
    # prove it actually runs, not just compiles
    loss = float(engine.train_batch(batch))
    return {
        "remat": remat,
        "temp_bytes": int(ma.temp_size_in_bytes),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "loss": loss,
    }, cfg


def measure_scan_only(remat: bool):
    """Isolate the pipelined scan fwd+bwd: no loss head, no optimizer —
    temp bytes here are dominated by pipeline activation liveness, the
    quantity 1F1B actually optimizes."""
    from deepspeed_tpu.parallel.pipeline import pipelined_scan, stage_spec
    from deepspeed_tpu.topology import MeshSpec
    from jax.sharding import PartitionSpec as P

    ms = MeshSpec.build({"pipe": S, "data": 8 // S})

    def block(act, wpair):
        w1, w2 = wpair
        h = jnp.tanh(act @ w1)
        return (act + h @ w2).astype(act.dtype), None

    L = LAYERS
    k = jax.random.PRNGKey(0)
    w1 = jax.random.normal(k, (L, DIM, 4 * DIM), jnp.bfloat16) * 0.05
    w2 = jax.random.normal(k, (L, 4 * DIM, DIM), jnp.bfloat16) * 0.05
    stacked = (jax.device_put(w1, ms.sharding(stage_spec(None))),
               jax.device_put(w2, ms.sharding(stage_spec(None))))
    x = jnp.ones((MB * M, SEQ, DIM), jnp.bfloat16)

    def loss(params, x):
        y = pipelined_scan(block, params, x, M, ms, remat=remat)
        return jnp.sum(y.astype(jnp.float32))

    g = jax.jit(jax.grad(loss))
    compiled = g.lower(stacked, x).compile()
    ma = compiled.memory_analysis()
    jax.block_until_ready(g(stacked, x))  # executes
    return {"remat": remat, "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes)}


def analytic_scan_bounds():
    """WHOLE-MESH activation-byte bounds for the isolated tanh-MLP scan
    (bf16).  memory_analysis on the virtual CPU mesh aggregates all 8
    devices' buffers, so bounds are per-stage * S * dp."""
    L_per_stage = LAYERS // S
    bytes_el = 2
    dp = 8 // S
    act_boundary = MB * SEQ * DIM * bytes_el
    # saved internals per microbatch per stage: per block the bwd needs
    # act [mb,seq,D] + h [mb,seq,4D] → 5 * act_boundary per layer
    act_layers = 5 * L_per_stage * act_boundary
    mesh = S * dp
    return {
        "act_boundary_bytes": act_boundary,
        "act_layers_bytes_per_microbatch_per_stage": act_layers,
        "gpipe_no_remat_bound": M * act_layers * mesh,
        "onef1b_no_remat_bound": S * act_layers * mesh,
        "remat_gpipe_bound": ((M + S - 1) * act_boundary + act_layers)
        * mesh,
    }


def main():
    no_remat, cfg = measure("none")
    with_remat, _ = measure("full")
    scan_plain = measure_scan_only(False)
    scan_remat = measure_scan_only(True)
    bounds = analytic_scan_bounds()
    ratio = scan_remat["temp_bytes"] / max(bounds["onef1b_no_remat_bound"],
                                           1)
    out = {
        "topology": {"stages": S, "n_micro": M, "dim": DIM,
                     "layers": LAYERS, "seq": SEQ, "microbatch": MB,
                     "backend": jax.default_backend(),
                     "note": "temp_bytes aggregate ALL 8 virtual devices"},
        "measured_full_engine_step": {
            "gpipe": no_remat, "gpipe_remat": with_remat},
        "measured_isolated_scan": {
            "gpipe": scan_plain, "gpipe_remat": scan_remat},
        "analytic_scan_bounds_whole_mesh": bounds,
        "remat_scan_temp_over_1f1b_bound": round(ratio, 3),
        "conclusion": (
            "remat-GPipe measured temp <= analytic 1F1B bound: an "
            "interleaved 1F1B schedule would not reduce peak memory here"
            if ratio <= 1.0
            else "remat-GPipe measured temp EXCEEDS the 1F1B bound by "
                 f"{ratio:.2f}x: an interleaved schedule would help at "
                 "this shape"),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PIPELINE_MEM.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
