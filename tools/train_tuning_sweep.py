#!/usr/bin/env python
"""Train-step tuning sweep on the local chip: remat policy × batch size
(× optional loss_chunk) for the bench llama config.

Decides whether bench.py's ``remat="save_dots", batch=4`` leaves MFU on
the table (BENCH_r02: 48.7% MFU / 300.9 ms).  Each configuration runs in
THIS process sequentially; run the whole script under an outer deadline
(the axon tunnel can hang indefinitely at init — see bench.py's
subprocess pattern for the guaranteed-output variant).

    timeout 1500 python tools/train_tuning_sweep.py
    python tools/train_tuning_sweep.py --cpu --quick   # smoke
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json-out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "TRAIN_SWEEP.json"))
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    steps = 3 if args.quick else 12
    if on_tpu:
        base = dict(vocab_size=16384, dim=2048, n_layers=8, n_heads=16,
                    n_kv_heads=8, ffn_dim=7168, max_seq_len=2048,
                    rope_theta=500000.0)
        seq = 2048
        grid = [("save_dots", 4, 0), ("none", 4, 0), ("save_dots", 8, 0),
                ("none", 8, 0), ("save_dots", 4, 8192),
                # save_attn keeps the tagged attention context, so the
                # backward skips the quadratic recompute
                ("save_attn", 4, 0), ("save_attn", 8, 0)]
    else:
        base = dict(vocab_size=256, dim=32, n_layers=2, n_heads=4,
                    n_kv_heads=2, max_seq_len=64)
        seq = 32
        grid = [("save_dots", 4, 0), ("none", 4, 0), ("save_dots", 4, 64)]

    rows = []
    for remat, batch, loss_chunk in grid:
        cfg = llama.LlamaConfig(**base, remat=remat, loss_chunk=loss_chunk)
        row = {"remat": remat, "batch": batch, "loss_chunk": loss_chunk}
        try:
            engine, _, _, _ = dstpu.initialize(
                loss_fn=llama.loss_fn(cfg),
                params=llama.init_params(jax.random.PRNGKey(0), cfg),
                config={"train_micro_batch_size_per_gpu": batch,
                        "zero_optimization": {"stage": 0},
                        "optimizer": {"type": "adamw",
                                      "params": {"lr": 1e-4}},
                        "bf16": {"enabled": True}})
            toks = jnp.asarray(np.random.default_rng(0).integers(
                0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)
            data = {"tokens": toks}
            float(engine.train_batch(data))          # compile
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = engine.train_batch(data)
            float(loss)                              # value fetch = sync
            dt = (time.perf_counter() - t0) / steps
            tps = batch * seq / dt
            fl = 6 * llama.param_count(cfg) \
                + 12 * cfg.n_layers * cfg.dim * seq
            peak = 197e12 if on_tpu else 1e12
            row.update(step_ms=round(1e3 * dt, 1), tokens_per_s=round(tps),
                       mfu=round(tps * fl / peak, 4))
            del engine
        except Exception as e:                       # OOM etc: record
            row["error"] = str(e)[:200]
        print(json.dumps(row), flush=True)
        rows.append(row)

    out = {"backend": jax.default_backend(), "steps": steps, "rows": rows}
    with open(args.json_out, "w") as f:
        json.dump(out, f, indent=1)
    print("→", args.json_out)


if __name__ == "__main__":
    main()
