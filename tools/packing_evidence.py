#!/usr/bin/env python
"""Packing-efficiency evidence (chip-independent): live-token fraction
of greedy first-fit packing (data/packing.py) vs one-document-per-row
padded batching, over realistic document-length distributions.  The
live fraction bounds compute utilization directly — attention and FLOPs
are spent on every slot, so 2x live fraction ≈ 2x useful tokens/s at
equal hardware throughput.

    python tools/packing_evidence.py            # writes PACKING_BENCH.json
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.data.packing import pack_documents, packing_efficiency


def padded_efficiency(lengths, T):
    """One document per row, truncated to T: live fraction."""
    lengths = np.minimum(lengths, T)
    return float(lengths.sum() / (len(lengths) * T))


def main():
    rng = np.random.default_rng(0)
    rows = []
    for name, lengths in (
            # lognormal ~ web-corpus doc lengths (median ~180 tokens)
            ("web_lognormal", np.minimum(rng.lognormal(
                5.2, 1.1, 20000).astype(int) + 1, 16384)),
            # chat turns: short, tight spread
            ("chat_short", rng.integers(16, 384, 20000)),
            # books: long docs, most exceed T
            ("books_long", rng.integers(1500, 12000, 2000))):
        for T in (512, 2048, 8192):
            docs = [[1] * int(n) for n in lengths]
            toks, segs = pack_documents(docs, seq_len=T)
            packed = packing_efficiency(segs)
            padded = padded_efficiency(lengths, T)
            rows.append({
                "distribution": name, "seq_len": T,
                "padded_live_frac": round(padded, 4),
                "packed_live_frac": round(packed, 4),
                "useful_token_speedup": round(packed / max(padded, 1e-9), 2),
                "rows_padded": len(lengths), "rows_packed": int(toks.shape[0]),
            })
            print(rows[-1], flush=True)
    out = {"metric": "packing_live_token_fraction", "rows": rows,
           "note": "live fraction bounds useful-FLOPs fraction; "
                   "speedup = packed/padded at equal hardware throughput"}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PACKING_BENCH.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("→", path)


if __name__ == "__main__":
    main()
