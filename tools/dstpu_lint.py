#!/usr/bin/env python
"""dstpu-lint CLI: project-native static analysis over deepspeed_tpu.

Runs the four pass families of ``deepspeed_tpu.analysis`` (hot-path
host-sync lint, lock-order/lock-scope checker, page-lifecycle
exception-safety pass, surface-parity gates incl. the Chrome-trace
pairing check) against the repo and diffs the result against the
committed zero-waiver baseline (``LINT_BASELINE.json``).

    python tools/dstpu_lint.py --check                  # exit 1 on any
                                                        # violation
    python tools/dstpu_lint.py --check --json-out LINT_REPORT.json
    python tools/dstpu_lint.py --check --pass hostsync --pass parity

Exit codes: 0 clean, 1 violations found, 2 usage/internal error.

``tools/run_slow_lane.sh`` runs ``--check`` each cadence and stamps
``LINT_REPORT.json``; ``BENCH_BASELINE.json`` rows pin
``violations == 0``, ``waivers == 0`` and ``passes_run >= 4`` so the
bench gate fails on lint regression.  Tier-1 runs the same check
in-process via ``tests/test_analysis.py`` (budget-aware).

Implementation note: the analysis package is loaded straight off its
files, NOT via ``import deepspeed_tpu`` — the package ``__init__``
pulls in jax and the engines, and a linter that imports its subject is
both slow and breakable by the very bugs it hunts.
"""

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_analysis(root: str = REPO):
    """Load ``deepspeed_tpu/analysis`` as a standalone package (no
    parent ``__init__`` execution, no jax)."""
    name = "dstpu_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(root, "deepspeed_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _atomic_write_json(doc: dict, path: str) -> None:
    # local copy of utils/evidence.atomic_write_json: this tool must
    # not import the package under analysis
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="project-native static analysis (dstpu-lint)")
    ap.add_argument("--check", action="store_true",
                    help="run the passes and gate against the "
                         "baseline (default action)")
    ap.add_argument("--root", default=REPO,
                    help="repo root to analyze")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: "
                         "<root>/LINT_BASELINE.json)")
    ap.add_argument("--pass", dest="passes", action="append",
                    default=None, metavar="NAME",
                    help="run only this pass (repeatable); default: "
                         "all four")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="skip passes that would start past this "
                         "many seconds (tier-1 budget awareness)")
    ap.add_argument("--json-out", default=None,
                    help="also stamp the report document (atomic)")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    # the pass implementations always come from THIS repo; --root only
    # selects the tree under analysis (fixture trees in tests)
    analysis = load_analysis()
    if args.list_passes:
        for p in analysis.PASSES:
            print(p)
        return 0

    try:
        report = analysis.check_repo(
            args.root, baseline_path=args.baseline,
            passes=tuple(args.passes) if args.passes
            else analysis.PASSES,
            budget_s=args.budget_s)
    except (OSError, ValueError, SyntaxError) as e:
        print(f"dstpu_lint: internal error: {e}", file=sys.stderr)
        return 2

    for f in report["findings"]:
        print(f"{f['path']}:{f['line']}: [{f['pass_name']}/"
              f"{f['code']}] {f['message']}")
    demoted = report.get("demoted") or []
    print(f"dstpu_lint: {report['passes_run']} passes, "
          f"{report['violations']} violations, "
          f"{report['waivers']} waivers, "
          f"{report.get('hot_regions', 0)} hot regions "
          f"({report.get('justified_syncs', 0)} justified syncs)"
          + (f", demoted to slow lane: {demoted}" if demoted else ""))
    if args.json_out:
        import time

        report["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        _atomic_write_json(report, args.json_out)
        print("→", args.json_out)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
