#!/usr/bin/env python
"""incident_report: render an incident bundle into a human timeline.

An incident bundle (written by
:class:`deepspeed_tpu.incidents.IncidentManager` — one atomic JSON per
deduped trip) holds the triggering event, the pre-trip metric-history
windows, the flight-recorder ring slice around t0, and the /statusz
snapshot.  This tool turns that JSON into the postmortem an operator
actually reads:

- a header (incident class, capture time, source, trigger details);
- the **event timeline** ordered around t0 (seconds relative to the
  trip; the trigger row is marked), interleaved with the history
  annotations (scale/rollout marks) that fell inside the window;
- the **top metric deltas**: each history series' mean over the
  pre-window vs its last pre-trip value, ranked by relative change —
  the "what was moving before it broke" list;
- a one-line /statusz digest (queue depth, active slots, SLO alert
  states) when the bundle carries one.

    python tools/incident_report.py INCIDENT_SAMPLE.json
    python tools/incident_report.py /tmp/dstpu_incidents/incident_*.json
    python tools/incident_report.py bundle.json --top 10 --events 40

Pure stdlib; multiple paths render back-to-back (a soak's bundle dir).
"""

import argparse
import json
import sys


def _rel_s(t_ns, base_ns):
    return (t_ns - base_ns) / 1e9


def _fmt_attrs(attrs, limit=100):
    if not attrs:
        return ""
    s = " ".join(f"{k}={v}" for k, v in attrs.items())
    return s if len(s) <= limit else s[:limit - 3] + "..."


def metric_deltas(history, top=8):
    """Rank series by |last pre-trip value vs pre-window mean|
    relative change.  A series that APPEARED from a zero pre-window
    (burn rate 0 -> 33) has no finite relative change — those rank
    first (by absolute delta) and render as "new"; all-zero series
    and single-point series are skipped."""
    rows = []
    for name, rec in (history or {}).get("series", {}).items():
        rings = rec.get("rings") or []
        pts = [v for _t, v in rings[0].get("points", [])] if rings else []
        if len(pts) < 2:
            continue
        pre, last = pts[:-1], pts[-1]
        mean = sum(pre) / len(pre)
        delta = last - mean
        if abs(mean) < 1e-9 and abs(delta) < 1e-9:
            continue                     # flat zero: nothing to read
        rows.append({
            "series": name,
            "pre_mean": round(mean, 6),
            "last": round(last, 6),
            "delta": round(delta, 6),
            "rel": (round(delta / abs(mean), 4)
                    if abs(mean) >= 1e-9 else None),
            "points": len(pts),
        })
    rows.sort(key=lambda r: (0, -abs(r["delta"])) if r["rel"] is None
              else (1, -abs(r["rel"])))
    return rows[:top]


def render_bundle(bundle, top=8, max_events=32):
    """One bundle -> list of text lines (the test drives this
    directly; main() prints it)."""
    L = []
    cls = bundle.get("incident", "?")
    L.append(f"INCIDENT [{cls}]  captured {bundle.get('t', '?')}  "
             f"source={bundle.get('source', '?')}  "
             f"seq={bundle.get('seq', '?')}")
    trig = bundle.get("trigger", {})
    if "phase" in trig:
        L.append(f"trigger: event `{trig['phase']}`  "
                 f"{_fmt_attrs(trig.get('attrs'))}")
    elif "detector" in trig:
        L.append(f"trigger: detector {trig['detector']}  "
                 f"value={trig.get('value')}  z={trig.get('z')}  "
                 f"baseline mean={trig.get('mean')} "
                 f"std={trig.get('std')}")
    else:
        L.append(f"trigger: {_fmt_attrs(trig)}")
    L.append(f"pre-window: {bundle.get('pre_window_s', '?')} s of "
             f"history for "
             f"{len((bundle.get('history') or {}).get('series', {}))} "
             f"series")
    L.append("-" * 72)

    # ---- timeline: ring events relative to t0 (the trigger's own
    # timestamp when it carries one, else the capture time)
    ring = bundle.get("ring") or []
    if ring:
        trig_ns = trig.get("t_ns")
        base_ns = trig_ns if trig_ns is not None else ring[-1]["t_ns"]
        events = ring[-max_events:]
        L.append(f"timeline ({len(events)} of {len(ring)} ring events, "
                 "seconds relative to t0; >>> marks the trigger):")
        for e in events:
            mark = ">>>" if trig_ns is not None and \
                e["t_ns"] == trig_ns and e["phase"] == \
                trig.get("phase") else "   "
            req = f" req={e['req']}" if "req" in e else ""
            L.append(f" {mark} {_rel_s(e['t_ns'], base_ns):+9.3f}s  "
                     f"{e['phase']:<22}{req}  "
                     f"{_fmt_attrs(e.get('attrs'), 60)}")
    anns = (bundle.get("history") or {}).get("annotations", [])
    if anns:
        L.append(f"annotations in window ({len(anns)}):")
        for a in anns[-12:]:
            L.append(f"     t={a.get('t')}  {a.get('label')}  "
                     f"{_fmt_attrs(a.get('attrs'), 60)}")
    L.append("-" * 72)

    # ---- top metric deltas vs the pre-window
    deltas = metric_deltas(bundle.get("history"), top=top)
    if deltas:
        L.append(f"top metric deltas (last pre-trip value vs "
                 f"pre-window mean, top {len(deltas)}):")
        L.append(f"  {'series':<44}{'pre-mean':>12}{'last':>12}"
                 f"{'rel':>9}")
        for r in deltas:
            rel = ("     new" if r["rel"] is None
                   else f"{100 * r['rel']:>7.1f}%")
            L.append(f"  {r['series'][:43]:<44}{r['pre_mean']:>12.4g}"
                     f"{r['last']:>12.4g} {rel}")
    else:
        L.append("no history series in the bundle (history block off "
                 "at capture time)")

    # ---- statusz digest
    st = bundle.get("statusz")
    if isinstance(st, dict) and "error" not in st:
        if "fleet" in st:
            fl = st["fleet"]
            L.append(f"statusz: fleet queue={fl.get('queue_depth')}  "
                     f"in-flight={fl.get('in_flight')}  "
                     f"states={fl.get('states')}")
        else:
            q = st.get("queue", {})
            L.append(f"statusz: queue={q.get('depth')}  "
                     f"active_slots={st.get('active_slots')}  "
                     f"uptime={st.get('uptime_s')}s")
        slo = st.get("slo", {})
        if slo.get("enabled"):
            firing = [name for name, t in slo.get("tiers", {}).items()
                      if t.get("alert_active")]
            L.append("statusz: slo alerts firing: "
                     + (", ".join(firing) if firing else "none"))
    return L


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render incident bundles into human timelines")
    ap.add_argument("bundles", nargs="+",
                    help="incident bundle JSON path(s)")
    ap.add_argument("--top", type=int, default=8,
                    help="metric-delta rows to show")
    ap.add_argument("--events", type=int, default=32,
                    help="timeline events to show")
    args = ap.parse_args(argv)
    rc = 0
    for i, path in enumerate(args.bundles):
        if i:
            print()
        try:
            with open(path) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"incident_report: {path}: {e}", file=sys.stderr)
            rc = 2
            continue
        try:
            print("\n".join(render_bundle(bundle, top=args.top,
                                          max_events=args.events)))
        except BrokenPipeError:     # `| head` closed the pipe: fine
            return rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
