#!/usr/bin/env python
"""Telemetry sampler: drive a short serving loop and pretty-print the
registry snapshot (ISSUE 2 satellite).

Runs a tiny gpt2 ServingEngine on whatever backend is available (pass
--cpu to force the CPU backend), serves a handful of requests, then

  1. pretty-prints ``registry.snapshot()`` (the on-demand JSON sink),
  2. writes the Prometheus text exposition next to the JSON stamp and
     parses it back (the same round-trip the tests assert),
  3. stamps TELEMETRY_SAMPLE.json (atomic) with the snapshot + run
     metadata, so slow-lane runs (tools/run_slow_lane.sh) leave a
     standing record of what a live registry looks like, and
  4. stamps STATUSZ_SAMPLE.json from the engine's introspection server
     (ISSUE 6): /statusz, /healthz and a /requestz drill-down fetched
     over REAL HTTP from the live engine — the snapshot schema is
     versioned in-repo and round-trip-parsed by a tier-1 test, and
  5. stamps DEVPROF_SAMPLE.json (ISSUE 17): the devprof block from
     /statusz plus the /profilez round-trip and a short on-demand
     jax.profiler capture, all over the same real HTTP server — the
     standing record of the compile ledger (steady_state_compiles
     must read 0), per-phase device seconds and MFU/MBU.

    python tools/telemetry_dump.py --cpu
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend in-process")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=24)
    ap.add_argument("--json-out",
                    default=os.path.join(REPO, "TELEMETRY_SAMPLE.json"))
    ap.add_argument("--statusz-out",
                    default=os.path.join(REPO, "STATUSZ_SAMPLE.json"))
    ap.add_argument("--devprof-out",
                    default=os.path.join(REPO, "DEVPROF_SAMPLE.json"))
    ap.add_argument("--capture-s", type=float, default=0.2,
                    help="on-demand /profilez device-trace length "
                         "(0 skips the capture)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from deepspeed_tpu.inference.serving import serving_engine
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.telemetry import parse_prometheus_text
    from deepspeed_tpu.utils.evidence import atomic_write_json

    cfg = gpt2.GPT2Config.tiny(dim=64, n_layers=2, n_heads=4,
                               max_seq_len=128)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    prompt_len = 24
    max_seq = prompt_len + args.new_tokens
    # prefix caching + speculation on: the sample registry carries LIVE
    # prefix_cache_* AND spec_* metric families (shared-prefix traffic
    # below produces real hits; the repetitive histories greedy decode
    # settles into give the ngram drafter real acceptances)
    # slo + introspection on: the stamps carry live slo_* families and
    # the /statusz sample comes over REAL HTTP (ephemeral port) from
    # the same traced engine
    eng = serving_engine(
        params, cfg, max_batch=4, page_size=8,
        num_pages=4 * (-(-max_seq // 8)) + 16, max_seq=max_seq,
        prefill_bucket=8, decode_chunk=4, prefix_cache=True,
        speculative={"draft_tokens": 4},
        slo={"tiers": {"interactive": {"ttft_s": 10.0,
                                       "deadline_s": 60.0},
                       "batch": {"deadline_s": 300.0, "target": 0.9}},
             "default_tier": "interactive"},
        telemetry={"http_port": 0, "interval_s": 0.0},
        # full-rate sampling: this is a tiny sample loop, so every
        # dispatch contributing device time gives the stamp dense
        # per-phase attribution (production default is 0.05)
        devprof={"sample_rate": 1.0})

    rng = np.random.default_rng(0)
    prefix = rng.integers(1, cfg.vocab_size, prompt_len - 4).tolist()
    t0 = time.perf_counter()
    for i in range(args.requests):
        eng.submit(i, prefix + rng.integers(1, cfg.vocab_size, 4).tolist(),
                   max_new_tokens=args.new_tokens,
                   tier="batch" if i % 2 else "interactive")
    out = eng.run()
    eng.step()                   # settle gauges after the drain
    wall = time.perf_counter() - t0

    snap = eng.registry.snapshot()
    print(json.dumps(snap, indent=1, sort_keys=True))

    prom_path = args.json_out.rsplit(".", 1)[0] + ".prom"
    eng.registry.write_prometheus(prom_path)
    with open(prom_path) as f:
        families = parse_prometheus_text(f.read())
    print(f"# prometheus exposition: {prom_path} "
          f"({len(families)} families, parsed back OK)")

    atomic_write_json({
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "requests": args.requests,
        "completed": len(out),
        "wall_s": round(wall, 2),
        "prometheus_families": len(families),
        "snapshot": snap,
    }, args.json_out)
    print("→", args.json_out)

    # introspection sample over real HTTP: the engine registered its
    # /statusz, /healthz and /requestz providers on the telemetry
    # server at construction — fetch all three so the stamped schema is
    # exactly what a fleet supervisor or dstpu_top would see
    import urllib.request

    base = f"http://127.0.0.1:{eng._tel_exporter.port}"

    def get(path, timeout=10):
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return json.loads(r.read().decode())

    statusz = get("/statusz")
    healthz = get("/healthz")
    requestz = get("/requestz?id=0")
    atomic_write_json({
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "endpoints": ["/statusz", "/healthz", "/requestz?id=",
                      "/metrics"],
        "statusz": statusz,
        "healthz": healthz,
        "requestz_sample": requestz,
    }, args.statusz_out)
    print(f"# introspection: fetched /statusz /healthz /requestz over "
          f"http from {base}")
    print("→", args.statusz_out)

    # device-truth sample over the same real HTTP server (ISSUE 17):
    # /profilez without a query returns the devprof status block;
    # with capture_s it runs a bounded jax.profiler capture and
    # returns the capture reference
    profilez = get("/profilez")
    capture = None
    if args.capture_s > 0:
        # profiler session start/stop costs ~15 s on some backends —
        # the capture fetch gets a generous client timeout
        capture = get(f"/profilez?capture_s={args.capture_s}",
                      timeout=120)
        capture.pop("devprof", None)   # already stamped above
    dp = statusz.get("devprof", {})
    atomic_write_json({
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "endpoints": ["/profilez", "/profilez?capture_s="],
        # the zero-recompile contract's standing evidence: this loop
        # served real traffic after warmup, so steady must be true and
        # steady_state_compiles must read 0
        "steady": dp.get("steady"),
        "steady_state_compiles": dp.get("compiles_steady"),
        "devprof": dp,
        "profilez": profilez,
        "capture": capture,
    }, args.devprof_out)
    print(f"# devprof: fetched /profilez over http from {base} "
          f"(capture_s={args.capture_s})")
    print("→", args.devprof_out)
    eng.shutdown()


if __name__ == "__main__":
    main()
