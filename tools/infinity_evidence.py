#!/usr/bin/env python
"""ZeRO-Infinity peak-params-per-chip evidence runner (round-2 verdict
task 1): produces INFINITY_BENCH.json with BOTH halves of the story on
the real chip —

  1. the NON-offload ceiling: the plain in-HBM engine at ~1.38B params
     fails to compile/allocate (the XLA error names the HBM deficit);
  2. the Infinity engine TRAINS the same model, with only the bf16
     compute copy resident on-chip and the f32 master+moments streamed
     from NVMe around host (CPU-Adam) sub-group updates.

    python tools/infinity_evidence.py --steps 2
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "zero_infinity_offload.py")


def run_probe(timeout_s: int) -> dict:
    p = subprocess.run(
        [sys.executable, EXAMPLE, "--scale", "1p4b", "--probe-plain"],
        capture_output=True, text=True, timeout=timeout_s)
    out = p.stdout + p.stderr
    m = re.search(r"Used [0-9.]+[GM] of [0-9.]+[GM] hbm[^\n]*", out)
    oom = ("RESOURCE_EXHAUSTED" in out or "Ran out of memory" in out
           or "ResourceExhausted" in out)
    return {
        "outcome": "oom" if (p.returncode != 0 and oom)
        else ("ran" if p.returncode == 0 else "error"),
        "returncode": p.returncode,
        "hbm_detail": m.group(0) if m else None,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--probe-timeout", type=int, default=900)
    ap.add_argument("--run-timeout", type=int, default=7200)
    ap.add_argument("--json-out",
                    default=os.path.join(REPO, "INFINITY_BENCH.json"))
    args = ap.parse_args()

    print("probing the plain in-HBM engine at 1p4b (expected: HBM OOM)…",
          flush=True)
    probe = run_probe(args.probe_timeout)
    print("probe:", probe, flush=True)

    tmp = args.json_out + ".run"
    print(f"running the Infinity engine for {args.steps} steps…", flush=True)
    p = subprocess.run(
        [sys.executable, EXAMPLE, "--scale", "1p4b",
         "--steps", str(args.steps), "--json-out", tmp],
        timeout=args.run_timeout)
    if not os.path.exists(tmp):
        raise SystemExit(f"infinity run produced no evidence (rc={p.returncode})")
    with open(tmp) as f:
        evidence = json.load(f)
    os.remove(tmp)
    evidence["plain_engine_probe"] = probe
    evidence["infinity_run_returncode"] = p.returncode
    with open(args.json_out, "w") as f:
        json.dump(evidence, f, indent=1)
    print(json.dumps(evidence, indent=1))


if __name__ == "__main__":
    main()
