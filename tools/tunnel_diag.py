#!/usr/bin/env python
"""Staged TPU-tunnel health probe: time compile+execute at increasing
scale to localize where the axon tunnel degrades (round-5: probe-scale
work returned in 2.7 s while the 0.6B bench and the kernel sweep both
wedged past their deadlines with ~0 local CPU time — everything blocked
in RPC).

Each stage prints one line immediately (flush) so a caller tailing the
output sees exactly where the stall begins even if the process is later
killed.  Times are wall-clock through float() fetches (under the tunnel
block_until_ready returns early).
"""

import json
import sys
import time


def stage(name, fn):
    t0 = time.perf_counter()
    try:
        out = fn()
        dt = time.perf_counter() - t0
        print(json.dumps({"stage": name, "s": round(dt, 2),
                          "out": out}), flush=True)
        return True
    except Exception as e:  # noqa: BLE001 — diagnostic tool
        dt = time.perf_counter() - t0
        print(json.dumps({"stage": name, "s": round(dt, 2),
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)
        return False


def main():
    import jax
    import jax.numpy as jnp

    stage("import+devices", lambda: str(jax.devices()))

    def mm(n):
        x = jnp.ones((n, n), jnp.bfloat16)
        f = jax.jit(lambda a: (a @ a).sum())
        return float(f(x))

    for n in (128, 1024, 4096, 8192):
        if not stage(f"matmul_{n}", lambda n=n: mm(n)):
            return

    def mm_loop(n, k):
        x = jnp.ones((n, n), jnp.bfloat16)
        f = jax.jit(lambda a: (a @ a).sum())
        float(f(x))  # compile
        t0 = time.perf_counter()
        for _ in range(k):
            r = f(x)
        v = float(r)
        return {"per_call_ms": round(1000 * (time.perf_counter() - t0) / k,
                                     2), "v": v}

    stage("matmul_4096_x20", lambda: mm_loop(4096, 20))

    # a transfer-heavy stage: 256 MB host->device->host
    def xfer():
        import numpy as np

        a = np.ones((64, 1024, 1024), jnp.float32)  # 256 MB
        t0 = time.perf_counter()
        d = jax.device_put(a)
        d.block_until_ready()
        up = time.perf_counter() - t0
        t0 = time.perf_counter()
        b = jax.device_get(d)
        down = time.perf_counter() - t0
        return {"h2d_s": round(up, 2), "d2h_s": round(down, 2),
                "ok": bool(b[0, 0, 0] == 1.0)}

    stage("transfer_256MB", xfer)

    # a small-but-real train graph: 4-layer 256-dim llama
    def tiny_train():
        sys.path.insert(0, ".")
        import deepspeed_tpu as dstpu
        from deepspeed_tpu.models import llama
        import numpy as np

        cfg = llama.LlamaConfig(
            vocab_size=1024, dim=256, n_layers=4, n_heads=4, n_kv_heads=4,
            ffn_dim=512, max_seq_len=256)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=llama.loss_fn(cfg), params=params,
            config={"train_micro_batch_size_per_gpu": 2,
                    "zero_optimization": {"stage": 0},
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True}})
        tokens = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 257)), jnp.int32)
        t0 = time.perf_counter()
        l0 = float(engine.train_batch({"tokens": tokens}))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(5):
            loss = engine.train_batch({"tokens": tokens})
        v = float(loss)
        return {"compile_s": round(compile_s, 1),
                "step_ms": round(1000 * (time.perf_counter() - t0) / 5, 1),
                "loss0": round(l0, 3), "loss5": round(v, 3)}

    stage("tiny_train_4L_256d", tiny_train)


if __name__ == "__main__":
    main()
