#!/usr/bin/env python
"""Obs-wire truth gate: a REAL child process, scraped over REAL HTTP.

Everything the wire plane claims, demonstrated against a subprocess
replica (tools/replica_child.py — its own interpreter, its own engine,
its own ephemeral-port exporter), not an in-process mock:

- scrape: RemoteReplica polls the child's /statusz + /healthz +
  /historyz through the schema check until FRESH, with zero errors.
- schema: a forged major bump on a genuinely-scraped document must
  raise WireSchemaError (``schema_ok`` covers both directions: real
  docs accepted, wrong major rejected).
- clock correlation: a second child runs with ``--skew-ns`` shifting
  its monotonic stamps; the min-RTT estimator must recover that known
  skew within its own reported error bound (+ scheduling slack).
- trace merge: both children's /tracez drains, merged with the
  measured offsets, must produce one monotone Chrome trace with both
  replica tags present.
- staleness: SIGKILL (no cleanup possible) flips the child to LOST
  within the configured window, the last-known snapshot survives, and
  every post-mortem poll() returns promptly — the loop never wedges
  on a dead peer.

    python tools/obswire_probe.py --cpu --json-out OBSWIRE_SAMPLE.json

Run by tools/run_slow_lane.sh; BENCH_BASELINE.json pins
``scrape_errors == 0``, ``schema_ok == 1`` and
``merged_trace_monotonic == 1`` through tools/bench_gate.py.
"""

import argparse
import copy
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHILD = os.path.join(REPO, "tools", "replica_child.py")


def spawn_child(replica: str, skew_ns: int = 0):
    """Start one replica_child (observability mode) and wait for
    its ready handshake.
    Returns (Popen, port)."""
    env = dict(os.environ)
    # the child builds its own 1-device CPU backend: scrub any runner
    # device-count flags (same idiom as tests/test_multiprocess.py)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, CHILD, "--replica", replica]
    if skew_ns:
        cmd += ["--skew-ns", str(skew_ns)]
    p = subprocess.Popen(cmd, cwd=REPO, env=env, text=True,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL)
    line = p.stdout.readline()      # blocks until the engine is up;
    if not line:                    # the slow lane's outer timeout caps it
        raise RuntimeError(
            f"replica_child {replica!r} died before the handshake "
            f"(rc={p.poll()})")
    return p, json.loads(line)["port"]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="accepted for slow-lane symmetry (children "
                         "always run JAX_PLATFORMS=cpu)")
    ap.add_argument("--json-out", default=os.path.join(
        REPO, "OBSWIRE_SAMPLE.json"))
    ap.add_argument("--skew-ns", type=int, default=250_000_000,
                    help="monotonic skew injected into child B")
    args = ap.parse_args()

    from deepspeed_tpu.config import ObsWireConfig
    from deepspeed_tpu.obs_wire import (
        FRESH, LOST, OBS_WIRE_SCHEMA_STR, RemoteReplica, WireSchemaError,
        check_wire_schema, merge_trace_segments)
    from deepspeed_tpu.utils.evidence import atomic_write_json
    from tools.trace_report import validate_chrome

    t_start = time.time()
    cfg = ObsWireConfig(enabled=True, poll_interval_s=0.05,
                        timeout_s=2.0, retries=2, backoff_s=0.02,
                        stale_after_s=0.5, lost_after_s=1.2,
                        fresh_after=2, offset_probes=12)

    out = {"t": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "wire_schema": OBS_WIRE_SCHEMA_STR,
           "cmd": "python tools/obswire_probe.py --cpu"}
    pa = pb = None
    try:
        pa, port_a = spawn_child("childA")
        pb, port_b = spawn_child("childB", skew_ns=args.skew_ns)
        ra = RemoteReplica(f"http://127.0.0.1:{port_a}", "childA",
                           cfg=cfg)
        rb = RemoteReplica(f"http://127.0.0.1:{port_b}", "childB",
                           cfg=cfg)

        # ---- scrape to FRESH over real HTTP ------------------------
        for rem in (ra, rb):
            deadline = time.monotonic() + 30
            while rem.state != FRESH and time.monotonic() < deadline:
                rem.poll()
                time.sleep(0.05)
        rows = [ra.statusz_row(), rb.statusz_row()]
        mets = ra.fetch_metrics()          # /metrics text round-trip
        out["scrape"] = {
            "states": {r.id: r.state for r in (ra, rb)},
            "scrapes": ra.scrapes + rb.scrapes,
            "rows": rows,
            "history_seen": bool(ra.last_historyz and rb.last_historyz),
            "slo_seen": bool(ra.slo_snapshot() and rb.slo_snapshot()),
            "metric_families": len(mets),
            "serving_metrics": sum(1 for k in mets
                                   if "serving_" in k),
        }
        scrape_ok = (ra.state == FRESH and rb.state == FRESH
                     and out["scrape"]["serving_metrics"] > 0)

        # ---- schema: real doc accepted, forged major rejected ------
        check_wire_schema(ra.last_healthz, "/healthz")
        forged = copy.deepcopy(ra.last_healthz)
        forged["wire_schema"] = "999.0"
        try:
            check_wire_schema(forged, "/healthz")
            schema_ok = False
        except WireSchemaError:
            schema_ok = True
        out["schema_ok"] = int(schema_ok)

        # ---- clock correlation vs the KNOWN injected skew ----------
        off_a, err_a = ra.estimate_clock_offset()
        off_b, err_b = rb.estimate_clock_offset()
        # childA shares this host's monotonic origin, childB reads
        # skew_ns ahead of it; scheduling jitter on a loaded CI box can
        # exceed the min-RTT bound, hence the additive slack
        slack_ns = 20_000_000
        offset_ok = (abs(off_a) <= err_a + slack_ns and
                     abs(off_b - args.skew_ns) <= err_b + slack_ns)
        out["clock"] = {
            "injected_skew_ns": args.skew_ns,
            "childA": {"offset_ns": off_a, "err_bound_ns": err_a},
            "childB": {"offset_ns": off_b, "err_bound_ns": err_b,
                       "recovery_error_ns": abs(off_b - args.skew_ns)},
            "slack_ns": slack_ns,
            "offset_within_bound": int(offset_ok),
        }

        # ---- cross-process trace merge -----------------------------
        ev_a, _ = ra.fetch_trace(since=0)
        ev_b, _ = rb.fetch_trace(since=0)
        merged = merge_trace_segments([
            {"events": ev_a, "offset_ns": off_a, "err_ns": err_a,
             "replica": "childA"},
            {"events": ev_b, "offset_ns": off_b, "err_ns": err_b,
             "replica": "childB"},
        ])
        validate_chrome(merged)         # raises on non-monotone ts or
        ts = [e["ts"] for e in merged["traceEvents"]  # unpaired spans
              if "ts" in e]             # (ph=M metadata carries no ts)
        tags = {(e.get("args") or {}).get("replica")
                for e in merged["traceEvents"]} - {None}
        merged_ok = (ts == sorted(ts) and
                     {"childA", "childB"} <= tags)
        out["merged_trace_monotonic"] = int(merged_ok)
        out["trace_merge"] = {
            "events": {"childA": len(ev_a), "childB": len(ev_b)},
            "chrome_events": len(merged["traceEvents"]),
            "replica_tags": sorted(tags),
            "clock_offsets": merged["otherData"]["clock_offsets"],
        }

        # ---- SIGKILL → LOST, snapshot retained, loop never wedges --
        pa.send_signal(signal.SIGKILL)
        pa.wait(timeout=10)
        deadline = time.monotonic() + 10
        max_poll_s = 0.0
        while ra.state != LOST and time.monotonic() < deadline:
            t0 = time.monotonic()
            ra.poll()                   # must absorb the dead peer
            max_poll_s = max(max_poll_s, time.monotonic() - t0)
            time.sleep(0.05)
        row = ra.statusz_row()
        out["sigkill"] = {
            "state": ra.state,
            "snapshot_retained": int(ra.last_statusz is not None),
            "row_state": row["state"],
            "scrape_age_s": row["scrape_age_s"],
            # per-poll wall time after the kill; bounded by
            # retries * (timeout + backoff), nowhere near a wedge
            "max_poll_s_after_kill": round(max_poll_s, 3),
        }
        lost_ok = (ra.state == LOST and ra.last_statusz is not None
                   and max_poll_s < cfg.retries * (cfg.timeout_s + 1.0))
        out["lost_after_sigkill"] = int(lost_ok)

        # post-kill transport errors are the staleness signal, not
        # failures of the plane — the gated count is from the healthy
        # scrape phase (and childB, never killed, end to end)
        out["scrape_errors"] = rb.scrape_errors + (
            0 if scrape_ok else ra.scrape_errors)
        out["ok"] = bool(scrape_ok and schema_ok and offset_ok
                         and merged_ok and lost_ok
                         and out["scrape_errors"] == 0)
    finally:
        for p in (pa, pb):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)

    out["duration_s"] = round(time.time() - t_start, 1)
    atomic_write_json(out, args.json_out)
    print(json.dumps({k: out[k] for k in
                      ("ok", "scrape_errors", "schema_ok",
                       "merged_trace_monotonic", "lost_after_sigkill",
                       "duration_s")}, indent=1))
    print("→", args.json_out)
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
