#!/usr/bin/env python
"""Per-request trace reporter (ISSUE 4 tentpole CLI).

Ingests either flight-recorder export — the JSONL structured log or the
Chrome trace-event JSON — and prints per-request WATERFALLS plus a
critical-path breakdown (queue wait vs prefill vs decode vs
stream-stall seconds, p50/p95):

    python tools/trace_report.py /tmp/dstpu_flight/flight_*.jsonl
    python tools/trace_report.py serving_trace.json

``--merge a.jsonl b.jsonl`` (or Chrome files) folds N per-process
segments into ONE monotone Chrome trace: each file's stamped clock
offset (obs_wire's min-RTT estimate, carried in the JSONL header /
``otherData``) shifts its events onto the local monotonic axis,
request spans stitch across replica tags, and the summary gains a
per-source segment count.

    python tools/trace_report.py --merge r0.jsonl r1.jsonl \\
        --merge-out merged.chrome.json

``--selftest`` drives a short traced gpt2 serving workload end to end,
exports BOTH formats next to ``--json-out``, validates the Chrome
export (parses back, monotonic ``ts``, matched async begin/end per
request), cross-checks the trace-derived TTFT against the telemetry
histogram (must agree within 1 ms — the two pillars measure the same
edges), prints the report, and stamps ``TRACE_SAMPLE.json`` (atomic) —
the slow lane (tools/run_slow_lane.sh) runs this on every pass.

    python tools/trace_report.py --selftest --cpu
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ------------------------------------------------------------- ingestion
def breakdown_from_chrome(trace: dict) -> dict:
    """Per-request components from the async span pairs of a Chrome
    export (same shape as ``request_breakdown``'s result, seconds).

    Requests still in flight at export time (the export force-closes
    their spans with ``args.truncated=true`` so the file always loads —
    exactly the hung requests a postmortem dump is about) are excluded
    from the stats and counted in ``summary.truncated_requests``,
    matching the JSONL path, which only measures observed edges."""
    spans = {}   # (id, name) -> [begin_ts, end_ts] in us
    truncated = set()
    spec = {}    # id -> {sweeps, drafted, accepted} from spec_accept
    kv = {}      # id -> {pages, wait_s} from kv_promote
    for ev in trace.get("traceEvents", []):
        if ev.get("cat") != "request":
            continue
        if ev.get("ph") == "n" and ev.get("name") == "spec_accept":
            args = ev.get("args") or {}
            rec = spec.setdefault(
                ev["id"], {"sweeps": 0, "drafted": 0, "accepted": 0})
            rec["sweeps"] += 1
            rec["drafted"] += int(args.get("drafted", 0))
            rec["accepted"] += int(args.get("accepted", 0))
            continue
        if ev.get("ph") == "n" and ev.get("name") == "kv_promote":
            args = ev.get("args") or {}
            rec = kv.setdefault(ev["id"], {"pages": 0, "wait_s": 0.0})
            rec["pages"] += int(args.get("pages", 0))
            rec["wait_s"] += float(args.get("wait_s", 0.0))
            continue
        if ev.get("ph") not in ("b", "e"):
            continue
        if (ev.get("args") or {}).get("truncated"):
            truncated.add(ev["id"])
            continue
        key = (ev["id"], ev["name"])
        rec = spans.setdefault(key, [None, None])
        rec[0 if ev["ph"] == "b" else 1] = ev["ts"]
    per = {}
    for (rid, name), (t0, t1) in spans.items():
        if t0 is None or t1 is None or rid in truncated:
            continue
        row = per.setdefault(rid, {})
        dur_s = (t1 - t0) / 1e6
        if name == "queued":
            row["queue_wait_s"] = dur_s
        elif name == "prefill":
            row["prefill_s"] = dur_s
        elif name == "decode":
            row["decode_s"] = dur_s
        elif name == "request":
            row["total_s"] = dur_s
    for row in per.values():
        if "queue_wait_s" in row and "prefill_s" in row:
            row["ttft_s"] = row["queue_wait_s"] + row["prefill_s"]
    stall = sum(ev.get("dur", 0.0) / 1e6
                for ev in trace.get("traceEvents", [])
                if ev.get("ph") == "X"
                and str(ev.get("name", "")).endswith("_stall"))
    from deepspeed_tpu.request_trace import (attach_kv_promotions,
                                             attach_speculation,
                                             kv_tier_summary,
                                             speculation_summary,
                                             summarize_components)

    spec = {rid: rec for rid, rec in spec.items()
            if rid not in truncated}
    kv = {rid: rec for rid, rec in kv.items() if rid not in truncated}
    attach_speculation(per, spec)
    attach_kv_promotions(per, kv)
    summary = summarize_components(per, stall)
    sp = speculation_summary(spec)
    if sp:
        summary["speculation"] = sp
    kt = kv_tier_summary(kv)
    if kt:
        summary["kv_tier"] = kt
    if truncated:
        summary["truncated_requests"] = sorted(str(r) for r in truncated)
    return {"requests": per, "summary": summary}


def device_time_summary(rows) -> dict:
    """Aggregate devprof's sampled device-time instants + compile
    ledger events into the report's device section.  ``rows`` is an
    iterable of ``(name, attrs)`` pairs — both export formats reduce
    to it.  Sampled means sampled: the totals cover one dispatch per
    1/devprof.sample_rate, a lower bound on device time, not a sum
    over every dispatch (that's the devprof_device_seconds counters'
    job)."""
    phases = {}
    compiles = {"warmup": 0, "steady": 0}
    for name, attrs in rows:
        attrs = attrs or {}
        if name == "devprof_sample":
            p = attrs.get("devprof_phase", "?")
            rec = phases.setdefault(p, {"dev_s": 0.0, "samples": 0})
            rec["dev_s"] += float(attrs.get("dev_s", 0.0))
            rec["samples"] += 1
        elif name == "xla_compile":
            compiles["steady" if attrs.get("steady") else
                     "warmup"] += int(attrs.get("n", 1))
    if not phases and not (compiles["warmup"] or compiles["steady"]):
        return {}
    return {"phases": phases, "compiles": compiles}


def load_breakdown(path: str) -> dict:
    from deepspeed_tpu.request_trace import read_jsonl, request_breakdown

    if path.endswith(".jsonl"):
        evs = read_jsonl(path)
        bd = request_breakdown(evs)
        dev = device_time_summary((e[3], e[4]) for e in evs)
    else:
        with open(path) as f:
            trace = json.load(f)
        bd = breakdown_from_chrome(trace)
        dev = device_time_summary(
            (ev.get("name"), ev.get("args"))
            for ev in trace.get("traceEvents", []))
    if dev:
        bd["summary"]["device"] = dev
    return bd


# ----------------------------------------------------------------- merge
def load_segment(path: str):
    """One trace file as a merge segment: ``(events, meta)`` where
    events are flight-recorder tuples and meta carries the per-file
    clock offset / replica tag when the exporter stamped them (JSONL:
    the ``flight_recorder`` header line; Chrome: ``otherData``)."""
    from deepspeed_tpu.request_trace import events_from_dicts

    if path.endswith(".jsonl"):
        meta, dicts = {}, []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "flight_recorder" in d:
                    meta = d["flight_recorder"]
                    continue
                dicts.append(d)
        return events_from_dicts(dicts), meta
    with open(path) as f:
        trace = json.load(f)
    od = trace.get("otherData", {})
    base = int(od.get("base_monotonic_ns", 0))
    # reconstruct absolute-monotonic tuples from the chrome ts (µs
    # from base); async request spans reduce to their begin/end edges
    events = []
    names = {"request": None, "queued": "queued", "prefill": "admitted",
             "decode": "first_token"}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M":
            continue
        t_ns = base + int(float(ev.get("ts", 0.0)) * 1000)
        if ev.get("cat") == "request":
            rid = ev.get("id")
            if ev["ph"] == "b" and ev["name"] in names:
                phase = names[ev["name"]]
                if phase:
                    events.append((t_ns, rid, -1, phase,
                                   ev.get("args")))
                elif ev["name"] == "request":
                    events.append((t_ns, rid, -1, "queued",
                                   None))
            elif ev["ph"] == "e" and ev["name"] == "request":
                events.append((t_ns, rid, -1, "finish",
                               ev.get("args")))
            elif ev["ph"] == "n":
                events.append((t_ns, rid, -1, ev["name"],
                               ev.get("args")))
        else:
            events.append((t_ns, None, -1, ev.get("name", "?"),
                           ev.get("args")))
    # dedup the double-begin the reconstruction above can produce for
    # the queued edge (request + queued open at the same ts)
    seen = set()
    uniq = []
    for e in sorted(events, key=lambda e: e[0]):
        k = (e[0], str(e[1]), e[3])
        if k in seen:
            continue
        seen.add(k)
        uniq.append(e)
    return uniq, od


def merge_traces(paths, out_path: str):
    """Fold N per-process exports into ONE monotone Chrome trace,
    applying each file's stamped clock offset (obs_wire's min-RTT
    estimate) so all segments share the local monotonic axis."""
    from deepspeed_tpu.obs_wire import merge_trace_segments

    segments = []
    sources = {}
    for i, path in enumerate(paths):
        events, meta = load_segment(path)
        tag = str(meta.get("replica")
                  or meta.get("pid") or f"seg{i}")
        segments.append({
            "events": events,
            "offset_ns": int(meta.get("clock_offset_ns") or 0),
            "err_ns": int(meta.get("clock_offset_err_ns") or 0),
            "replica": tag,
        })
        sources[os.path.basename(path)] = {
            "replica": tag, "events": len(events),
            "offset_ns": int(meta.get("clock_offset_ns") or 0)}
    merged = merge_trace_segments(segments)
    validate_chrome(merged)
    with open(out_path, "w") as f:
        json.dump(merged, f)
    bd = breakdown_from_chrome(merged)
    bd["summary"]["sources"] = sources
    return merged, bd


# -------------------------------------------------------------- printing
def print_report(bd: dict, limit: int = 20) -> None:
    per, summary = bd["requests"], bd["summary"]
    ms = lambda s: f"{1000 * s:9.2f}"
    print(f"{'request':>12} | {'queue ms':>9} | {'prefill ms':>10} | "
          f"{'decode ms':>9} | {'total ms':>9}  waterfall")
    shown = list(per.items())[:limit]
    for req, row in shown:
        total = row.get("total_s", 0.0)
        bar = ""
        if total > 0:
            width = 28
            for comp, ch in (("queue_wait_s", "."), ("prefill_s", "#"),
                             ("decode_s", "=")):
                bar += ch * max(int(width * row.get(comp, 0.0) / total),
                                1 if row.get(comp, 0.0) > 0 else 0)
        spec = (f"  spec×{row['spec_sweeps']} "
                f"len={row['spec_mean_accept_len']:.2f}"
                if row.get("spec_sweeps") else "")
        print(f"{str(req)[:12]:>12} | {ms(row.get('queue_wait_s', 0)):>9} | "
              f"{ms(row.get('prefill_s', 0)):>10} | "
              f"{ms(row.get('decode_s', 0)):>9} | "
              f"{ms(row.get('total_s', 0)):>9}  {bar}{spec}")
    if len(per) > len(shown):
        print(f"... {len(per) - len(shown)} more requests")
    print("\ncritical path (seconds):")
    for comp in ("queue_wait_s", "prefill_s", "decode_s", "ttft_s",
                 "total_s", "kv_promote_s"):
        if comp in summary:
            c = summary[comp]
            print(f"  {comp:<13} p50={c['p50']:.4f}  p95={c['p95']:.4f}  "
                  f"mean={c['mean']:.4f}  (n={c['n']})")
    print(f"  stream_stall_s total={summary['stream_stall_s']:.4f}")
    sp = summary.get("speculation")
    if sp:
        # decode-time attribution: each verify sweep is one model sweep
        # (one full weight stream under ZeRO-Inference) amortized over
        # mean_accept_len emitted tokens
        print(f"  speculation: {sp['sweeps']} verify sweeps, "
              f"{sp['drafted_tokens']} drafted / "
              f"{sp['accepted_tokens']} accepted "
              f"({sp['rejected_tokens']} rolled back), "
              f"mean accept len {sp['mean_accept_len']:.2f} "
              f"tokens/sweep")
    kt = summary.get("kv_tier")
    if kt:
        # promotion waits sit INSIDE prefill/TTFT: an evicted prefix
        # that cost a DMA shows here instead of as re-prefill compute
        print(f"  kv_tier: {kt['promotions']} promotions, "
              f"{kt['promoted_pages']} pages streamed back, "
              f"{kt['promote_wait_s']:.4f}s inside TTFT")
    dv = summary.get("device")
    if dv:
        # device truth next to the host columns above: the host clock
        # includes dispatch/python; these are block_until_ready deltas
        print("  device_s (sampled)  "
              + "  ".join(f"{p}={rec['dev_s']:.4f}s"
                          f"/{rec['samples']}x"
                          for p, rec in sorted(dv["phases"].items())))
        c = dv["compiles"]
        print(f"  xla compiles: {c['warmup']} warmup, "
              f"{c['steady']} steady"
              + ("  <-- STEADY-STATE RECOMPILE (shape drift)"
                 if c["steady"] else ""))
    if summary.get("truncated_requests"):
        print(f"  still in flight at export (excluded from stats): "
              f"{', '.join(summary['truncated_requests'])}")


# -------------------------------------------------------------- selftest
def validate_chrome(trace: dict) -> None:
    """The catapult contract the tests also assert: parses back,
    non-decreasing ``ts``, and every async begin has its end."""
    blob = json.dumps(trace)
    trace = json.loads(blob)
    evs = [e for e in trace["traceEvents"] if e["ph"] != "M"]
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts), "trace ts not monotonic"
    assert all(t >= 0 for t in ts), "negative ts"
    depth = {}
    for e in evs:
        if e.get("cat") == "request" and e["ph"] in ("b", "e"):
            d = depth.get(e["id"], 0) + (1 if e["ph"] == "b" else -1)
            assert d >= 0, f"async end before begin for {e['id']}"
            depth[e["id"]] = d
    dangling = {k: v for k, v in depth.items() if v}
    assert not dangling, f"unmatched async begins: {dangling}"


def selftest(args) -> int:
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from deepspeed_tpu.inference.serving import serving_engine
    from deepspeed_tpu.models import gpt2
    from deepspeed_tpu.request_trace import request_breakdown
    from deepspeed_tpu.utils.evidence import atomic_write_json

    cfg = gpt2.GPT2Config.tiny(dim=64, n_layers=2, n_heads=4,
                               max_seq_len=128)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    prompt_len = 24
    max_seq = prompt_len + args.new_tokens
    # speculation on: the stamped sample demonstrates draft/verify/
    # rollback attribution (spec_accept instants inside request spans,
    # sweep events on the speculative track, summary.speculation)
    # devprof on at sample_rate=1: the stamped sample demonstrates the
    # device-time column + compile ledger next to the host breakdown
    eng = serving_engine(
        params, cfg, max_batch=4, page_size=8,
        num_pages=4 * (-(-max_seq // 8)) + 16, max_seq=max_seq,
        prefill_bucket=8, decode_chunk=4, prefix_cache=True,
        speculative={"draft_tokens": 4},
        tracing={"sample_rate": 1.0},
        devprof={"sample_rate": 1.0})

    rng = np.random.default_rng(0)
    prefix = rng.integers(1, cfg.vocab_size, prompt_len - 4).tolist()
    t0 = time.perf_counter()
    for i in range(args.requests):
        eng.submit(i, prefix + rng.integers(1, cfg.vocab_size, 4).tolist(),
                   max_new_tokens=args.new_tokens)
    out = eng.run()
    wall = time.perf_counter() - t0

    eng.tracer.fold_comms()
    base = args.json_out.rsplit(".", 1)[0]
    chrome_path, jsonl_path = base + ".chrome.json", base + ".jsonl"
    trace = eng.tracer.export_chrome(chrome_path)
    eng.tracer.export_jsonl(jsonl_path)
    validate_chrome(trace)
    with open(chrome_path) as f:
        validate_chrome(json.load(f))
    print(f"# chrome export OK: {chrome_path} "
          f"({len(trace['traceEvents'])} events; load it in Perfetto or "
          "chrome://tracing)")
    print(f"# jsonl export:     {jsonl_path}")

    events = eng.tracer.recorder.events()
    bd = request_breakdown(events)
    dev = device_time_summary((e[3], e[4]) for e in events)
    if dev:
        bd["summary"]["device"] = dev
    print_report(bd)

    # the acceptance cross-check: trace-derived mean TTFT must agree
    # with the telemetry histogram (same submit→first-token edges,
    # independent clocks/plumbing) within 1 ms
    h = eng.registry.snapshot()["histograms"]["serving_ttft_seconds"]
    tel_ttft = h["mean"]
    trace_ttft = bd["summary"]["ttft_s"]["mean"]
    delta_ms = abs(tel_ttft - trace_ttft) * 1000
    print(f"\nTTFT mean: telemetry {1000 * tel_ttft:.3f} ms, "
          f"trace {1000 * trace_ttft:.3f} ms, delta {delta_ms:.4f} ms")
    ok = delta_ms < 1.0
    if not ok:
        print("FAIL: trace/telemetry TTFT disagree by >= 1 ms")

    atomic_write_json({
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "model": "gpt2-tiny",
        "requests": args.requests,
        "completed": len(out),
        "wall_s": round(wall, 2),
        "events_recorded": len(events),
        "dropped_events": eng.tracer.recorder.dropped,
        "chrome_trace_events": len(trace["traceEvents"]),
        "ttft_telemetry_ms": round(1000 * tel_ttft, 3),
        "ttft_trace_ms": round(1000 * trace_ttft, 3),
        "ttft_delta_ms": round(delta_ms, 4),
        "ttft_within_1ms": ok,
        "breakdown": bd["summary"],
        "devprof": eng.statusz().get("devprof", {}),
    }, args.json_out)
    print("→", args.json_out)
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", nargs="?",
                    help="flight-recorder export to report on "
                         "(.jsonl structured log or .json Chrome trace)")
    ap.add_argument("--merge", nargs="+", metavar="TRACE",
                    help="merge N per-process exports (.jsonl or "
                         "Chrome) into one monotone Chrome trace, "
                         "applying per-file clock offsets from the "
                         "trace meta; report on the merged view")
    ap.add_argument("--merge-out", default="merged_trace.chrome.json",
                    help="where --merge writes the merged Chrome "
                         "trace")
    ap.add_argument("--selftest", action="store_true",
                    help="drive a short traced gpt2 serving workload, "
                         "validate the exports, stamp TRACE_SAMPLE.json")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend in-process")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=24)
    ap.add_argument("--limit", type=int, default=20,
                    help="max per-request waterfall rows printed")
    ap.add_argument("--json-out",
                    default=os.path.join(REPO, "TRACE_SAMPLE.json"))
    args = ap.parse_args()

    if args.selftest:
        sys.exit(selftest(args))
    if args.merge:
        merged, bd = merge_traces(args.merge, args.merge_out)
        print(f"# merged {len(args.merge)} segments -> "
              f"{args.merge_out} "
              f"({len(merged['traceEvents'])} events, monotone)")
        for src, rec in bd["summary"]["sources"].items():
            print(f"#   {src}: {rec['events']} events "
                  f"[{rec['replica']}] offset {rec['offset_ns']}ns")
        print_report(bd, limit=args.limit)
        return
    if not args.trace:
        ap.error("give a trace file, --merge, or --selftest")
    print_report(load_breakdown(args.trace), limit=args.limit)


if __name__ == "__main__":
    main()
