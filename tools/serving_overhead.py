#!/usr/bin/env python
"""Serving scheduler-overhead breakdown (round-4 verdict task 6).

FastGen's claim is iteration-level scheduling with negligible host cost;
this tool bounds OUR host cost without needing the TPU: for each
(model, slots, decode_chunk) point it

  1. drives the full ServingEngine (submit/admit/prefill/decode/retire)
     and records wall-clock per decode step, then
  2. replays the engine's OWN compiled decode-chunk function on the
     final cache state, giving pure jit ms per decode step, so

     scheduler_ms_per_step = total_ms_per_step - jit_ms_per_step

is the host's bookkeeping cost (sampling bookkeeping, page-table
uploads, queue management, slot retire).  Prompts are kept short and
generations long so prefill contributes little to the total; the
residual is reported per point, not hidden.

Writes SERVING_OVERHEAD.json.  Runs on any backend; CPU numbers bound
the scheduler cost (the host work is backend-independent; only
jit_ms_per_step changes on the TPU).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure_point(model_name, slots, decode_chunk, prompt_len=8,
                  new_tokens=48, requests=None, telemetry=True,
                  tracing=True, slo=False, history=False,
                  devprof=False, obs_wire=False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference.serving import serving_engine
    from deepspeed_tpu.models import gpt2, llama, mixtral

    if model_name == "mixtral":
        mod, cfg = mixtral, mixtral.MixtralConfig.tiny(
            dim=64, n_layers=2, n_heads=4, n_kv_heads=2, num_experts=4)
    elif model_name == "gpt2":
        mod, cfg = gpt2, gpt2.GPT2Config.tiny(dim=64, n_layers=2,
                                              n_heads=4, max_seq_len=128)
    else:
        mod, cfg = llama, llama.LlamaConfig.tiny(dim=64, n_layers=2,
                                                 n_heads=4, n_kv_heads=2)
    params = mod.init_params(jax.random.PRNGKey(0), cfg)
    requests = requests or 2 * slots
    max_seq = prompt_len + new_tokens
    # the slo arm declares real objectives on the default tier so the
    # enabled path pays classification + window bookkeeping, not a
    # degenerate no-objective fast path
    slo_block = {"tiers": {"default": {
        "ttft_s": 30.0, "itl_s": 5.0, "deadline_s": 120.0}}} \
        if slo else None
    # the history arm runs BOTH new blocks at their production
    # cadences (1 s sampling / 1 s evaluation): the claim under test is
    # that the per-step cost of the shared tick pass is one monotonic
    # compare, whatever the rings record when a tick lands
    history_block = {"sample_interval_s": 1.0} if history else None
    incidents_block = None
    if history:
        import tempfile

        incidents_block = {
            "dir": tempfile.mkdtemp(prefix="dstpu_overhead_inc_"),
            "eval_interval_s": 1.0}
    # the obs_wire arm serves a REAL ephemeral-port HTTP exporter and
    # keeps a RemoteReplica scraping it throughout the timed loop —
    # the enabled delta is the price of being observed over the wire
    # (the exporter handles requests on its own thread; the engine
    # step loop itself has no obs_wire code path)
    telemetry_block = {"http_port": 0} if obs_wire else telemetry
    eng = serving_engine(
        params, cfg, max_batch=slots, page_size=8,
        num_pages=slots * (-(-max_seq // 8)) + 8, max_seq=max_seq,
        prefill_bucket=prompt_len, decode_chunk=decode_chunk,
        telemetry=telemetry_block, tracing=tracing, slo=slo_block,
        history=history_block, incidents=incidents_block,
        devprof=bool(devprof))
    scrape_stop = scraper = rem = None
    if obs_wire:
        import threading

        from deepspeed_tpu.config import ObsWireConfig
        from deepspeed_tpu.obs_wire import RemoteReplica

        rem = RemoteReplica(
            f"http://127.0.0.1:{eng._tel_exporter.port}", "ab",
            cfg=ObsWireConfig(enabled=True, poll_interval_s=0.05,
                              timeout_s=1.0, retries=1))
        scrape_stop = threading.Event()

        def _scrape_loop():
            while not scrape_stop.is_set():
                rem.maybe_poll()
                scrape_stop.wait(0.02)

        scraper = threading.Thread(target=_scrape_loop, daemon=True)
        scraper.start()

    def decode_steps():
        return int(eng.registry.snapshot()["counters"]
                   .get("serving_decode_steps", 0))

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(requests)]
    # warmup: compile prefill + decode chunk
    eng.submit("warmup", prompts[0], max_new_tokens=2)
    eng.run()
    eng.drain_finished()

    warmup_steps = decode_steps()

    for i, p in enumerate(prompts):
        eng.submit(i, p, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    calls = 0
    while eng.has_work:
        eng.step()
        calls += 1
    wall = time.perf_counter() - t0
    if scrape_stop is not None:
        scrape_stop.set()
        scraper.join(timeout=5)
    out = eng.drain_finished()
    generated = sum(len(v) - prompt_len for v in out.values())
    # warmup's decode steps are outside the timed window — they must
    # not dilute the per-step cost
    steps = decode_steps() - warmup_steps
    if steps <= 0:
        # telemetry disabled: the registry counters are no-ops; every
        # iteration of this workload runs one K-step decode chunk
        # (prompts admit whole, slots never idle), so calls*K is the
        # same count the stats path reports
        steps = calls * eng.decode_chunk
    total_ms = 1000 * wall / max(steps, 1)

    # pure jit cost of one decode step: replay the engine's compiled
    # chunk fn, feeding the returned cache back in (its donated input)
    K = eng.decode_chunk
    tok = jnp.zeros((slots, 1), jnp.int32)
    temps = jnp.zeros((slots,), jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(1),
                            K * slots).reshape(K, slots, 2)
    c = eng.cache
    toks, c = eng._decode_chunk_fn(eng.params, tok, c, keys, temps)
    float(jnp.sum(toks))  # ensure compiled + done
    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        toks, c = eng._decode_chunk_fn(eng.params, tok, c, keys, temps)
    float(jnp.sum(toks))
    jit_ms = 1000 * (time.perf_counter() - t0) / (iters * K)

    return {
        "model": model_name, "slots": slots, "decode_chunk": K,
        "requests": requests, "generated": generated,
        "telemetry": bool(telemetry), "tracing": bool(tracing),
        "slo": bool(slo), "history": bool(history),
        "devprof": bool(devprof), "obs_wire": bool(obs_wire),
        "scrapes_during_run": rem.scrapes if rem is not None else 0,
        "scrape_errors_during_run":
            rem.scrape_errors if rem is not None else 0,
        "decode_steps": steps,
        "prefill_chunks": int(eng.registry.snapshot()["counters"]
                              .get("serving_prefill_chunks", 0)),
        "total_ms_per_step": round(total_ms, 3),
        "jit_ms_per_step": round(jit_ms, 3),
        "scheduler_ms_per_step": round(max(total_ms - jit_ms, 0.0), 3),
        "scheduler_fraction": round(
            max(total_ms - jit_ms, 0.0) / total_ms, 3) if total_ms else None,
    }


def _ab(param, best_of=3, **fixed):
    """Best-of-N A/B of one measure_point flag: the decode loop with
    the feature DISABLED must sit within noise of the enabled loop's
    cost (CPU wall jitter dominates a single rep)."""
    ab = {}
    for on in (True, False):
        reps = [measure_point("llama", 4, decode_chunk=8,
                              **{param: on}, **fixed)
                for _ in range(best_of)]
        best = min(reps, key=lambda r: r["total_ms_per_step"])
        ab["enabled" if on else "disabled"] = best
        print(json.dumps({f"{param}_ab": best}), flush=True)
    d_ms = (ab["enabled"]["total_ms_per_step"]
            - ab["disabled"]["total_ms_per_step"])
    return ab, {
        "enabled_ms_per_step": ab["enabled"]["total_ms_per_step"],
        "disabled_ms_per_step": ab["disabled"]["total_ms_per_step"],
        "enabled_minus_disabled_ms": round(d_ms, 3),
        "enabled_overhead_fraction": round(
            max(d_ms, 0.0) / ab["disabled"]["total_ms_per_step"], 4)
        if ab["disabled"]["total_ms_per_step"] else None,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend in-process")
    ap.add_argument("--ab-only", action="store_true",
                    help="re-run only the telemetry/tracing overhead "
                         "A/Bs and merge into an existing json-out "
                         "(keeps the full sweep's rows)")
    ap.add_argument("--json-out",
                    default=os.path.join(REPO, "SERVING_OVERHEAD.json"))
    args = ap.parse_args()
    if args.ab_only and not os.path.exists(args.json_out):
        ap.error(f"--ab-only merges into an existing --json-out, but "
                 f"{args.json_out} does not exist (run the full sweep "
                 "first, or fix the path)")

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    rows = []
    if not args.ab_only:
        # slots sweep at the default chunking, all three families
        for model in ("llama", "mixtral", "gpt2"):
            for slots in (1, 2, 4, 8):
                rows.append(measure_point(model, slots, decode_chunk=8))
                print(json.dumps(rows[-1]), flush=True)
        # sync-amortization sweep: K=1 pays one host sync per token
        for k in (1, 2, 4):
            rows.append(measure_point("llama", 4, decode_chunk=k))
            print(json.dumps(rows[-1]), flush=True)

    # telemetry-overhead A/B (ISSUE 2 acceptance): registry on vs off.
    # The enabled delta is the price of TTFT/ITL histograms + gauges on
    # every step.
    _, telemetry_overhead = _ab("telemetry", tracing=False)
    telemetry_overhead["backend"] = jax.default_backend()
    telemetry_overhead["note"] = (
        "best-of-3 ms/decode-step, registry enabled vs disabled on the "
        "same build; disabled path = no-op metric singletons, no clock "
        "reads in the decode loop")

    # tracing-overhead A/B (ISSUE 4 acceptance): flight recorder on vs
    # off, telemetry on in BOTH arms — the enabled delta is the price
    # of the lifecycle events (one ring append per edge + one per
    # decode sync).
    _, tracing_overhead = _ab("tracing")
    tracing_overhead["backend"] = jax.default_backend()
    tracing_overhead["note"] = (
        "best-of-3 ms/decode-step, flight recorder enabled vs disabled "
        "(telemetry on in both arms); disabled path = shared no-op "
        "tracer, no clock read, no ring append")

    # slo-overhead A/B (ISSUE 6 acceptance): per-tier classification +
    # rolling windows + burn gauges on vs off, telemetry/tracing on in
    # both arms — the enabled delta is the price of one shared clock
    # read per token and the finish-time classification.
    _, slo_overhead = _ab("slo")
    slo_overhead["backend"] = jax.default_backend()
    slo_overhead["note"] = (
        "best-of-3 ms/decode-step, SLO tracker enabled (default tier "
        "with ttft/itl/deadline objectives) vs disabled on the same "
        "build (telemetry+tracing on in both arms); disabled path = "
        "shared no-op tracker")

    # history+incidents-overhead A/B (ISSUE 15 acceptance): rings +
    # incident detectors on vs off, telemetry/tracing/slo on in BOTH
    # arms — the enabled delta is the price of the exporter tick-hook
    # pass in the step loop (one monotonic compare until a hook is
    # due; sampling itself lands at most once per second, off the
    # decode hot path).
    _, history_overhead = _ab("history", slo=True)
    history_overhead["backend"] = jax.default_backend()
    history_overhead["note"] = (
        "best-of-3 ms/decode-step, history rings + incident engine "
        "enabled (1 s sampling / 1 s evaluation cadence) vs disabled "
        "on the same build (telemetry+tracing+slo on in both arms); "
        "the enabled path adds one tick-hook compare per step")

    # devprof-overhead A/B (ISSUE 17 acceptance): compile sentinel +
    # sampled device-time attribution + roofline counters on vs off,
    # telemetry/tracing on in BOTH arms — the enabled delta is the
    # price of the sentinel's cache-size check, two counter adds per
    # dispatch, and one block_until_ready per 1/sample_rate dispatches.
    _, devprof_overhead = _ab("devprof")
    devprof_overhead["backend"] = jax.default_backend()
    devprof_overhead["note"] = (
        "best-of-3 ms/decode-step, devprof enabled (compile sentinel + "
        "5% sampled block_until_ready attribution + per-dispatch "
        "flops/bytes accounting) vs disabled on the same build "
        "(telemetry+tracing on in both arms); disabled path = shared "
        "NULL_DEVPROF, wrap() is the identity")

    # obs_wire-overhead A/B (ISSUE 19 acceptance): a real HTTP
    # exporter on an ephemeral port + a RemoteReplica actively
    # scraping statusz/healthz/historyz at a 50 ms cadence during the
    # timed decode loop, vs the plain in-process registry —
    # telemetry/tracing on in BOTH arms.  The enabled delta is the
    # price of being observed over the wire; the decode loop itself
    # has no obs_wire branch, so the cost is exporter-thread GIL
    # contention only.
    _, obs_wire_overhead = _ab("obs_wire")
    obs_wire_overhead["backend"] = jax.default_backend()
    obs_wire_overhead["note"] = (
        "best-of-3 ms/decode-step, ephemeral-port HTTP exporter + "
        "live RemoteReplica scrape loop (50 ms cadence) vs in-process "
        "registry only (telemetry+tracing on in both arms); the "
        "engine step loop has no obs_wire code path — the delta is "
        "serving-the-scrapes contention")

    if args.ab_only and os.path.exists(args.json_out):
        with open(args.json_out) as f:
            out = json.load(f)
    else:
        out = {
            "metric": "serving_scheduler_overhead",
            "backend": jax.default_backend(),
            "note": ("scheduler_ms_per_step = wall/decode_steps minus "
                     "pure-jit replay of the engine's compiled decode "
                     "chunk; host cost is backend-independent, so the "
                     "CPU rows bound the TPU scheduler overhead"),
            "rows": rows,
        }
    out["telemetry_overhead"] = telemetry_overhead
    out["tracing_overhead"] = tracing_overhead
    out["slo_overhead"] = slo_overhead
    out["history_overhead"] = history_overhead
    out["devprof_overhead"] = devprof_overhead
    out["obs_wire_overhead"] = obs_wire_overhead
    with open(args.json_out, "w") as f:
        json.dump(out, f, indent=1)
    print("→", args.json_out)


if __name__ == "__main__":
    main()
