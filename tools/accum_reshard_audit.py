#!/usr/bin/env python
"""Gradient-accumulation reshard audit (round-2 verdict task 7): show,
from compiled HLO, what the in-jit microbatch split costs on the wire —
the naive contiguous reshape vs the device-aligned split the engine now
uses (engine.accum_split).

Writes ACCUM_AUDIT.json with both variants' collective digests.

    python tools/accum_reshard_audit.py
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as dstpu
from deepspeed_tpu.comm.digest import analyze_collectives
from deepspeed_tpu.engine import accum_split
from deepspeed_tpu.topology import MeshSpec

DP, ACCUM, MICRO, DIN, DOUT = 8, 4, 2, 64, 128


def digest_split(split_fn, label):
    """Compile grad-accum over the given split and digest its HLO."""
    ms = MeshSpec.build({"data": DP})
    B = MICRO * ACCUM * DP
    sh = ms.sharding(ms.batch_spec())
    w = jax.random.normal(jax.random.PRNGKey(0), (DIN, DOUT))

    def step(w, batch):
        mbatch = split_fn(batch)

        def micro(g, mb):
            gi = jax.grad(lambda ww: jnp.mean(
                (mb["x"] @ ww - mb["y"]) ** 2))(w)
            return g + gi, None

        g, _ = jax.lax.scan(micro, jnp.zeros_like(w), mbatch)
        return w - 0.1 * g / ACCUM

    rng = np.random.default_rng(0)
    batch = {"x": jnp.asarray(rng.normal(size=(B, DIN)), jnp.float32),
             "y": jnp.asarray(rng.normal(size=(B, DOUT)), jnp.float32)}
    compiled = jax.jit(step, in_shardings=(None, sh)).lower(
        w, batch).compile()
    d = analyze_collectives(compiled.as_text())
    d["label"] = label
    return d


def main():
    naive = digest_split(
        lambda b: jax.tree.map(
            lambda x: x.reshape((ACCUM, x.shape[0] // ACCUM) + x.shape[1:]),
            b),
        "naive contiguous reshape")
    aligned = digest_split(
        lambda b: accum_split(b, ACCUM, DP), "device-aligned accum_split")
    out = {
        "topology": {"dp": DP, "accum": ACCUM, "micro": MICRO},
        "naive_reshape": naive,
        "device_aligned_split": aligned,
        "batch_collective_bytes_removed":
            naive["total_bytes"] - aligned["total_bytes"],
        "conclusion": (
            "device-aligned split removes all batch-movement collectives"
            if set(aligned["per_kind"]) <= {"all-reduce"}
            else "device-aligned split STILL moves batch data — inspect"),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ACCUM_AUDIT.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
