#!/usr/bin/env python
"""One serving replica in its own OS process: engine + wire plane +
transport endpoint.

The generalization of the old obswire_child harness — ONE child
entrypoint for every subprocess replica:

- **observability mode** (default, what tools/obswire_probe.py
  spawns): build a tiny engine behind a REAL ephemeral-port HTTP
  introspection server, run a small traced workload, print the
  ready handshake, serve until killed.
- **fleet mode** (``--transport shm|tcp``, what
  :mod:`deepspeed_tpu.proc_fleet` spawns): additionally serve the
  engine's submit/poll/migrate/handoff verbs over a
  :class:`~deepspeed_tpu.transport.Channel` so a router in another
  process can drive it.  The engine spec arrives as a JSON blob
  (``--engine-json``) so children rebuild IDENTICAL params from
  ``(model config, seed)`` — same-host replicas are token-identical
  to an in-process oracle by construction.

Protocol: prints ONE JSON line ``{"port": N, "pid": P, "replica":
R, "tcp_port": T|null, "caps": {...}}`` to stdout once the engine is
up — the parent's ready handshake.  SIGTERM drains cleanly (stop
admitting, finish in-flight, engine shutdown); SIGKILL is the
failover path and needs no cooperation from this process — cleanup
is never load-bearing.  ``--skew-ns N`` shifts this process's
monotonic wire stamps (the obswire clock-correlation probe).
"""

import argparse
import json
import os
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_SPEC = {
    "model": {"family": "gpt2", "dim": 32, "n_layers": 2,
              "n_heads": 2, "max_seq_len": 64},
    "engine": {"max_batch": 2, "page_size": 8, "num_pages": 24,
               "max_seq": 32, "prefill_bucket": 8,
               "slo": True, "history": True},
    "seed": 0,
}


def build_engine(spec, replica):
    """Deterministic engine construction from a JSON spec: the same
    (model config, seed) yields bit-identical params in every process
    on this host, which is what makes cross-process token-identity
    checks meaningful."""
    import jax

    from deepspeed_tpu.inference.serving import serving_engine
    from deepspeed_tpu.models import gpt2

    m = dict(spec.get("model", {}))
    fam = m.pop("family", "gpt2")
    if fam != "gpt2":
        raise SystemExit(
            f"replica_child: unsupported engine family {fam!r} "
            "(the subprocess harness builds tiny gpt2 replicas)")
    cfg = gpt2.GPT2Config.tiny(**m)
    params = gpt2.init_params(
        jax.random.PRNGKey(int(spec.get("seed", 0))), cfg)
    kw = dict(spec.get("engine", {}))
    kw.setdefault("telemetry", {"http_port": 0})
    kw.setdefault("tracing", {"sample_rate": 1.0})
    eng = serving_engine(params, cfg, replica_id=replica, **kw)
    fab = None
    if spec.get("fabric"):
        # child-local TRANSIT fabric: export_pages stages entries here
        # before they cross the wire; admit publishes arrivals here so
        # admit_fabric's existing checksum-verified promotion path
        # consumes them unchanged
        from deepspeed_tpu.kv_fabric import KVFabric
        fab = KVFabric(spec["fabric"], registry=eng.registry)
        eng.attach_fabric(fab)
    return cfg, eng, fab


class ReplicaServer:
    """The child side of the proc-fleet protocol: a single-threaded
    serve loop that alternates transport handling with engine steps.
    Finished results land in an ack-retained outbox — a lost or
    corrupted poll reply re-delivers them on the next poll, so a
    result that exists is never lost to the wire."""

    def __init__(self, eng, fab, chan):
        self.eng, self.fab, self.chan = eng, fab, chan
        self.outbox = []            # [ [idx, result dict] ... ]
        self.next_idx = 0
        self.submitted = set()      # rpc dedup (retried submits)
        self.closing = False
        self._last_digest = None
        self._digest_v = 0

    # ------------------------------------------------------- encoding
    def _pump_finished(self):
        from deepspeed_tpu.inference.serving import (RequestFailed,
                                                     RequestShed)
        eng = self.eng
        for rid in list(eng.finished.keys()):
            res = eng.finished.pop(rid)
            if isinstance(res, RequestShed):
                enc = {"rid": rid, "kind": "shed",
                       "reason": res.reason, "tier": res.tier}
            elif isinstance(res, RequestFailed):
                enc = {"rid": rid, "kind": "failed",
                       "reason": res.reason, "error": res.error,
                       "tier": res.tier,
                       "generated": int(res.generated)}
            else:
                enc = {"rid": rid, "kind": "ok",
                       "tokens": [int(t) for t in res]}
            self.outbox.append([self.next_idx, enc])
            self.next_idx += 1

    def _progress(self):
        # req_ids ride as JSON VALUES (lists of pairs), never as JSON
        # object keys — an int id must come back an int
        return {
            "queued": [r.req_id for r in self.eng.queue],
            "active": [[s.req.req_id, len(s.generated)]
                       for s in self.eng.slots if s is not None],
        }

    def _digest_delta(self):
        d = {k.hex(): v for k, v in self.eng.warm_digest().items()}
        if d == self._last_digest:
            return None
        self._last_digest = d
        self._digest_v += 1
        return d

    # ------------------------------------------------------- handlers
    def handle(self, msg, blobs):
        """Dispatch one request; returns (reply_msg, reply_blobs).
        Every op is idempotent under RPC retry: duplicate submits
        dedup, a re-polled outbox re-delivers, a second take_queued /
        abandon just finds nothing left."""
        from deepspeed_tpu import transport as tx
        from deepspeed_tpu.inference.serving import EngineClosed
        eng = self.eng
        op = msg.get("op")
        if op == "submit":
            rid = msg["req_id"]
            key = repr(rid)
            if key in self.submitted:
                return {"ok": True, "dup": True}, ()
            arrival = time.perf_counter() - float(msg.get("age_s", 0.0))
            try:
                shed = eng.submit(
                    rid, msg["tokens"],
                    max_new_tokens=int(msg.get("max_new_tokens", 32)),
                    temperature=float(msg.get("temperature", 0.0)),
                    tier=msg.get("tier"), arrival=arrival)
            except EngineClosed:
                return {"closed": True}, ()
            except ValueError as e:
                return {"error": str(e)}, ()
            if shed is not None:
                eng.finished.pop(rid, None)
                return {"shed": {"reason": shed.reason,
                                 "tier": shed.tier}}, ()
            self.submitted.add(key)
            return {"ok": True}, ()
        if op == "poll":
            ack = int(msg.get("ack", -1))
            self.outbox = [e for e in self.outbox if e[0] > ack]
            self._pump_finished()
            rep = {
                "results": self.outbox,
                "progress": self._progress(),
                "has_work": bool(eng.has_work),
                "healthz": eng.healthz(),
                "slo": eng.slo_tracker.snapshot(),
                "counters": {"n_shed": eng._n_shed,
                             "n_failed": eng._n_failed,
                             "n_submitted": eng._n_submitted},
            }
            d = self._digest_delta()
            if d is not None:
                rep["digest"] = d
            rep["digest_v"] = self._digest_v
            return rep, ()
        if op == "take_queued":
            taken = eng.take_queued()
            return {"queued": [r.req_id for r in taken]}, ()
        if op == "abandon":
            outs = eng.abandon_inflight()
            return {"inflight": [[r.req_id, int(g)]
                                 for r, g in outs]}, ()
        if op == "export":
            keys = [bytes.fromhex(k) for k in msg["keys"]]
            if self.fab is None:
                return {"error": "no fabric on this child", "n": 0}, ()
            try:
                n = eng.export_pages(keys, fabric=self.fab)
            except Exception as e:
                return {"error": str(e), "n": 0}, ()
            entries = [self.fab.entries[k] for k in keys[:n]
                       if k in self.fab.entries]
            rep, rblobs = tx.entries_to_frame(entries, {"n": n})
            return rep, rblobs
        if op == "admit":
            if self.fab is None:
                return {"error": "no fabric on this child",
                        "admitted": 0}, ()
            entries = tx.entries_from_frame(msg, blobs)
            for e in entries:
                try:
                    self.fab.publish(e.key, e)
                except Exception:
                    break
            keys = [bytes.fromhex(k) for k in msg["keys"]]
            deadline = time.perf_counter() + float(
                msg.get("budget_s", 5.0))
            n = eng.admit_fabric(keys, deadline=deadline)
            locs = []
            for k in keys[:n]:
                if k in eng.allocator.index:
                    locs.append([k.hex(), "hbm"])
                else:
                    locs.append([k.hex(),
                                 eng._kv_pool.location(k) or "host"])
            return {"admitted": n, "locations": locs}, ()
        if op == "healthz":
            return eng.healthz(), ()
        if op == "check_leaks":
            return {"leaks": eng.check_leaks()}, ()
        if op == "warm_digest":
            return {"digest": {k.hex(): v for k, v in
                               eng.warm_digest().items()}}, ()
        if op == "shutdown":
            self.closing = True
            return {"ok": True}, ()
        return {"error": f"unknown op {op!r}"}, ()

    # ------------------------------------------------------ serve loop
    def serve(self, drain_grace_s: float = 10.0):
        from deepspeed_tpu import transport as tx
        from deepspeed_tpu.utils.logging import logger
        drain_deadline = None
        while True:
            if self.closing and drain_deadline is None:
                drain_deadline = time.monotonic() + drain_grace_s
            if self.closing and (not self.eng.has_work
                                 or time.monotonic() > drain_deadline):
                break
            timeout = 0.0 if self.eng.has_work else 0.02
            try:
                got = self.chan.recv(timeout_s=timeout)
            except tx.TransportCorrupt:
                continue        # drop the frame; the caller's RPC
                                # retry re-sends it
            except tx.TransportError:
                break           # parent gone — no reason to linger
            if got is not None:
                msg, blobs = got
                try:
                    rep, rblobs = self.handle(msg, blobs)
                except Exception as e:
                    logger.exception("replica_child: op failed")
                    rep, rblobs = {"error": repr(e)}, ()
                if "_seq" in msg:
                    rep["_seq"] = msg["_seq"]
                try:
                    self.chan.send(rep, rblobs)
                except tx.TransportError:
                    break
            if self.eng.has_work:
                try:
                    self.eng.step()
                except Exception:
                    logger.exception("replica_child: engine step")
                    break
                self._pump_finished()
            elif got is None and os.getppid() == 1:
                break           # orphaned by a dead parent: exit
        try:
            self.eng.shutdown()
        except Exception:
            pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica", default="child0")
    ap.add_argument("--skew-ns", type=int, default=0)
    ap.add_argument("--requests", type=int, default=4,
                    help="preload workload size (observability mode)")
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--engine-json", default=None,
                    help="engine spec blob; default = the obswire "
                         "probe's tiny gpt2")
    ap.add_argument("--transport", default="none",
                    choices=("none", "tcp", "shm"))
    ap.add_argument("--shm-c2s", default=None)
    ap.add_argument("--shm-s2c", default=None)
    ap.add_argument("--accept-timeout-s", type=float, default=60.0)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)

    if args.skew_ns:
        # simulate a foreign monotonic origin: every wire_stamp (and
        # therefore every /statusz//healthz//historyz//tracez doc this
        # process serves) reads skew_ns ahead of the true clock
        from deepspeed_tpu import obs_wire

        real_stamp = obs_wire.wire_stamp

        def skewed_stamp():
            d = real_stamp()
            d["t_mono_ns"] += args.skew_ns
            return d

        obs_wire.wire_stamp = skewed_stamp

    spec = (json.loads(args.engine_json)
            if args.engine_json else DEFAULT_SPEC)
    cfg, eng, fab = build_engine(spec, args.replica)

    if args.requests:
        import numpy as np
        rng = np.random.default_rng(0)
        for i in range(args.requests):
            eng.submit(i, rng.integers(1, cfg.vocab_size, 6).tolist(),
                       max_new_tokens=args.new_tokens)
        eng.run()

    listener = None
    if args.transport == "tcp":
        from deepspeed_tpu.transport import TcpListener
        listener = TcpListener()

    caps = {
        "kvt_on": bool(getattr(eng, "_kvt_on", False)),
        "pc_on": bool(getattr(eng, "_pc_on", False)),
        "eos": getattr(eng, "eos", None),
        "page_size": int(eng.page_size),
        "weights_version": getattr(eng, "weights_version", None),
        "max_seq": int(eng.max_seq),
        "vocab_size": int(cfg.vocab_size),
    }
    print(json.dumps({"port": eng._tel_exporter.port,
                      "pid": os.getpid(),
                      "replica": args.replica,
                      "tcp_port": listener.port if listener else None,
                      "caps": caps}), flush=True)

    if args.transport == "none":
        # observability mode: HTTP is the only plane — serve until
        # killed, SIGTERM shuts the engine down cleanly
        def bye(signum, frame):
            eng.shutdown()
            sys.exit(0)

        signal.signal(signal.SIGTERM, bye)
        while True:
            time.sleep(0.2)

    from deepspeed_tpu import transport as tx

    if args.transport == "tcp":
        endpoint = listener.accept(timeout_s=args.accept_timeout_s)
    else:
        if not (args.shm_c2s and args.shm_s2c):
            raise SystemExit(
                "replica_child: --transport shm needs --shm-c2s and "
                "--shm-s2c ring paths")
        endpoint = tx.attach_shm_pair(args.shm_c2s, args.shm_s2c,
                                      "server")
    chan = tx.Channel(endpoint, peer="parent", registry=eng.registry)
    server = ReplicaServer(eng, fab, chan)

    def drain(signum, frame):
        # SIGTERM = planned drain: stop admitting, let in-flight work
        # finish inside the serve loop's grace window, then shut down
        server.closing = True

    signal.signal(signal.SIGTERM, drain)
    server.serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
