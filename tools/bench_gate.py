#!/usr/bin/env python
"""Bench regression gate: diff the stamped evidence files against a
committed baseline manifest and exit nonzero on regressions — the perf
trajectory (serving tokens/s, prefix-cache TTFT wins, speculative
amortization, observability overhead) becomes an enforced contract
rather than folklore.

Manifest (``BENCH_BASELINE.json``): one entry per gated metric —

    {"file": "SPEC_BENCH.json",          # evidence file (repo-relative)
     "path": "spec_ab.speedup",          # dot path into its JSON
     "baseline": 1.448,                  # the committed value
     "direction": "higher",              # higher|lower is better
     "rel_tol": 0.25,                    # allowed fractional slack
     "abs_tol": 0.0,                     # allowed absolute slack
     "when": {"path": "backend",         # optional: gate only when a
              "equals": "cpu"}}          #   provenance key matches

A ``higher`` metric regresses when
``value < baseline * (1 - rel_tol) - abs_tol``; a ``lower`` metric when
``value > baseline * (1 + rel_tol) + abs_tol`` (abs_tol carries
near-zero metrics like overhead fractions, where any rel_tol is
meaningless).  A missing file SKIPs (the slow lane stamps evidence
best-effort; an absent stamp is not a regression) unless ``--strict``;
a missing *path inside a present file* FAILS — that is a schema break,
exactly what the gate exists to catch.

    python tools/bench_gate.py --check            # gate, exit 1 on fail
    python tools/bench_gate.py --check --json-out BENCH_GATE.json
    python tools/bench_gate.py --update           # re-baseline from the
                                                  # current evidence

``tools/run_slow_lane.sh`` runs ``--check`` after re-stamping the
evidence files, so every slow-lane cadence leaves a pass/fail verdict
(``BENCH_GATE.json``) next to the stamps.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_MANIFEST = os.path.join(REPO, "BENCH_BASELINE.json")


def get_path(obj, dot_path: str):
    """Resolve ``a.b.0.c`` (ints index lists); raises KeyError."""
    cur = obj
    for part in dot_path.split("."):
        if isinstance(cur, list):
            cur = cur[int(part)]
        elif isinstance(cur, dict):
            cur = cur[part]
        else:
            raise KeyError(
                f"{dot_path!r}: hit a leaf before {part!r}")
    return cur


def check_entry(entry: dict, files_root: str, cache: dict) -> dict:
    """Evaluate one manifest entry → a verdict row."""
    fname = entry["file"]
    row = {"file": fname, "path": entry["path"],
           "baseline": entry.get("baseline"),
           "direction": entry.get("direction", "higher")}
    fpath = os.path.join(files_root, fname)
    if fname not in cache:
        try:
            with open(fpath) as f:
                cache[fname] = json.load(f)
        except FileNotFoundError:
            cache[fname] = None
        except json.JSONDecodeError as e:
            cache[fname] = e
    doc = cache[fname]
    if doc is None:
        row.update(status="SKIP", reason="evidence file missing")
        return row
    if isinstance(doc, json.JSONDecodeError):
        row.update(status="FAIL", reason=f"unparseable JSON: {doc}")
        return row
    when = entry.get("when")
    if when:
        try:
            actual = get_path(doc, when["path"])
        except (KeyError, IndexError, ValueError):
            actual = None
        if actual != when["equals"]:
            row.update(status="SKIP",
                       reason=f"{when['path']}={actual!r} != "
                              f"{when['equals']!r}")
            return row
    try:
        value = get_path(doc, entry["path"])
    except (KeyError, IndexError, ValueError) as e:
        row.update(status="FAIL",
                   reason=f"metric path missing (schema break): {e}")
        return row
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        row.update(status="FAIL",
                   reason=f"metric is not numeric: {value!r}")
        return row
    row["value"] = value
    base = float(entry["baseline"])
    rel = float(entry.get("rel_tol", 0.0))
    ab = float(entry.get("abs_tol", 0.0))
    if entry.get("direction", "higher") == "higher":
        floor = base * (1.0 - rel) - ab
        row["bound"] = round(floor, 6)
        ok = value >= floor
    else:
        ceil = base * (1.0 + rel) + ab
        row["bound"] = round(ceil, 6)
        ok = value <= ceil
    row["status"] = "PASS" if ok else "FAIL"
    if not ok:
        row["reason"] = (
            f"{entry['path']} = {value} regressed past bound "
            f"{row['bound']} (baseline {base}, "
            f"{entry.get('direction', 'higher')} is better)")
    return row


def run_gate(manifest: dict, files_root: str,
             strict: bool = False) -> dict:
    """Gate every manifest entry; returns the verdict document."""
    cache: dict = {}
    rows = [check_entry(e, files_root, cache)
            for e in manifest["entries"]]
    if strict:
        for r in rows:
            if r["status"] == "SKIP":
                r["status"] = "FAIL"
                r["reason"] = "--strict: " + r.get("reason", "skipped")
    n_fail = sum(r["status"] == "FAIL" for r in rows)
    return {
        "ok": n_fail == 0,
        "checked": len(rows),
        "passed": sum(r["status"] == "PASS" for r in rows),
        "skipped": sum(r["status"] == "SKIP" for r in rows),
        "failed": n_fail,
        "rows": rows,
    }


def update_baselines(manifest: dict, files_root: str) -> dict:
    """Rewrite every reachable entry's baseline from the current
    evidence (tolerances and provenance guards stay as committed)."""
    cache: dict = {}
    updated = skipped = 0
    for e in manifest["entries"]:
        row = check_entry(e, files_root, cache)
        if "value" in row:
            e["baseline"] = row["value"]
            updated += 1
        else:
            skipped += 1
    return {"updated": updated, "skipped": skipped}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--manifest", default=DEFAULT_MANIFEST)
    ap.add_argument("--files-root", default=REPO,
                    help="directory holding the evidence files")
    ap.add_argument("--check", action="store_true",
                    help="gate the current evidence (default action)")
    ap.add_argument("--update", action="store_true",
                    help="re-baseline the manifest from the current "
                         "evidence files")
    ap.add_argument("--strict", action="store_true",
                    help="missing evidence files fail instead of skip")
    ap.add_argument("--json-out", default=None,
                    help="also write the verdict document (atomic)")
    args = ap.parse_args()

    with open(args.manifest) as f:
        manifest = json.load(f)

    if args.update:
        res = update_baselines(manifest, args.files_root)
        from deepspeed_tpu.utils.evidence import atomic_write_json

        atomic_write_json(manifest, args.manifest)
        print(f"bench_gate: re-baselined {res['updated']} entries "
              f"({res['skipped']} unreachable) → {args.manifest}")
        return 0

    verdict = run_gate(manifest, args.files_root, strict=args.strict)
    import time

    verdict["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    verdict["manifest"] = os.path.relpath(args.manifest, args.files_root)
    for r in verdict["rows"]:
        mark = {"PASS": "ok  ", "SKIP": "skip", "FAIL": "FAIL"}[
            r["status"]]
        detail = (f"{r.get('value')} vs bound {r.get('bound')}"
                  if "value" in r else r.get("reason", ""))
        print(f"[{mark}] {r['file']}:{r['path']}  {detail}")
    print(f"bench_gate: {verdict['passed']} passed, "
          f"{verdict['skipped']} skipped, {verdict['failed']} FAILED")
    if args.json_out:
        from deepspeed_tpu.utils.evidence import atomic_write_json

        atomic_write_json(verdict, args.json_out)
        print("→", args.json_out)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
