#!/usr/bin/env python
"""Turnkey runner for the queued on-chip evidence backlog.

The axon TPU tunnel is down for multi-hour stretches; when it comes
back, ONE command must capture every queued measurement before it drops
again:

    python tools/onchip_backlog.py            # everything, priority order
    python tools/onchip_backlog.py --only bench,kernels

Each item runs as a subprocess under a hard deadline (the tunnel's
failure mode is an uninterruptible hang inside the first device touch,
so in-process timeouts don't work — round-1 postmortem).  Items write
their own evidence JSONs; this runner records per-item outcomes in
ONCHIP_RUNLOG.json and keeps going on failure.

Priority order (round-3 verdict task 1 + round-4 additions):
  probe     — hard-deadline jax.devices(); abort everything if down
  bench     — headline MFU with the measured 512/512 flash tiles +
              ZeRO-3 config (BENCH fields), writes BENCH_PREVIEW.json
  kernels   — flash/adam/paged/chunk sweeps incl. the above-gate
              paged-decode row (KERNEL_BENCH.json)
  serving   — baseline + split-fuse + int8 rows (SERVING_BENCH.json)
  tuning    — remat x batch sweep (TRAIN_TUNING.json) — decides whether
              bench.py's remat/batch leave MFU on the table
  infinity  — 1.38B phase-breakdown run with the fused C++ CPU-Adam +
              grad prefetch (INFINITY_BENCH.json; r3: 406 s/step)
  pstream   — the >HBM parameter-offload proof at 10B-class scale
              (PARAM_STREAM_BENCH.json)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def run_item(name, argv, deadline_s):
    print(f"=== {name} (deadline {deadline_s}s): {' '.join(argv)}",
          flush=True)
    t0 = time.perf_counter()
    # stream the child's output STRAIGHT to the .out file: a timed-out
    # run must still leave its partial output behind (round-5: the
    # serving item hung 900 s on a dropped tunnel and capture_output
    # left zero diagnostics)
    out_path = os.path.join(REPO, f"ONCHIP_{name}.out")
    err_path = os.path.join(REPO, f"ONCHIP_{name}.err")
    try:
        # separate files: interleaving stderr into stdout can corrupt
        # the final JSON result line the bench parser extracts
        with open(out_path, "w") as fo, open(err_path, "w") as fe:
            # unbuffered child stdio: a SIGKILL on timeout must not
            # discard block-buffered output — the partial capture is
            # the whole point
            p = subprocess.run(argv, cwd=REPO, timeout=deadline_s,
                               stdout=fo, stderr=fe, text=True,
                               env={**os.environ,
                                    "PYTHONUNBUFFERED": "1"})
        with open(out_path) as f:
            captured = f.read()
        with open(err_path) as f:
            err_tail = f.read()[-400:]
        out = {"rc": p.returncode, "s": round(time.perf_counter() - t0, 1),
               "stdout_tail": captured[-800:], "stderr_tail": err_tail}
        if name in ("bench", "bench_tuned") and p.returncode == 0:
            for line in reversed(captured.strip().splitlines()):
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                with open(os.path.join(
                        REPO, f"BENCH_PREVIEW_{name}.json"), "w") as f:
                    json.dump(row, f, indent=1)
                if row.get("detail", {}).get("backend") != "tpu":
                    # bench.py degrades to a CPU number on probe/OOM
                    # failure and still exits 0 — that is NOT a capture;
                    # leave the item failed so resume re-runs it
                    out["rc"] = 2
                    # head-truncate: the marker must survive the cap
                    out["stdout_tail"] = (
                        "cpu fallback (backend != tpu) — not captured; " +
                        out["stdout_tail"])[:800]
                break
    except subprocess.TimeoutExpired:
        tails = []
        for path in (out_path, err_path):
            try:
                with open(path) as f:
                    tails.append(f.read()[-400:])
            except OSError:
                tails.append("")
        out = {"rc": None, "s": deadline_s,
               "stdout_tail": "TIMEOUT; partial: " + tails[0],
               "stderr_tail": tails[1]}
    print(f"--- {name}: rc={out['rc']} in {out['s']}s", flush=True)
    return out


# round-5: the bench item deadline must exceed bench.py's INTERNAL
# TPU-child deadline (DSTPU_BENCH_TPU_S, defaulted here) or a
# slow-compiling TPU attempt kills the whole item, CPU fallback included
os.environ.setdefault("DSTPU_BENCH_TPU_S", "1500")
# persistent TPU compile cache shared by every backlog child: tunnel
# windows are ~5 min (r5), often shorter than one item's compile — a
# window that dies mid-compile still warms the cache, so the NEXT
# window resumes at execution instead of recompiling from scratch
# SAME dir bench.py's TPU child uses, so compiles accumulated in watcher
# windows also warm the driver's end-of-round bench run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dstpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
ITEMS = {
    "probe": ([PY, "-c", "import jax; print(jax.devices())"], 120),
    "bench": ([PY, "bench.py"], 1800),
    "kernels": ([PY, "tools/kernel_bench.py"], 1800),
    "serving": None,   # expanded below: four rows (base/splitfuse/int8/moe)
    "tuning": ([PY, "tools/train_tuning_sweep.py"], 1800),
    "autotune": ([PY, "tools/autotune_onchip.py"], 2400),
    # re-run after autotune: bench.py consumes AUTOTUNE_TABLE.json's
    # winner, so this is the tuned headline number
    "bench_tuned": ([PY, "bench.py"], 1800),
    # r5 kernels already captured when this was added, so the v2 decode
    # A/B (paged_decode_attention_v2 vs v1 vs gather) runs as its own item
    "kernels_v2": ([PY, "tools/kernel_bench.py", "--families",
                    "paged_decode_v2,chunk_prefill_v2,flash_packed",
                    "--json-out", "KERNEL_BENCH_V2.json"], 1800),
    "infinity": ([PY, "tools/infinity_evidence.py", "--steps", "3"], 7200),
    # 8b, cpu tier: the largest >HBM-bf16 proof this host can hold
    # (10b needs 137 GB of tier state vs 80 GB disk / 123 GB free RAM)
    "pstream": ([PY, "examples/param_stream_offload.py", "--scale", "8b",
                 "--tier", "cpu", "--steps", "2",
                 "--json-out", "PARAM_STREAM_BENCH.json"], 7200),
}
ORDER = ["probe", "bench", "kernels", "serving", "tuning", "autotune",
         "bench_tuned", "infinity", "pstream", "kernels_v2"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default="",
                    help="comma-separated subset of: " + ",".join(ORDER))
    ap.add_argument("--log", default=os.path.join(REPO,
                                                  "ONCHIP_RUNLOG.json"))
    args = ap.parse_args()
    picked = [s for s in args.only.split(",") if s] or ORDER
    unknown = [s for s in picked if s not in ORDER]
    if unknown:
        raise SystemExit(f"unknown --only items {unknown}; "
                         f"valid: {','.join(ORDER)}")

    # item-granular resume: completed items (rc 0 in an existing runlog)
    # are not re-run — a rerun after a mid-stage timeout must not burn
    # the tunnel window re-capturing what already succeeded
    log = {}
    if os.path.exists(args.log):
        try:
            with open(args.log) as f:
                log = {k: v for k, v in json.load(f).items()
                       if v.get("rc") == 0}
        except ValueError:
            log = {}

    def fresh(sub):
        if sub in log:
            print(f"--- {sub}: already captured, skipping", flush=True)
            return False
        return True

    for name in ORDER:
        if name not in picked:
            continue
        if name == "serving":
            # distinct evidence files — the default --json-out would
            # overwrite the baseline row with the variant rows
            for sub, extra in (
                    ("serving", ["--json-out", "SERVING_BENCH.json"]),
                    ("serving_splitfuse",
                     ["--prefill-chunk", "64",
                      "--json-out", "SERVING_SPLITFUSE.json"]),
                    ("serving_int8",
                     ["--weight-dtype", "int8",
                      "--json-out", "SERVING_INT8.json"]),
                    ("serving_moe",
                     ["--model", "mixtral",
                      "--json-out", "SERVING_MOE.json"]),
                    # ZeRO-Inference A/B: resident vs host-streamed
                    # rows in one file (bench_serving runs both when
                    # --zero-inference is set) — the >HBM serving
                    # bandwidth story on the real chip
                    ("serving_zero_inference",
                     ["--zero-inference",
                      "--json-out", "SERVING_ZERO_INFERENCE.json"])):
                if not fresh(sub):
                    continue
                log[sub] = run_item(
                    sub, [PY, "bench_serving.py"] + extra, 900)
                with open(args.log, "w") as f:
                    json.dump(log, f, indent=1)
            continue
        if not fresh(name):
            continue
        argv, deadline = ITEMS[name]
        log[name] = run_item(name, argv, deadline)
        # incremental: a caller-killed run must not lose the outcomes of
        # items that DID complete
        with open(args.log, "w") as f:
            json.dump(log, f, indent=1)
        if name == "probe" and log[name]["rc"] != 0:
            print("TPU probe failed — aborting the backlog run",
                  flush=True)
            break
    with open(args.log, "w") as f:
        json.dump(log, f, indent=1)
    print("→", args.log)


if __name__ == "__main__":
    main()
