#!/usr/bin/env bash
# Slow-lane coverage runner (round-5 verdict weak #6): the default test
# selection skips ~67 slow-marked equivalence tests to keep tier-1 fast,
# which means nothing was actually running them anywhere.  This script
# runs `pytest --runslow` on the 8-device CPU mesh and stamps the
# outcome into SLOW_LANE.json (then best-effort commits the stamp), so
# the heavy lane has a standing pass/fail record with a timestamp.
#
#   bash tools/run_slow_lane.sh
#
# Invoked by tools/onchip_watcher.py while the chip is down (idle time
# costs nothing) on a DSTPU_SLOW_LANE_CADENCE_S cadence; also fine to
# run by hand.  SLOW_LANE_DEADLINE_S caps the run (default 2700 s).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
OUT="${SLOW_LANE_OUT:-$REPO/SLOW_LANE.json}"
DEADLINE="${SLOW_LANE_DEADLINE_S:-2700}"
T0=$(date +%s)
LOG=$(mktemp /tmp/dstpu_slow_lane.XXXXXX.log)

# NO --continue-on-collection-errors: since the modern-mesh core
# landed (deepspeed_tpu/mesh.py) every module imports on the pinned
# JAX — the lane no longer tolerates the old shard_map failure floor,
# so a collection error is a hard regression that fails the run
# immediately instead of burning the deadline on the survivors
timeout -k 30 "$DEADLINE" env JAX_PLATFORMS=cpu python -m pytest tests/ \
  -q --runslow -p no:cacheprovider \
  2>&1 | tee "$LOG"
RC=${PIPESTATUS[0]}

# telemetry + introspection samples: every slow-lane run also stamps
# TELEMETRY_SAMPLE.json (a live registry snapshot off a short gpt2
# serving loop), STATUSZ_SAMPLE.json (/statusz, /healthz and a
# /requestz drill-down fetched over real HTTP from the same engine)
# and DEVPROF_SAMPLE.json (the compile ledger, per-phase device time
# and MFU/MBU via /statusz + /profilez incl. a short on-demand
# jax.profiler capture, same real HTTP server) next to SLOW_LANE.json
# — best-effort, never the reason the lane fails
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/telemetry_dump.py \
  --cpu --json-out "$REPO/TELEMETRY_SAMPLE.json" \
  --statusz-out "$REPO/STATUSZ_SAMPLE.json" \
  --devprof-out "$REPO/DEVPROF_SAMPLE.json" >/dev/null 2>&1 || true

# prefix-cache A/B: the shared-prefix workload served with caching off
# vs on (TTFT, tokens/s, hit rate) stamps PREFIX_BENCH.json through the
# same atomic evidence writer — best-effort like the telemetry sample
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_serving.py --cpu \
  --prefix-cache --requests 32 --new-tokens 16 \
  --json-out "$REPO/PREFIX_BENCH.json" >/dev/null 2>&1 || true

# speculative-decoding A/B: the repetitive-motif workload served with
# speculation off vs on, plus the ZeRO-Inference streamed pair whose
# rows record weight bytes streamed per generated token — stamps
# SPEC_BENCH.json, best-effort like the samples above.  --cpu-dim 512
# scales the smoke model past cache-resident (~28 MB bf16) so decode
# pays real weight reads — the bandwidth-bound regime speculation
# amortizes (the 64-dim toy is dispatch-bound and can't show it)
# requests > slots keeps the batch backfilled: per-slot acceptance
# variance otherwise leaves a low-occupancy straggler tail that still
# pays one full weight sweep per verify
timeout -k 10 900 env JAX_PLATFORMS=cpu python bench_serving.py --cpu \
  --speculative --zero-inference --slots 4 --requests 12 \
  --new-tokens 96 --cpu-dim 512 --cpu-layers 4 --repeats 2 \
  --json-out "$REPO/SPEC_BENCH.json" >/dev/null 2>&1 || true

# tiered-KV A/B: the eviction-churn workload (4 shared prefixes over a
# pool holding ~1.5) served with the host/NVMe spill tier off vs on,
# plus the no-eviction oracle row the token-identity gate compares
# against — stamps KV_TIER_BENCH.json, best-effort like the samples.
# --prefill-chunk 16 = split-fuse absorption, the production serving
# mode where a re-prefill costs prefix_len/16 chunk sweeps (the whole-
# prompt flash path is one fused dispatch and hides the cost on a CPU
# toy); --cpu-dim 256 puts real weight reads under each chunk
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_serving.py --cpu \
  --kv-tier --requests 32 --new-tokens 16 --cpu-dim 256 --cpu-layers 2 \
  --prefill-chunk 16 --repeats 2 \
  --json-out "$REPO/KV_TIER_BENCH.json" >/dev/null 2>&1 || true

# trace selftest: a short traced serving workload, Chrome-export
# validation (matched async spans, monotonic ts) + the trace-vs-
# telemetry TTFT cross-check, stamped into TRACE_SAMPLE.json —
# best-effort like the samples above
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/trace_report.py \
  --selftest --cpu --json-out "$REPO/TRACE_SAMPLE.json" \
  >/dev/null 2>&1 || true

# chaos soak: serve traffic under a seeded injected-fault schedule
# (aio failures, spilled-page corruption, slot exceptions, a queue
# burst) and assert graceful degradation — completed requests token-
# identical to a fault-free oracle, no watchdog fire, clean drain,
# zero page leaks, and shed/failed counts reconciling across
# telemetry, SLO and trace exports.  Stamps CHAOS_SOAK.json, gated by
# bench_gate below.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_soak.py \
  --cpu --json-out "$REPO/CHAOS_SOAK.json" >/dev/null 2>&1 || true

# fleet soak: the 3-replica router under a seeded schedule that kills
# one replica mid-traffic while the script drains and rejoins another
# — completed requests token-identical to a single-replica oracle,
# typed results for everything else, zero leaks/orphans, bounded
# failover recovery.  Stamps FLEET_SOAK.json, gated by bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_soak.py \
  --cpu --fleet --json-out "$REPO/FLEET_SOAK.json" >/dev/null 2>&1 || true

# open-loop fleet bench: Poisson arrival sweep past saturation
# (goodput-vs-load) plus a mid-traffic replica kill (failover
# recovery curve) — stamps FLEET_BENCH.json, best-effort
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_fleet.py --cpu \
  --json-out "$REPO/FLEET_BENCH.json" >/dev/null 2>&1 || true

# elastic soak: the autoscaler under a scripted load wave — scale up
# through an injected factory failure + slow cold-start, scale back
# down, a rolling weight update with a mid-rollout replica kill, and
# a burn-rate-tripped rollback — token identity, zero orphans/leaks,
# exactly-once scale/rollout events.  Stamps ELASTIC_SOAK.json, gated
# by bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_soak.py \
  --cpu --elastic --json-out "$REPO/ELASTIC_SOAK.json" >/dev/null 2>&1 || true

# elastic bench: sine-wave arrivals vs the autoscaler plus a live
# weight swap mid-wave — goodput, p99 TTFT, replica-count breathing,
# scale-up-to-first-token, and the zero-drop/orphan/leak gate rows.
# Stamps ELASTIC_BENCH.json, gated by bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_fleet.py --cpu \
  --elastic --json-out "$REPO/ELASTIC_BENCH.json" >/dev/null 2>&1 || true

# disagg soak: the prefill/decode roles fleet + KV fabric under
# seeded fabric faults (export error, fetch latency, in-fabric
# corruption after checksum) and a mid-handoff decode-replica kill,
# plus a drain/rejoin of the only prefill replica — token identity,
# corruption caught by the importer's crc, zero leaks/orphans.
# Stamps DISAGG_SOAK.json, gated by bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_soak.py \
  --cpu --disagg --json-out "$REPO/DISAGG_SOAK.json" >/dev/null 2>&1 || true

# disagg bench: the KV-fabric A/Bs — affinity-miss TTFT with
# migration on/off (gated: speedup >= 1, mismatched = 0) and goodput
# under prefill-heavy vs decode-heavy mixes with/without the role
# split.  Stamps DISAGG_BENCH.json, gated by bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_fleet.py --cpu \
  --disagg --json-out "$REPO/DISAGG_BENCH.json" >/dev/null 2>&1 || true

# out-of-process fleet soak: three REAL child processes behind the
# shm/TCP transport, a seeded wire-fault schedule (injected corruption
# caught by the frame crc, recv latency/error rules) and an actual
# SIGKILL mid-generation — harvest-first salvage, typed never-double-
# generate partition, token identity vs an in-process oracle, zero
# leaks/orphans/orphan-processes, bounded recovery.  Stamps
# PROC_SOAK.json, gated by bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_soak.py \
  --cpu --procs --json-out "$REPO/PROC_SOAK.json" >/dev/null 2>&1 || true

# out-of-process fleet bench: the in-process vs out-of-process
# throughput A/B (wire_cost_ratio), SIGKILL failover recovery on the
# proc fleet, and the shm-vs-tcp-vs-off KV-fabric migration A/B with
# cross-arm token identity.  Stamps PROC_FLEET_BENCH.json, gated by
# bench_gate.
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_fleet.py --cpu \
  --procs --json-out "$REPO/PROC_FLEET_BENCH.json" >/dev/null 2>&1 || true

# tensor-parallel serving A/B: the same traffic on a 1-device engine
# vs a 2-device model-axis mesh (virtual host CPUs) — decode tokens/s,
# TTFT, and the token-identity gate (tp_ab.mismatched_requests must
# stay 0: sharding is an execution strategy).  Stamps TP_BENCH.json,
# gated by bench_gate below.
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_serving.py --cpu \
  --tp 2 --requests 16 --new-tokens 32 --cpu-dim 256 --cpu-layers 2 \
  --json-out "$REPO/TP_BENCH.json" >/dev/null 2>&1 || true

# serving-gate crossover sweeps: the two families behind
# pallas_paged_gate / pallas_sample_gate.  On a chip they time the
# forced Pallas arms vs XLA at shapes bracketing the crossovers; on
# this CPU lane they stamp interpret-mode IDENTITY rows (explicit
# backend/note labels) and MERGE into KERNEL_BENCH.json without
# clobbering the committed TPU families.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/kernel_bench.py \
  --quick --families paged_v2_vs_xla,fused_sample_vs_xla \
  --json-out "$REPO/KERNEL_BENCH.json" >/dev/null 2>&1 || true

# forced-kernel serving A/B: the same traffic with every serving
# kernel forced off (paged=xla, fused_sampling=off) vs forced on
# (paged=pallas_v2 interpret, fused_sampling=on) — tokens/s, TTFT,
# and the token-identity gate (kernel_ab.mismatched_requests must
# stay 0: a kernel is an execution strategy).  Stamps
# KERNEL_SERVING_BENCH.json, gated by bench_gate below.
timeout -k 10 600 env JAX_PLATFORMS=cpu python bench_serving.py --cpu \
  --kernels --requests 16 --new-tokens 32 --cpu-dim 256 --cpu-layers 2 \
  --json-out "$REPO/KERNEL_SERVING_BENCH.json" >/dev/null 2>&1 || true

# hierarchical + quantized collectives A/B: the same ZeRO-2 training
# run under three gradient-wire schemes (flat f32 / flat int8 /
# two-level hierarchical int8) on the 8-device mesh — per-arm step
# times, the analytic wire-bytes table (ratio_vs_f32 >= 3.5), a
# 60-step loss-parity window, and the two zero-tolerance bit-exact
# contracts (qwZ trajectory identity, exact codec == pmean).  Stamps
# COMM_BENCH.json, gated by bench_gate below.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/comm_bench.py \
  --cpu --json-out "$REPO/COMM_BENCH.json" >/dev/null 2>&1 || true

# obs-wire truth gate: a real child process (own interpreter, own
# engine, ephemeral-port exporter) scraped over real HTTP — FRESH
# walk, forged-schema rejection, min-RTT offset recovery vs an
# injected 250 ms skew, the two-process trace merge, and the
# SIGKILL→LOST staleness walk with the loop never wedging.  Stamps
# OBSWIRE_SAMPLE.json; bench_gate pins scrape_errors == 0,
# schema_ok == 1, merged_trace_monotonic == 1.
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/obswire_probe.py \
  --cpu --json-out "$REPO/OBSWIRE_SAMPLE.json" >/dev/null 2>&1 || true

# static analysis: the four dstpu-lint pass families (hot-path
# host-sync lint, lock-order/scope, page lifecycle, surface parity
# incl. the Chrome-trace pairing check against the selftest stamp
# above) against the committed zero-waiver baseline.  Stamps
# LINT_REPORT.json; bench_gate pins violations == 0, waivers == 0,
# passes_run >= 4.  No JAX needed — the linter never imports the
# package it judges.
timeout -k 10 300 python tools/dstpu_lint.py --check \
  --json-out "$REPO/LINT_REPORT.json" || true

# bench regression gate: AFTER the stamps above, diff the evidence
# files against the committed BENCH_BASELINE.json and leave a verdict
# in BENCH_GATE.json — the perf trajectory as an enforced contract.
# The lane itself stays best-effort (exit 0), but the verdict is
# visible per cadence run and tier-1 tests assert the gate logic.
timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/bench_gate.py \
  --check --json-out "$REPO/BENCH_GATE.json" || true
SUMMARY=$(grep -aE '[0-9]+ (passed|failed|error|skipped)' "$LOG" | tail -1)

python - "$OUT" "$RC" "$T0" "$SUMMARY" <<'EOF'
import sys, time
sys.path.insert(0, ".")
from deepspeed_tpu.utils.evidence import atomic_write_json
out, rc, t0, summary = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), \
    sys.argv[4]
# atomic: the watcher TERM/KILLs this run when the chip comes up, and a
# truncated stamp with a fresh mtime would suppress the retry cadence
atomic_write_json({"t": time.strftime("%Y-%m-%dT%H:%M:%S"), "rc": rc,
                   "ok": rc == 0,
                   "duration_s": int(time.time()) - t0,
                   "summary": summary.strip(),
                   "cmd": "pytest tests/ -q --runslow"}, out)
EOF

# best-effort stamp commit (just this file); the round snapshot would
# pick it up anyway — this keeps the pass/fail visible per cadence run.
# add first: `commit -o` errors on a path git has never tracked, which
# is exactly the first cadence run
if [ "$RC" -eq 0 ]; then MSG="slow lane: pass"; else MSG="slow lane: fail rc=$RC"; fi
git -C "$REPO" add -- SLOW_LANE.json >/dev/null 2>&1 || true
git -C "$REPO" commit -o SLOW_LANE.json -m "$MSG" >/dev/null 2>&1 || true
rm -f "$LOG"
exit 0
