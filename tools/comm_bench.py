#!/usr/bin/env python
"""Hierarchical + quantized collectives A/B (ISSUE 18; ref: ZeRO++
arXiv:2306.10209, EQuARX arXiv:2506.17615): the same ZeRO-2 training
run under three gradient-wire schemes on the 8-device mesh —

  flat_f32     ring reduce over the full f32 payload (the baseline)
  flat_quant   one-level int8 wire (qgZ without the hierarchy)
  hier_quant   two-level schedule: intra quantized RS -> inter
               quantized exchange -> int8 gathers, bucketed overlap

Stamps ``COMM_BENCH.json`` with per-arm step times and loss
trajectories, the analytic per-device wire-bytes table (device truth:
tree size is static, so payload bytes are deterministic — the same
numbers the engine's ``comm_*`` counters carry), a >= ``--steps``-step
loss-parity block, and the two bit-exact contracts pinned at zero
mismatches:

  * qwZ trajectory identity — routing the stage-3 weight gather
    through the hierarchy must not move the loss AT ALL vs the flat
    int8 gather (the codes are made once, before any hop), and
  * the ``exact`` codec through the two-level schedule must be
    bit-equal to ``pmean`` on integer-valued data.

Gated rows (``BENCH_BASELINE.json`` via ``tools/bench_gate.py``):
``wire.ratio_vs_f32`` >= 3.5, both mismatch counts == 0, and the
parity window must actually span >= 50 steps.  Step TIME is stamped
but not gated on CPU: 8 virtual devices share one host core, so the
quantize fan-out costs here what the wire saves on a real fabric.

    python tools/comm_bench.py --cpu --json-out COMM_BENCH.json
    python tools/comm_bench.py --cpu --quick          # smoke (12 steps)
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORLD = 8
AXIS = "data"


def _mlp_loss(params, batch):
    import jax.numpy as jnp

    h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
    pred = h @ params["w2"] + params["b2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _build(zero, comm, hidden):
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu as dstpu

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {"w1": jax.random.normal(k1, (16, hidden)) * 0.3,
              "b1": jnp.zeros((hidden,)),
              "w2": jax.random.normal(k2, (hidden, 4)) * 0.3,
              "b2": jnp.zeros((4,))}
    cfg = {"train_micro_batch_size_per_gpu": 8,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "mesh": {AXIS: WORLD}, "zero_optimization": zero}
    if comm is not None:
        cfg["comm"] = comm
    engine, _, _, _ = dstpu.initialize(
        loss_fn=_mlp_loss, params=params, config=cfg)
    return engine


def _run_arm(eng, batch, steps):
    losses, times = [], []
    for i in range(steps):
        t0 = time.perf_counter()
        losses.append(float(eng.train_batch(batch)))  # float() syncs
        if i >= 2:  # first steps carry compile
            times.append(time.perf_counter() - t0)
    times.sort()
    return {
        "steps": steps,
        "first_loss": round(losses[0], 6),
        "final_loss": round(losses[-1], 6),
        "learned": losses[-1] < losses[0],
        "step_ms_p50": round(1e3 * times[len(times) // 2], 3),
        "step_ms_mean": round(1e3 * sum(times) / len(times), 3),
    }, losses


def _rel_gap(a, b):
    return abs(a - b) / max(abs(b), 1e-9)


def _bit_exact_checks(qwz_steps):
    """The two zero-tolerance contracts, counted as mismatches."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm import collectives as C
    from deepspeed_tpu.topology import MeshSpec

    # qwZ trajectory identity: flat int8 gather vs two-hop hpZ gather
    rng = np.random.default_rng(0)
    batch = {"x": jnp.asarray(rng.normal(size=(64, 16)), jnp.float32),
             "y": jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)}
    flat = _build({"stage": 3, "zero_quantized_weights": True},
                  {"hierarchy_size": 1}, hidden=32)
    hier = _build({"stage": 3, "zero_quantized_weights": True},
                  {"hierarchy_size": 2}, hidden=32)
    lf = [float(flat.train_batch(batch)) for _ in range(qwz_steps)]
    lh = [float(hier.train_batch(batch)) for _ in range(qwz_steps)]
    qwz_mism = sum(a != b for a, b in zip(lf, lh))

    # exact codec vs pmean on integer-valued data (bit-equal: every
    # arm is a SEPARATE jitted call compared host-side — subtracting
    # two collective pipelines inside one jit lets XLA reassociate
    # across them and manufactures ~1-ulp phantom diffs)
    ms = MeshSpec.build({AXIS: WORLD})
    x = jnp.asarray(rng.integers(-512, 512, size=(WORLD, 4096)),
                    jnp.float32)
    h = C.Hierarchy(WORLD, 2)

    def sharded(f):
        def body(loc):
            return f(loc[0])[None]

        return jax.shard_map(body, mesh=ms.mesh, in_specs=P(AXIS),
                             out_specs=P(AXIS), check_vma=False)(x)

    ref = np.asarray(sharded(lambda v: jax.lax.pmean(v, AXIS)))
    got = np.asarray(sharded(
        lambda v: C.hierarchical_all_reduce(v, AXIS, h, codec="exact")))
    return {
        "qwz_trajectory_mismatches": int(qwz_mism),
        "qwz_compared_steps": qwz_steps,
        "exact_codec_elem_mismatches": int((ref != got).sum()),
        "exact_codec_compared_elems": int(ref.size),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--quick", action="store_true",
                    help="12-step smoke (the stamped parity window "
                         "then fails the >= 50-step gate by design)")
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--hidden", type=int, default=512,
                    help="MLP width; 512 -> 10756 params, 3 group-codec"
                         " buckets at bucket_mb=0.015625")
    ap.add_argument("--json-out",
                    default=os.path.join(REPO, "COMM_BENCH.json"))
    args = ap.parse_args()

    if args.cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from deepspeed_tpu.utils.evidence import atomic_write_json

    if len(jax.devices()) != WORLD:
        print(f"comm_bench: need {WORLD} devices, have "
              f"{len(jax.devices())}", file=sys.stderr)
        return 1

    steps = 12 if args.quick else args.steps
    comm_q = {"hierarchy_size": 1, "codec": "group",
              "bucket_mb": 0.015625}
    comm_h = dict(comm_q, hierarchy_size=2)
    arms = {
        "flat_f32": ({"stage": 2}, None),
        "flat_quant": ({"stage": 2, "zero_quantized_gradients": True},
                       comm_q),
        "hier_quant": ({"stage": 2, "zero_quantized_gradients": True},
                       comm_h),
    }

    rng = np.random.default_rng(1)
    import jax.numpy as jnp

    batch = {"x": jnp.asarray(rng.normal(size=(64, 16)), jnp.float32),
             "y": jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)}

    out_arms, trajs, wire = {}, {}, None
    for name, (zero, comm) in arms.items():
        eng = _build(zero, comm, args.hidden)
        row, losses = _run_arm(eng, batch, steps)
        info = eng.comm_info()
        if info is not None:
            row["comm_info"] = info
            if name == "hier_quant":
                wire = info["wire"]
        out_arms[name] = row
        trajs[name] = losses
        print(f"comm_bench: {name:10s} final_loss "
              f"{row['final_loss']:.6f}  step p50 "
              f"{row['step_ms_p50']:.1f} ms")

    f32 = trajs["flat_f32"]
    parity = {
        "steps": steps,
        "flat_quant_final_rel_gap": round(
            _rel_gap(trajs["flat_quant"][-1], f32[-1]), 6),
        "hier_quant_final_rel_gap": round(
            _rel_gap(trajs["hier_quant"][-1], f32[-1]), 6),
        "hier_vs_flat_quant_max_rel_gap": round(
            max(_rel_gap(a, b) for a, b in
                zip(trajs["hier_quant"], trajs["flat_quant"])), 6),
        "all_arms_learned": all(r["learned"] for r in out_arms.values()),
    }
    bit_exact = _bit_exact_checks(qwz_steps=4)

    doc = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "backend": jax.default_backend(),
        "world": WORLD,
        "hidden": args.hidden,
        "codec": comm_h["codec"],
        "bucket_mb": comm_h["bucket_mb"],
        "arms": out_arms,
        "wire": wire,
        "loss_parity": parity,
        "bit_exact": bit_exact,
    }
    atomic_write_json(doc, args.json_out)
    print(f"comm_bench: wire ratio_vs_f32 "
          f"{(wire or {}).get('ratio_vs_f32', 0.0):.3f}  "
          f"hier final rel gap {parity['hier_quant_final_rel_gap']}  "
          f"qwz mismatches {bit_exact['qwz_trajectory_mismatches']}  "
          f"exact-codec mismatches "
          f"{bit_exact['exact_codec_elem_mismatches']}")
    print("→", args.json_out)
    ok = ((wire or {}).get("ratio_vs_f32", 0.0) >= 3.5
          and bit_exact["qwz_trajectory_mismatches"] == 0
          and bit_exact["exact_codec_elem_mismatches"] == 0
          and parity["all_arms_learned"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
