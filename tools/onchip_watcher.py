#!/usr/bin/env python
"""Detached watcher: probe the axon tunnel periodically; on success run
the on-chip backlog in stages (fast evidence first) so a short tunnel
window still captures the headline numbers.

    nohup python tools/onchip_watcher.py > /tmp/onchip_watcher.log 2>&1 &

- Resume: a stage that completed leaves ONCHIP_STAGE_<name>.done and is
  skipped on rerun, so interrupted runs pick up at the first missing
  stage instead of burning the window on re-captures.
- The watcher owns probing (one probe recipe, imported from
  onchip_backlog.ITEMS): it probes before EVERY stage and stops when
  the tunnel drops — stages never run against a dead chip.
- Stage timeouts kill the whole process GROUP (start_new_session), so a
  wedged grandchild bench cannot survive to contend with the next stage.
- Status in ONCHIP_WATCHER_STATUS.json; per-stage item outcomes in
  ONCHIP_RUNLOG_<stage>.json (written incrementally by the backlog).
"""

import atexit
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
PY = sys.executable
STATUS = os.path.join(REPO, "ONCHIP_WATCHER_STATUS.json")
PIDFILE = "/tmp/dstpu_onchip_watcher.pid"

STAGES = [
    ("fast", ["bench", "kernels"], 4500),
    ("serving", ["serving"], 4000),
    # infinity + pstream answer NAMED verdict gaps (the 406 s/step
    # re-measure ask and row 8's "partial"); tuning is upside on a
    # headline that already beats the standing number — so they go first
    ("infinity", ["infinity"], 7500),
    ("pstream", ["pstream"], 7500),
    ("tuning", ["tuning", "autotune", "bench_tuned"], 6000),
    # last: a nice-to-have A/B, never ahead of the evidence the verdict
    # actually asked for
    ("kernels_v2", ["kernels_v2"], 2400),
]


def put_status(**kw):
    kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(STATUS, "w") as f:
        json.dump(kw, f, indent=1)


def probe() -> bool:
    """One probe recipe for watcher and backlog alike."""
    from onchip_backlog import ITEMS

    argv, deadline = ITEMS["probe"]
    try:
        p = subprocess.run(argv, timeout=deadline, capture_output=True,
                           text=True)
        # device repr varies by jax version/platform: TpuDevice(...) vs
        # "[TPU v5 lite0]" — match case-insensitively
        return p.returncode == 0 and "tpu" in p.stdout.lower()
    except subprocess.TimeoutExpired:
        return False


def run_stage(name, items, deadline) -> str:
    """Run one backlog stage in its own process group; returns outcome."""
    proc = subprocess.Popen(
        [PY, "tools/onchip_backlog.py", "--only", ",".join(items),
         "--log", f"ONCHIP_RUNLOG_{name}.json"],
        cwd=REPO, start_new_session=True)
    try:
        rc = proc.wait(timeout=deadline)
        return f"rc={rc}"
    except subprocess.TimeoutExpired:
        # kill the whole group: a wedged grandchild holding the chip
        # must not survive into the next stage
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return "timeout"


def pidfile_guard() -> bool:
    """True if another live watcher owns the pidfile."""
    if os.path.exists(PIDFILE):
        try:
            pid = int(open(PIDFILE).read())
            with open(f"/proc/{pid}/cmdline") as f:
                if "onchip_watcher" in f.read():
                    return True
        except (ValueError, FileNotFoundError, PermissionError):
            pass
    with open(PIDFILE, "w") as f:
        f.write(str(os.getpid()))
    atexit.register(lambda: os.path.exists(PIDFILE) and os.remove(PIDFILE))
    return False


MAX_STAGE_ATTEMPTS = 3

# Idle-time slow-lane coverage (round-5 verdict weak #6): while the
# chip is DOWN the watcher has nothing to do but sleep — spend that
# time running `pytest --runslow` (tools/run_slow_lane.sh) on a
# cadence so the ~67 slow-marked tests have a standing pass/fail stamp
# (SLOW_LANE.json).  0 disables.  The run is a DETACHED background
# process: the 3-minute probe cadence keeps ticking underneath it, and
# the moment the chip comes up the run is killed — slow tests must
# never eat a tunnel window or contend with an on-chip stage.
SLOW_LANE_CADENCE_S = float(
    os.environ.get("DSTPU_SLOW_LANE_CADENCE_S", str(6 * 3600)))
_slow_lane_proc = None


def maybe_run_slow_lane():
    global _slow_lane_proc
    if SLOW_LANE_CADENCE_S <= 0:
        return
    if _slow_lane_proc is not None and _slow_lane_proc.poll() is None:
        return                        # already running in the background
    deadline = float(os.environ.get("SLOW_LANE_DEADLINE_S", "2700"))
    if DEADLINE > 0:
        # same stand-down contract as the stages: the driver's
        # end-of-round bench must never contend with a CPU-saturating
        # pytest run — clamp to the remaining window, skip when tight
        remaining = DEADLINE - time.time()
        if remaining < 300:
            return
        deadline = min(deadline, remaining - 120)
    stamp = os.path.join(REPO, "SLOW_LANE.json")
    try:
        if time.time() - os.path.getmtime(stamp) < SLOW_LANE_CADENCE_S:
            return
    except OSError:
        pass   # no stamp yet — run
    print("chip down — starting the slow test lane (background)",
          flush=True)
    _slow_lane_proc = subprocess.Popen(
        ["bash", os.path.join("tools", "run_slow_lane.sh")],
        cwd=REPO, start_new_session=True,
        env={**os.environ, "SLOW_LANE_DEADLINE_S": str(int(deadline))})


def stop_slow_lane():
    """Chip is up (or stand-down): the idle work yields — no stamp is
    written for a killed run, so the cadence retries it on the next
    idle stretch.  TERM first with a grace period: a blind SIGKILL can
    land mid git-commit in run_slow_lane.sh and strand .git/index.lock,
    blocking every later evidence/snapshot commit in the repo."""
    global _slow_lane_proc
    p = _slow_lane_proc
    if p is not None and p.poll() is None:
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            p.wait()
        print("slow lane stopped — yielding the host", flush=True)
    _slow_lane_proc = None

# hard stand-down time (epoch secs, DSTPU_WATCHER_DEADLINE): the driver
# runs its own bench.py at round end — a watcher stage holding the chip
# at that moment would collide (double HBM allocation → the DRIVER's
# headline capture OOMs to CPU).  0 = no deadline.
DEADLINE = float(os.environ.get("DSTPU_WATCHER_DEADLINE", "0"))


def _past_deadline() -> bool:
    return DEADLINE > 0 and time.time() >= DEADLINE


def main():
    if pidfile_guard():
        print("watcher already running")
        return
    # a detached slow-lane child must not outlive the watcher (its own
    # internal `timeout` still bounds it if the watcher is SIGKILLed)
    atexit.register(stop_slow_lane)

    # outer loop: survive tunnel drops — go back to probing and resume
    # at the first missing stage instead of exiting (round-5: the
    # tunnel came up, wedged mid-bench, and an exit-on-drop watcher
    # would have slept through any later recovery)
    n = 0
    attempts = {name: 0 for name, _, _ in STAGES}
    while True:
        if _past_deadline():
            put_status(state="deadline_exit", stage_attempts=attempts)
            print("deadline reached — standing down for the driver's "
                  "end-of-round bench", flush=True)
            return
        up = probe()
        n += 1
        put_status(state="probing", attempt=n, chip_up=up,
                   stage_attempts=attempts)
        print(f"probe {n}: chip_up={up}", flush=True)
        if not up:
            # idle chip = free compute: keep the slow lane covered
            # (background — probes keep ticking at the 3-min cadence)
            maybe_run_slow_lane()
            # 3 min, not 10: the round-5 tunnel window lasted ~20 min
            # total — a 10-min probe cadence can eat half of one
            time.sleep(180)
            continue
        # the window is open: idle work yields the host NOW
        stop_slow_lane()

        done, dropped = [], False
        for name, items, deadline in STAGES:
            marker = os.path.join(REPO, f"ONCHIP_STAGE_{name}.done")
            if os.path.exists(marker):
                done.append({name: "already-done"})
                continue
            if attempts[name] >= MAX_STAGE_ATTEMPTS:
                # a stage that fails repeatedly on a healthy chip is a
                # broken workload, not a tunnel blip — don't burn the
                # window re-running it
                done.append({name: "attempts-exhausted"})
                continue
            if not probe():          # tunnel must be up RIGHT NOW
                put_status(state="tunnel_dropped", done=done,
                           next_stage=name, stage_attempts=attempts)
                print("tunnel dropped — back to probing", flush=True)
                dropped = True
                break
            if DEADLINE > 0:
                # never let a stage run past the stand-down time
                remaining = DEADLINE - time.time()
                if remaining < 120:
                    put_status(state="deadline_exit", done=done,
                               stage_attempts=attempts)
                    print("deadline imminent — standing down", flush=True)
                    return
                deadline = min(deadline, int(remaining))
            attempts[name] += 1
            put_status(state="running", stage=name, done=done,
                       stage_attempts=attempts)
            print(f"=== stage {name}: {items}", flush=True)
            outcome = run_stage(name, items, deadline)
            # the backlog exits 0 even when items inside failed: the
            # marker must key off the per-item outcomes, or a failed
            # capture gets permanently skipped as "done"
            ok = False
            try:
                with open(os.path.join(
                        REPO, f"ONCHIP_RUNLOG_{name}.json")) as f:
                    runlog = json.load(f)
                ok = (outcome == "rc=0" and runlog
                      and all(v.get("rc") == 0 for v in runlog.values()))
            except (FileNotFoundError, ValueError):
                pass
            done.append({name: outcome if not ok else "ok"})
            if ok:
                with open(marker, "w") as f:
                    f.write(time.strftime("%Y-%m-%dT%H:%M:%S"))
            elif not probe():
                # the tunnel died under the stage — that's a tunnel
                # failure, not a workload failure: refund the attempt
                # so 3 wedges can't permanently retire the stage
                attempts[name] -= 1
                put_status(state="tunnel_dropped", done=done,
                           stage=name, stage_attempts=attempts)
                print("tunnel dropped mid-stage — back to probing",
                      flush=True)
                dropped = True
                break
        if dropped:
            time.sleep(180)   # same cadence as the probe loop
            continue
        missing = [name for name, _, _ in STAGES
                   if not os.path.exists(
                       os.path.join(REPO, f"ONCHIP_STAGE_{name}.done"))]
        pending = [name for name in missing
                   if attempts[name] < MAX_STAGE_ATTEMPTS]
        if not missing:
            put_status(state="complete", done=done,
                       stage_attempts=attempts)
            print("backlog capture complete", flush=True)
            return
        if not pending:
            # every missing stage burned its attempts on a HEALTHY
            # tunnel — that's a broken workload, not a blip; say so
            # instead of claiming completion
            put_status(state="gave_up", missing=missing, done=done,
                       stage_attempts=attempts)
            print(f"gave up: stages {missing} exhausted their attempts",
                  flush=True)
            return
        print(f"stages pending retry: {pending}", flush=True)
        time.sleep(300)


if __name__ == "__main__":
    main()
