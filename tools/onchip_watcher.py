#!/usr/bin/env python
"""Detached watcher: probe the axon tunnel periodically; on the first
success, run the on-chip backlog in stages (fast evidence first) so a
short tunnel window still captures the headline numbers.

    nohup python tools/onchip_watcher.py > /tmp/onchip_watcher.log 2>&1 &

Stages run as separate onchip_backlog.py invocations so each stage's
evidence files are durably on disk before the next (longer) stage
starts.  Status in ONCHIP_WATCHER_STATUS.json; exits after one full
capture (or when the tunnel drops mid-run — rerun to resume remaining
stages).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
STATUS = os.path.join(REPO, "ONCHIP_WATCHER_STATUS.json")
PIDFILE = "/tmp/dstpu_onchip_watcher.pid"

STAGES = [
    ("fast", ["bench", "kernels"], 3600),
    ("serving", ["serving"], 4000),
    ("tuning", ["tuning", "autotune", "bench_tuned"], 6000),
    ("infinity", ["infinity"], 7500),
    ("pstream", ["pstream"], 7500),
]


def put_status(**kw):
    kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(STATUS, "w") as f:
        json.dump(kw, f, indent=1)


def probe() -> bool:
    try:
        p = subprocess.run(
            [PY, "-c", "import jax; print(jax.devices())"],
            timeout=120, capture_output=True, text=True)
        return p.returncode == 0 and "Tpu" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    if os.path.exists(PIDFILE):
        try:
            pid = int(open(PIDFILE).read())
            os.kill(pid, 0)
            print(f"watcher already running (pid {pid})")
            return
        except (ProcessLookupError, ValueError):
            pass
    with open(PIDFILE, "w") as f:
        f.write(str(os.getpid()))

    n = 0
    while True:
        n += 1
        up = probe()
        put_status(state="probing", attempt=n, chip_up=up)
        print(f"probe {n}: chip_up={up}", flush=True)
        if up:
            break
        time.sleep(600)

    done = []
    for name, items, deadline in STAGES:
        put_status(state="running", stage=name, done=done)
        print(f"=== stage {name}: {items}", flush=True)
        try:
            p = subprocess.run(
                [PY, "tools/onchip_backlog.py", "--only",
                 ",".join(["probe"] + items),
                 "--log", f"ONCHIP_RUNLOG_{name}.json"],
                cwd=REPO, timeout=deadline)
            done.append({name: p.returncode})
        except subprocess.TimeoutExpired:
            done.append({name: "timeout"})
        # tunnel may have dropped mid-capture: re-probe between stages
        if not probe():
            put_status(state="tunnel_dropped_midway", done=done)
            print("tunnel dropped — stopping; rerun to resume", flush=True)
            return
    put_status(state="complete", done=done)
    print("backlog capture complete", flush=True)


if __name__ == "__main__":
    main()
