#!/usr/bin/env python
"""Child process for the obs_wire truth gate: one tiny gpt2 serving
replica behind a REAL HTTP introspection server on an ephemeral port.

Spawned by ``tools/obswire_probe.py`` and the wire-plane tests — never
run it by hand unless debugging.  Protocol:

- builds the engine (telemetry ``http_port=0``, tracing at
  ``sample_rate=1``, SLO + history on), runs a small traced workload,
  then prints ONE JSON line ``{"port": N, "pid": P}`` to stdout and
  flushes — the parent's ready handshake and scrape address.
- keeps serving HTTP until killed.  SIGTERM exits cleanly (engine
  shutdown); the probe's staleness test uses SIGKILL on purpose, so
  cleanup must never be load-bearing.
- ``--skew-ns N`` shifts the monotonic timestamp this process stamps
  into every wire document, simulating a remote host whose monotonic
  clock origin differs — the known injected skew the parent's offset
  estimator must recover within its error bound.
"""

import argparse
import json
import os
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica", default="child0")
    ap.add_argument("--skew-ns", type=int, default=0)
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--new-tokens", type=int, default=8)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)

    if args.skew_ns:
        # simulate a foreign monotonic origin: every wire_stamp (and
        # therefore every /statusz//healthz//historyz//tracez doc this
        # process serves) reads skew_ns ahead of the true clock
        from deepspeed_tpu import obs_wire

        real_stamp = obs_wire.wire_stamp

        def skewed_stamp():
            d = real_stamp()
            d["t_mono_ns"] += args.skew_ns
            return d

        obs_wire.wire_stamp = skewed_stamp

    import numpy as np

    from deepspeed_tpu.inference.serving import serving_engine
    from deepspeed_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny(dim=32, n_layers=2, n_heads=2,
                               max_seq_len=64)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    eng = serving_engine(
        params, cfg, max_batch=2, page_size=8, num_pages=24,
        max_seq=32, prefill_bucket=8,
        telemetry={"http_port": 0},
        tracing={"sample_rate": 1.0},
        slo=True, history=True,
        replica_id=args.replica)

    rng = np.random.default_rng(0)
    for i in range(args.requests):
        eng.submit(i, rng.integers(1, cfg.vocab_size, 6).tolist(),
                   max_new_tokens=args.new_tokens)
    eng.run()

    def bye(signum, frame):
        eng.shutdown()
        sys.exit(0)

    signal.signal(signal.SIGTERM, bye)

    print(json.dumps({"port": eng._tel_exporter.port,
                      "pid": os.getpid(),
                      "replica": args.replica}), flush=True)
    while True:       # serve until killed
        time.sleep(0.2)


if __name__ == "__main__":
    sys.exit(main())
